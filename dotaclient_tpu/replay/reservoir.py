"""Bounded, staleness-aware, prioritized replay reservoir.

Single-writer contract: `offer`/`sample`/`expire` run on exactly one
thread (the staging consumer); only `stats()` is safe from any thread.
See the package docstring for where this sits in the data plane.

Entries are bucketed by behavior-policy version so expiry is a whole-
bucket drop, prioritized by the standard PER |TD-error| proxy for
|advantage| decayed by age, bounded by a byte budget with lowest-
priority-first eviction, and optionally spilled in place to
zlib-compressed storage once occupancy crosses a threshold.
"""

from __future__ import annotations

import threading
import zlib
from typing import Any, Callable, Dict, List, Optional, Tuple

import numpy as np

from dotaclient_tpu.config import ReplayConfig

# Upper edges of the replayed-frame age histogram (in learner versions);
# the last bucket is open-ended. Exported so metrics consumers and tests
# share one bucketing.
AGE_BUCKET_EDGES = (4, 8, 16, 32)


def td_error_priority(rewards, values, dones, gamma: float) -> float:
    """Mean |one-step TD residual| over the chunk — the standard PER
    proxy for |advantage|, computable host-side from the actor-stamped
    behavior values without a learner forward pass. The final step
    bootstraps from its own value (the true bootstrap value lives in the
    obs tail row and is not shipped as a scalar); the bias is uniform
    across candidates, which is all a *ranking* key needs."""
    r = np.asarray(rewards, np.float32)
    if r.size == 0:
        return 0.0
    v = np.asarray(values, np.float32)
    d = np.asarray(dones, np.float32)
    v_next = np.concatenate([v[1:], v[-1:]])
    delta = r + gamma * v_next * (1.0 - d) - v
    # A diverged actor (NaN/inf values or rewards) must yield a FINITE
    # key: a NaN priority would poison the sampling weights and starve
    # batch formation until the entry expired.
    return float(np.nan_to_num(np.mean(np.abs(delta)), nan=0.0, posinf=1e6, neginf=0.0))


class _Entry:
    __slots__ = (
        "eid", "payload", "version", "priority", "nbytes", "raw_nbytes",
        "uses", "compressed", "spill_exempt", "meta",
    )

    def __init__(
        self, eid: int, payload: Any, version: int, priority: float, nbytes: int,
        meta: Any = None,
    ):
        self.eid = eid
        self.payload = payload
        self.version = version
        self.priority = priority
        self.nbytes = nbytes  # current stored size (shrinks on spill)
        self.raw_nbytes = nbytes
        self.uses = 0
        self.compressed = False
        self.spill_exempt = False  # zlib couldn't shrink it; try only once
        # Opaque caller context carried alongside the payload (the obs
        # pipeline's TraceRef). Never encoded/spilled — it rides the
        # entry object, not the payload bytes.
        self.meta = meta


class ReplayReservoir:
    """Version-bucketed prioritized reservoir over opaque payloads.

    `encode(payload) -> bytes` / `decode(bytes) -> payload` adapt the
    two staging item types: the native packer path stores raw wire-frame
    bytes (encode/decode are identity) while the python path stores
    Rollout objects (encode=serialize_rollout, decode=deserialize_rollout).
    Spill compresses `encode(payload)`; sampling a spilled entry returns
    `decode(decompress(...))` without re-inflating the stored copy.
    """

    def __init__(
        self,
        cfg: ReplayConfig,
        encode: Optional[Callable[[Any], bytes]] = None,
        decode: Optional[Callable[[bytes], Any]] = None,
        seed: int = 0,
    ):
        if not 0.0 <= cfg.ratio < 1.0:
            raise ValueError(f"replay.ratio={cfg.ratio} must be in [0, 1)")
        if cfg.max_staleness < 1:
            raise ValueError(f"replay.max_staleness={cfg.max_staleness} must be >= 1")
        if cfg.byte_budget <= 0:
            raise ValueError(f"replay.byte_budget={cfg.byte_budget} must be positive")
        self.cfg = cfg
        self._encode = encode if encode is not None else (lambda p: p)
        self._decode = decode if decode is not None else (lambda b: b)
        self._rng = np.random.default_rng(seed)
        # version → {entry_id: _Entry}; consumer-thread-only. _count and
        # _bytes are plain ints maintained by the same single writer so
        # stats() can read them from any thread without iterating the
        # buckets mid-mutation.
        self._buckets: Dict[int, Dict[int, _Entry]] = {}
        self._bytes = 0
        self._count = 0
        self._next_id = 0
        self._stats_lock = threading.Lock()
        self._stats = {
            "admitted": 0,
            "rejected_stale": 0,
            "expired": 0,
            "evicted": 0,
            "retired": 0,
            "sampled": 0,
            "spilled_entries": 0,
            "bytes_spilled": 0,
        }
        self._age_hist = [0] * (len(AGE_BUCKET_EDGES) + 1)

    # ------------------------------------------------------------ queries

    @property
    def occupancy(self) -> int:
        return self._count

    @property
    def occupancy_bytes(self) -> int:
        return self._bytes

    # ---------------------------------------------------------- admission

    def offer(self, payload: Any, version: int, priority: float, nbytes: int,
              current_version: int, meta: Any = None) -> bool:
        """Admit one near-stale item. Returns False (rejected) when the
        item is already past the reservoir's own staleness window —
        the caller counts that as a plain stale drop. `meta` is opaque
        caller context (obs TraceRef) returned with the payload by
        sample()."""
        if current_version - version > self.cfg.max_staleness:
            with self._stats_lock:
                self._stats["rejected_stale"] += 1
            return False
        priority = float(priority)
        if not np.isfinite(priority):  # belt-and-braces vs a caller's own key
            priority = 0.0
        e = _Entry(
            self._next_id, payload, version, max(priority, 0.0), int(nbytes), meta=meta
        )
        self._next_id += 1
        self._buckets.setdefault(version, {})[e.eid] = e
        self._bytes += e.nbytes
        self._count += 1
        with self._stats_lock:
            self._stats["admitted"] += 1
        self._maybe_spill(current_version)
        self._evict_over_budget(current_version)
        return True

    def expire(self, current_version: int) -> int:
        """Drop whole buckets older than the staleness window."""
        cutoff = current_version - self.cfg.max_staleness
        dead = [v for v in self._buckets if v < cutoff]
        n = 0
        for v in dead:
            bucket = self._buckets.pop(v)
            n += len(bucket)
            self._bytes -= sum(e.nbytes for e in bucket.values())
            self._count -= len(bucket)
        if n:
            with self._stats_lock:
                self._stats["expired"] += n
        return n

    # ----------------------------------------------------------- sampling

    def _entries(self) -> List[_Entry]:
        return [e for b in self._buckets.values() for e in b.values()]

    def _effective_priorities(self, entries: List[_Entry], current_version: int) -> np.ndarray:
        """PER-style priority^alpha, exponentially decayed by age so an
        equally-surprising older chunk loses to a fresher one."""
        pri = np.asarray([e.priority for e in entries], np.float64) + 1e-6
        age = np.asarray(
            [max(current_version - e.version, 0) for e in entries], np.float64
        )
        w = pri ** self.cfg.alpha * np.exp2(-age / max(self.cfg.age_half_life, 1e-6))
        # Never hand non-finite weights to rng.choice: a single poisoned
        # entry must not make sample() raise forever (the staging consumer
        # would drain fresh frames on every failed attempt).
        return np.nan_to_num(w, nan=0.0, posinf=1e30, neginf=0.0)

    def sample(self, k: int, current_version: int) -> List[Tuple[Any, int, Any]]:
        """Draw up to k distinct entries, priority-weighted, and return
        [(payload, behavior_version, meta)] — `meta` is whatever the
        offer() caller attached (None by default). Entries stay resident
        (classic PER reuse) until they expire, are evicted, or hit the
        per-entry `max_replays` cap (then retired). Call `expire` first;
        this method assumes the window is already clean."""
        entries = self._entries()
        k = min(k, len(entries))
        if k <= 0:
            return []
        w = self._effective_priorities(entries, current_version)
        total = float(w.sum())
        # Uniform fallback whenever weighted choice can't draw k distinct
        # entries — including the age-decay-underflow case where fewer
        # than k entries carry nonzero weight (rng.choice would raise,
        # and sample() must never raise: the staging consumer has already
        # committed this batch's fresh rows).
        if total <= 0 or int(np.count_nonzero(w)) < k:
            idx = self._rng.choice(len(entries), size=k, replace=False)
        else:
            idx = self._rng.choice(len(entries), size=k, replace=False, p=w / total)
        out = []
        retired = 0
        for i in idx:
            e = entries[int(i)]
            if e.compressed:
                payload = self._decode(zlib.decompress(e.payload))
            else:
                payload = e.payload
            out.append((payload, e.version, e.meta))
            e.uses += 1
            age = max(current_version - e.version, 0)
            b = 0
            while b < len(AGE_BUCKET_EDGES) and age > AGE_BUCKET_EDGES[b]:
                b += 1
            with self._stats_lock:
                self._age_hist[b] += 1
            if self.cfg.max_replays > 0 and e.uses >= self.cfg.max_replays:
                self._remove(e)
                retired += 1
        with self._stats_lock:
            self._stats["sampled"] += len(out)
            self._stats["retired"] += retired
        return out

    # ----------------------------------------------------- budget / spill

    def _remove(self, e: _Entry) -> None:
        bucket = self._buckets.get(e.version)
        if bucket and bucket.pop(e.eid, None) is not None:
            self._bytes -= e.nbytes
            self._count -= 1
            if not bucket:
                del self._buckets[e.version]

    def _evict_over_budget(self, current_version: int) -> None:
        """Lowest-effective-priority-first eviction down to the budget.
        One priority pass + one argsort for the whole burst — not a full
        rescan per evicted entry (this runs on the staging consumer's
        critical path)."""
        if self._bytes <= self.cfg.byte_budget:
            return
        entries = self._entries()
        if not entries:
            return
        w = self._effective_priorities(entries, current_version)
        n_evicted = 0
        for i in np.argsort(w):  # coldest first
            if self._bytes <= self.cfg.byte_budget:
                break
            self._remove(entries[int(i)])
            n_evicted += 1
        if n_evicted:
            with self._stats_lock:
                self._stats["evicted"] += n_evicted

    def _maybe_spill(self, current_version: int) -> None:
        """Compress the coldest entries in place once occupancy crosses
        `spill_threshold` of the budget — buys headroom before eviction
        has to throw priorities away. Skips entries compression cannot
        shrink (already-dense wire bytes compress ~3-5x in practice)."""
        if not self.cfg.spill_compress:
            return
        threshold = self.cfg.spill_threshold * self.cfg.byte_budget
        if self._bytes <= threshold:
            return
        entries = [e for e in self._entries() if not e.compressed and not e.spill_exempt]
        if not entries:
            return
        w = self._effective_priorities(entries, current_version)
        spilled = bytes_spilled = 0
        for i in np.argsort(w):  # coldest first
            if self._bytes <= threshold:
                break
            e = entries[int(i)]
            packed = zlib.compress(self._encode(e.payload), level=1)
            if len(packed) >= e.nbytes:
                # incompressible: never pay this zlib pass for it again
                e.spill_exempt = True
                continue
            self._bytes -= e.nbytes - len(packed)
            bytes_spilled += e.raw_nbytes
            e.payload = packed
            e.nbytes = len(packed)
            e.compressed = True
            spilled += 1
        if spilled:
            with self._stats_lock:
                self._stats["spilled_entries"] += spilled
                self._stats["bytes_spilled"] += bytes_spilled

    # ------------------------------------------------- checkpoint support

    def snapshot(self) -> dict:
        """Consumer-thread-only (single-writer contract): a serializable
        image of the reservoir for the full-state checkpoint — encoded
        payload bytes with their compression state, ABSOLUTE behavior
        versions (so restored staleness stamps are exact, not re-aged),
        priorities and use counts, plus the sampling RNG's bit-generator
        state so the post-restore draw sequence continues the pre-kill
        stream bit-for-bit (the resume soak's bit-exactness depends on
        it). Entry `meta` (obs TraceRefs) is process-local and
        deliberately NOT captured — a restored entry re-enters the trace
        pipeline as untraced."""
        entries = []
        for bucket in self._buckets.values():
            for e in bucket.values():
                payload = e.payload if e.compressed else self._encode(e.payload)
                entries.append(
                    {
                        "payload": bytes(payload),
                        "compressed": e.compressed,
                        "version": int(e.version),
                        "priority": float(e.priority),
                        "uses": int(e.uses),
                        "raw_nbytes": int(e.raw_nbytes),
                        "spill_exempt": bool(e.spill_exempt),
                    }
                )
        return {"entries": entries, "rng_state": self._rng.bit_generator.state}

    def restore(self, snap: dict) -> int:
        """Rebuild entries + RNG stream from a snapshot(). PRE-START
        only: must run before the staging consumer thread exists (the
        learner restores in __init__), so there is no concurrent writer
        to race. Returns the number of entries restored."""
        n = 0
        for rec in snap.get("entries", []):
            if rec["compressed"]:
                payload, nbytes = rec["payload"], len(rec["payload"])
            else:
                payload, nbytes = self._decode(rec["payload"]), rec["raw_nbytes"]
            e = _Entry(
                self._next_id, payload, rec["version"], rec["priority"], nbytes, meta=None
            )
            e.uses = rec["uses"]
            e.compressed = rec["compressed"]
            e.raw_nbytes = rec["raw_nbytes"]
            e.spill_exempt = rec.get("spill_exempt", False)
            self._next_id += 1
            self._buckets.setdefault(e.version, {})[e.eid] = e
            self._bytes += e.nbytes
            self._count += 1
            n += 1
        rng_state = snap.get("rng_state")
        if rng_state is not None:
            self._rng.bit_generator.state = rng_state
        return n

    # ------------------------------------------------------------- stats

    def stats(self) -> Dict[str, float]:
        with self._stats_lock:
            out = dict(self._stats)
            hist = list(self._age_hist)
        out["occupancy"] = self.occupancy
        out["occupancy_bytes"] = self._bytes
        from dotaclient_tpu.runtime.metrics import histogram_scalars

        out.update(histogram_scalars("age", AGE_BUCKET_EDGES, hist))
        return out
