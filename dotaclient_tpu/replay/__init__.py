"""Host-side prioritized experience replay between staging and the learner.

Where this sits relative to the reference RMQ pipe
--------------------------------------------------

The reference dotaclient pipe (agent → RabbitMQ → optimizer) is strictly
on-policy: the optimizer consumes whatever the queue holds and drops
rollouts whose model version has aged past its staleness bound. This
repo's `runtime/staging.py` reproduces that policy on the host — frames
older than `ppo.max_staleness` learner versions are discarded in
`_ingest`, before they cost any device time. Every dropped frame is
wasted actor work, and on scarce TPU windows (TPU_PROBE_LOG.md) the
actor fleet and the learner are chronically mismatched: the learner's
version counter sprints ahead inside a window, mass-staling the frames
in flight.

This package converts that drop-on-stale policy into a tunable
freshness/efficiency tradeoff, following two pieces of related work:

- ACER (arxiv 1611.01224): off-policy reuse with *truncated importance
  weights* recovers the sample efficiency of replayed experience while
  bounding the variance of stale-ratio gradients. The loss-side half
  lives in `ops/ppo.py` — rows stamped with a positive behavior-policy
  staleness get their ratio truncated at `ppo.replay_rho_bar` before
  entering the clipped surrogate (exactly the plain PPO loss for
  fresh rows, so replay-off behavior is bit-identical).
- "Accelerating Distributed Deep RL by In-Network Experience Sampling"
  (arxiv 2110.13506): the sampling layer belongs in the *transport
  path*, not the learner. The reservoir therefore hangs off the
  broker-draining consumer thread in `runtime/staging.py` — the seam
  this repo already owns between the wire and the packed batch — not
  off the train loop.

Data plane (replay enabled):

    broker ─→ staging consumer thread
                ├─ fresh (within ppo.max_staleness) ──→ pending → packer
                ├─ near-stale (within replay.max_staleness)
                │        └──→ ReplayReservoir.offer  (would have been
                │             dropped_stale before)
                └─ too stale ──→ dropped_stale (as before)
    packer: each batch = (B - k) fresh + k = ratio·B reservoir samples,
            every row stamped with behavior-policy staleness
    learner: ships the batch as today; ops/ppo.py truncates the IS
            ratio on stale rows (ACER c̄ = ppo.replay_rho_bar)

The reservoir itself (`reservoir.py`) is single-writer by construction:
only the staging consumer thread calls `offer`/`sample`/`expire`, the
same discipline `tests/test_staging.py` asserts for the pending list;
`stats()` takes a lock and may be read from any thread. Entries are
version-bucketed so whole generations expire in O(1) bucket drops,
priorities follow the standard PER |TD-error| proxy for |advantage|
decayed by age, the total footprint is bounded by a byte budget with
lowest-priority-first eviction, and cold entries optionally spill to
zlib-compressed storage in place (still sampleable, ~3-5x smaller).

Default-off: with `LearnerConfig.replay.enabled=False` nothing here is
ever imported on the hot path and the staging/learner behavior — batch
contents, PPO loss, jit treedefs — is bit-identical to the pre-replay
code.
"""

from dotaclient_tpu.replay.reservoir import ReplayReservoir, td_error_priority

__all__ = ["ReplayReservoir", "td_error_priority"]
