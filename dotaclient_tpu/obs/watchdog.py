"""Acting watchdog: turns the learner's own telemetry into liveness.

The failure modes this catches all share one trait: the process stays
up, so nothing restarts it, and the cluster silently stops learning —
a stalled train loop (wedged device, deadlocked collective), input
starvation (actors dead, broker partitioned), a NaN'd loss (never
self-heals; every later step is wasted), and a quiet steps/s collapse.

The watchdog is a side thread reading MetricsLogger.latest() plus the
live version counter — data the learner already produces; it adds ZERO
work to the loop. On failure it escalates by consecutive strikes:

  strike 1                log a warning (grep-able, alert-able)
  strike cfg.dump_after   dump the flight recorder (evidence before the
                          pod dies — the dump is the artifact a human
                          reads after the restart)
  strike cfg.trip_after   trip: /healthz flips to 503, and the k8s
                          liveness probe restarts the pod

Strikes are counted at the cadence of the EVIDENCE, not the check:

- LIVE detectors (stall, NaN loss) read state that is current at every
  check, so each failing check is a strike.
- WINDOW detectors (starvation, steps/s regression) read
  MetricsLogger.latest(), which only refreshes once per metrics window
  (every metrics_every steps). Each window is judged exactly ONCE —
  a strike per consecutive FAILING WINDOW. Re-judging the same sample
  every interval_s would either restart a learner that already
  recovered mid-window (the stale >threshold value keeps failing until
  the next log) or, if stale samples were skipped instead, never
  accumulate the consecutive strikes sustained starvation deserves.

A fully healthy check clears the strikes AND the trip — if the
condition self-heals before the probe's failureThreshold, the pod
lives. All thresholds under --obs.watchdog.*, default off.

Testability: check() is a plain method driven by an injectable
monotonic clock; the background thread is just `while not
stop.wait(interval): check()`. Tests drive check() directly with a fake
clock — no sleeps in tier-1.
"""

from __future__ import annotations

import logging
import math
import statistics
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from dotaclient_tpu.config import WatchdogConfig

_log = logging.getLogger(__name__)


class Watchdog:
    def __init__(
        self,
        cfg: WatchdogConfig,
        latest_fn: Callable[[], Dict[str, float]],
        version_fn: Callable[[], int],
        recorder=None,
        time_fn: Callable[[], float] = time.monotonic,
        latest_seq_fn: Optional[Callable[[], int]] = None,
    ):
        self.cfg = cfg
        self._latest = latest_fn
        self._version = version_fn
        # Identity of the metrics window latest_fn reflects (the learner
        # passes MetricsLogger.latest_step). latest() only refreshes
        # every metrics_every steps, so per-check detectors must know
        # whether they are re-reading a window they already judged.
        self._latest_seq = latest_seq_fn
        self._recorder = recorder
        self._now = time_fn
        self._lock = threading.Lock()
        t = self._now()
        self._start_t = t
        # The version baseline is captured on the FIRST check(), not
        # here: any version write that lands before the watchdog's first
        # look (checkpoint restore at boot) must read as "where the
        # counter starts", never as a train-step heartbeat — a restore
        # counted as the first advance would end the boot grace before
        # the first step and crashloop a restored learner whose cold
        # start exceeds stall_s.
        self._last_version: Optional[int] = None
        self._last_advance_t = t
        self._booted = False  # flips on the first advance OBSERVED between checks
        # Rate samples for the regression baseline; appended once per
        # JUDGED metrics window so the per-check cadence never floods
        # the window with duplicates of the same logged sample.
        self._rates: deque = deque(maxlen=max(int(cfg.window), 1))
        # Window-detector state: which metrics window was last judged,
        # and per-detector consecutive-failing-WINDOW counts + the
        # verdict text that holds until the next window overrides it.
        self._judged_seq: Optional[int] = None
        self._win_counts: Dict[str, int] = {"starvation": 0, "regression": 0}
        self._win_reasons: Dict[str, str] = {}
        self._last_rate_version: Optional[int] = None  # legacy-path dedup
        self._live_strikes = 0  # consecutive failing checks (stall/NaN)
        self._dumped = False  # one flight-recorder dump per unhealthy episode
        self._warned_sig = None  # last (strikes, reasons) warned about
        self.strikes = 0  # reported: max(live strikes, window strikes)
        self.tripped = False
        self.trips_total = 0
        self.checks_done = 0
        self.reasons: List[str] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ checks

    def _live_failures(self, now: float, latest: Dict[str, float]) -> List[str]:
        """Detectors whose evidence is current at every check."""
        cfg = self.cfg
        fails: List[str] = []

        # STALL — the version counter is the loop's heartbeat. Before the
        # first advance the threshold is the (larger) boot grace: compile
        # + restore + first-batch wait must not read as a stall, or the
        # liveness restart replays the same slow boot forever.
        v = int(self._version())
        stall_s = cfg.stall_s if self._booted else max(cfg.stall_s, cfg.boot_grace_s)
        if self._last_version is None:
            # First look: baseline only. The grace clock keeps running
            # from construction; only an advance observed BETWEEN checks
            # (a real train step) ends boot.
            self._last_version = v
        elif v != self._last_version:
            self._last_version = v
            self._last_advance_t = now
            self._booted = True
        elif now - self._last_advance_t > stall_s:
            fails.append(
                f"stall: version {v} unchanged for "
                f"{now - self._last_advance_t:.0f}s (> {stall_s:.0f}s"
                f"{'' if self._booted else ', boot grace'})"
            )

        # NaN/inf loss — never self-heals; restart is the cure.
        if cfg.nan_check:
            loss = latest.get("loss")
            if loss is not None and not math.isfinite(float(loss)):
                fails.append(f"nan_loss: latest loss is {loss!r}")
        return fails

    def _judge_window(
        self, latest: Dict[str, float], seq: Optional[int], v: int
    ) -> None:
        """Window detectors: judge each metrics window exactly once.

        latest() refreshes every metrics_every steps while checks run
        every interval_s, so without the once-per-window gate a single
        sample would be re-judged dozens of times: a transient bad
        window keeps striking a learner that already recovered, and the
        regression baseline floods with duplicates of the newest sample.
        The verdict (and its consecutive-WINDOW strike count) holds
        until the next window overrides it. check() only calls this
        with an internally consistent (latest, seq) pair; seq None
        means no identity is wired (latest_seq_fn=None), which degrades
        to judging every check with the baseline deduped on version
        advance — the pre-identity behavior."""
        cfg = self.cfg
        if seq is not None:
            if seq == self._judged_seq:
                return  # same window: verdicts and counts hold
            self._judged_seq = seq
        # What one count unit is: a metrics window when the identity is
        # wired, a (possibly re-read) check otherwise.
        unit = "windows" if seq is not None else "checks"

        # STARVATION — fetch-phase fraction from the StepPhaseTimer
        # scalars (inert unless obs.step_phases produced them).
        if cfg.starvation_frac > 0:
            frac = latest.get("compute_phase_fetch_frac")
            if frac is not None and float(frac) > cfg.starvation_frac:
                n = self._win_counts["starvation"] + 1
                self._win_counts["starvation"] = n
                self._win_reasons["starvation"] = (
                    f"starvation: fetch phase {float(frac):.0%} of step wall "
                    f"(> {cfg.starvation_frac:.0%}; {n} consecutive {unit})"
                )
            else:
                self._win_counts["starvation"] = 0

        # REGRESSION — this window's steps/s vs the median of the
        # trailing windows (one baseline sample per window, appended
        # AFTER judging so a window is never compared to itself).
        if cfg.regression_frac > 0:
            rate = latest.get("env_steps_per_sec")
            if rate is None:
                self._win_counts["regression"] = 0
            else:
                rate = float(rate)
                failed = False
                if len(self._rates) == self._rates.maxlen:
                    baseline = statistics.median(self._rates)
                    if baseline > 0 and rate < cfg.regression_frac * baseline:
                        failed = True
                        n = self._win_counts["regression"] + 1
                        self._win_counts["regression"] = n
                        self._win_reasons["regression"] = (
                            f"regression: {rate:.1f} env-steps/s < "
                            f"{cfg.regression_frac:.2f} x trailing median "
                            f"{baseline:.1f} ({n} consecutive {unit})"
                        )
                if not failed:
                    self._win_counts["regression"] = 0
                # Baseline append: once per window when identity is
                # wired (we only reach here on a fresh seq); the legacy
                # path dedups on version advance so a re-served sample
                # still can't flood the median with duplicates.
                if seq is not None or v != self._last_rate_version:
                    self._rates.append(rate)
                    self._last_rate_version = v

    def check(self) -> Dict:
        """Run every detector once; escalate or clear. Returns verdict().
        Never raises — a watchdog that dies IS the failure mode it
        exists to catch, so detector errors log and count as healthy."""
        try:
            now = self._now()
            # Bracketed read: identity, sample, identity again. The
            # learner's log() can land between any two of these reads;
            # judging would then pair one window's step with another
            # window's scalars (mis-attributed verdict, and the real
            # window permanently skipped as already-judged). Steps are
            # monotonic, so an unchanged before/after identity proves
            # the middle latest() read came from that exact window —
            # anything else (mismatch, or a reader raising) leaves the
            # window UNJUDGED with its identity unconsumed, and the
            # next check 5s later judges it with stable data.
            pair_ok = True
            seq: Optional[int] = None
            if self._latest_seq is not None:
                try:
                    seq = int(self._latest_seq())
                except Exception:
                    pair_ok = False
            try:
                latest = self._latest()
            except Exception:
                latest = {}
                pair_ok = False
            if pair_ok and self._latest_seq is not None:
                try:
                    pair_ok = int(self._latest_seq()) == seq
                except Exception:
                    pair_ok = False
            live = self._live_failures(now, latest)
            if pair_ok:
                # _live_failures just synced _last_version to the
                # current version — the legacy append-dedup key.
                self._judge_window(latest, seq, int(self._last_version))
        except Exception:
            _log.exception("watchdog check failed; treating as healthy")
            live = []
        with self._lock:
            self.checks_done += 1
            win_reasons = [
                self._win_reasons[k] for k, c in self._win_counts.items() if c > 0
            ]
            if not live and not win_reasons:
                if self.tripped:
                    _log.warning("watchdog recovered; /healthz back to 200")
                self._live_strikes = 0
                self._dumped = False
                self._warned_sig = None
                self.strikes = 0
                self.reasons = []
                self.tripped = False
                return self._verdict_locked()
            self._live_strikes = self._live_strikes + 1 if live else 0
            # One ladder, two cadences: live detectors strike per failing
            # CHECK, window detectors per failing WINDOW.
            self.strikes = max(
                self._live_strikes, max(self._win_counts.values(), default=0)
            )
            self.reasons = live + win_reasons
            strikes = self.strikes
            fails = self.reasons
            dump_now = (
                strikes >= self.cfg.dump_after
                and not self._dumped
                and self._recorder is not None
            )
            if dump_now:
                self._dumped = True
            # Warn once per DISTINCT verdict, not per check: a held
            # window verdict would otherwise re-emit the identical
            # strike line every interval_s for the rest of the window —
            # dozens of alert firings for one already-judged sample.
            sig = (strikes, tuple(fails))
            warn_now = sig != self._warned_sig
            if warn_now:
                self._warned_sig = sig
        # Escalation I/O outside the lock: dump() can hit a slow disk and
        # verdict()/healthz readers must never block behind it.
        if warn_now:
            _log.warning("watchdog strike %d: %s", strikes, "; ".join(fails))
        if dump_now:
            self._recorder.record("watchdog", strikes=strikes, reasons=fails)
            self._recorder.dump("watchdog", once=False)
        if strikes >= self.cfg.trip_after:
            with self._lock:
                if not self.tripped:
                    self.tripped = True
                    self.trips_total += 1
                    _log.error(
                        "watchdog TRIPPED after %d strikes (%s); /healthz -> 503",
                        strikes,
                        "; ".join(fails),
                    )
        return self.verdict()

    # ----------------------------------------------------------- surface

    def _verdict_locked(self) -> Dict:
        return {
            "enabled": True,
            "ok": not self.tripped,
            "tripped": self.tripped,
            "strikes": self.strikes,
            "reasons": list(self.reasons),
            "trips_total": self.trips_total,
            "checks_done": self.checks_done,
            "uptime_s": round(self._now() - self._start_t, 1),
        }

    def verdict(self) -> Dict:
        with self._lock:
            return self._verdict_locked()

    def scalars(self) -> Dict[str, float]:
        """The watchdog_* gauge family for the scrape surface."""
        with self._lock:
            return {
                "watchdog_ok": 0.0 if self.tripped else 1.0,
                "watchdog_strikes": float(self.strikes),
                "watchdog_trips_total": float(self.trips_total),
                "watchdog_checks_total": float(self.checks_done),
            }

    # ------------------------------------------------------------ thread

    def start(self) -> "Watchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.cfg.interval_s):
                self.check()

        self._thread = threading.Thread(target=_run, daemon=True, name="obs-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
