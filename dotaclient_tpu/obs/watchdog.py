"""Acting watchdog: turns the learner's own telemetry into liveness.

The failure modes this catches all share one trait: the process stays
up, so nothing restarts it, and the cluster silently stops learning —
a stalled train loop (wedged device, deadlocked collective), input
starvation (actors dead, broker partitioned), a NaN'd loss (never
self-heals; every later step is wasted), and a quiet steps/s collapse.

The watchdog is a side thread reading MetricsLogger.latest() plus the
live version counter — data the learner already produces; it adds ZERO
work to the loop. On a failing check it escalates by consecutive
strikes:

  strike 1                log a warning (grep-able, alert-able)
  strike cfg.dump_after   dump the flight recorder (evidence before the
                          pod dies — the dump is the artifact a human
                          reads after the restart)
  strike cfg.trip_after   trip: /healthz flips to 503, and the k8s
                          liveness probe restarts the pod

A healthy check clears the strikes AND the trip — if the condition
self-heals before the probe's failureThreshold, the pod lives. All
thresholds under --obs.watchdog.*, default off.

Testability: check() is a plain method driven by an injectable
monotonic clock; the background thread is just `while not
stop.wait(interval): check()`. Tests drive check() directly with a fake
clock — no sleeps in tier-1.
"""

from __future__ import annotations

import logging
import math
import statistics
import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional

from dotaclient_tpu.config import WatchdogConfig

_log = logging.getLogger(__name__)


class Watchdog:
    def __init__(
        self,
        cfg: WatchdogConfig,
        latest_fn: Callable[[], Dict[str, float]],
        version_fn: Callable[[], int],
        recorder=None,
        time_fn: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg
        self._latest = latest_fn
        self._version = version_fn
        self._recorder = recorder
        self._now = time_fn
        self._lock = threading.Lock()
        t = self._now()
        self._start_t = t
        self._last_version = int(version_fn())
        self._last_advance_t = t
        self._booted = False  # flips on the first observed version advance
        # (version, rate) samples for the regression baseline; appended
        # only when the version advanced so one metrics window never
        # floods the window with duplicates.
        self._rates: deque = deque(maxlen=max(int(cfg.window), 1))
        self._last_rate_version = self._last_version
        self.strikes = 0
        self.tripped = False
        self.trips_total = 0
        self.checks_done = 0
        self.reasons: List[str] = []
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------ checks

    def _failures(self) -> List[str]:
        cfg = self.cfg
        now = self._now()
        fails: List[str] = []
        try:
            latest = self._latest()
        except Exception:
            latest = {}

        # STALL — the version counter is the loop's heartbeat. Before the
        # first advance the threshold is the (larger) boot grace: compile
        # + restore + first-batch wait must not read as a stall, or the
        # liveness restart replays the same slow boot forever.
        v = int(self._version())
        stall_s = cfg.stall_s if self._booted else max(cfg.stall_s, cfg.boot_grace_s)
        if v != self._last_version:
            self._last_version = v
            self._last_advance_t = now
            self._booted = True
        elif now - self._last_advance_t > stall_s:
            fails.append(
                f"stall: version {v} unchanged for "
                f"{now - self._last_advance_t:.0f}s (> {stall_s:.0f}s"
                f"{'' if self._booted else ', boot grace'})"
            )

        # NaN/inf loss — never self-heals; restart is the cure.
        if cfg.nan_check:
            loss = latest.get("loss")
            if loss is not None and not math.isfinite(float(loss)):
                fails.append(f"nan_loss: latest loss is {loss!r}")

        # STARVATION — fetch-phase fraction from the StepPhaseTimer
        # scalars (inert unless obs.step_phases produced them).
        if cfg.starvation_frac > 0:
            frac = latest.get("compute_phase_fetch_frac")
            if frac is not None and float(frac) > cfg.starvation_frac:
                fails.append(
                    f"starvation: fetch phase {float(frac):.0%} of step wall "
                    f"(> {cfg.starvation_frac:.0%})"
                )

        # REGRESSION — current steps/s vs the trailing-window median.
        if cfg.regression_frac > 0:
            rate = latest.get("env_steps_per_sec")
            if rate is not None:
                rate = float(rate)
                if len(self._rates) == self._rates.maxlen:
                    baseline = statistics.median(self._rates)
                    if baseline > 0 and rate < cfg.regression_frac * baseline:
                        fails.append(
                            f"regression: {rate:.1f} env-steps/s < "
                            f"{cfg.regression_frac:.2f} x trailing median {baseline:.1f}"
                        )
                if v != self._last_rate_version:
                    self._rates.append(rate)
                    self._last_rate_version = v
        return fails

    def check(self) -> Dict:
        """Run every detector once; escalate or clear. Returns verdict().
        Never raises — a watchdog that dies IS the failure mode it
        exists to catch, so detector errors log and count as healthy."""
        try:
            fails = self._failures()
        except Exception:
            _log.exception("watchdog check failed; treating as healthy")
            fails = []
        with self._lock:
            self.checks_done += 1
            if not fails:
                if self.tripped:
                    _log.warning("watchdog recovered; /healthz back to 200")
                self.strikes = 0
                self.reasons = []
                self.tripped = False
                return self._verdict_locked()
            self.strikes += 1
            self.reasons = fails
            strikes = self.strikes
        # Escalation I/O outside the lock: dump() can hit a slow disk and
        # verdict()/healthz readers must never block behind it.
        _log.warning("watchdog strike %d: %s", strikes, "; ".join(fails))
        if strikes == self.cfg.dump_after and self._recorder is not None:
            self._recorder.record("watchdog", strikes=strikes, reasons=fails)
            self._recorder.dump("watchdog", once=False)
        if strikes >= self.cfg.trip_after:
            with self._lock:
                if not self.tripped:
                    self.tripped = True
                    self.trips_total += 1
                    _log.error(
                        "watchdog TRIPPED after %d strikes (%s); /healthz -> 503",
                        strikes,
                        "; ".join(fails),
                    )
        return self.verdict()

    # ----------------------------------------------------------- surface

    def _verdict_locked(self) -> Dict:
        return {
            "enabled": True,
            "ok": not self.tripped,
            "tripped": self.tripped,
            "strikes": self.strikes,
            "reasons": list(self.reasons),
            "trips_total": self.trips_total,
            "checks_done": self.checks_done,
            "uptime_s": round(self._now() - self._start_t, 1),
        }

    def verdict(self) -> Dict:
        with self._lock:
            return self._verdict_locked()

    def scalars(self) -> Dict[str, float]:
        """The watchdog_* gauge family for the scrape surface."""
        with self._lock:
            return {
                "watchdog_ok": 0.0 if self.tripped else 1.0,
                "watchdog_strikes": float(self.strikes),
                "watchdog_trips_total": float(self.trips_total),
                "watchdog_checks_total": float(self.checks_done),
            }

    # ------------------------------------------------------------ thread

    def start(self) -> "Watchdog":
        if self._thread is not None and self._thread.is_alive():
            return self
        self._stop.clear()

        def _run():
            while not self._stop.wait(self.cfg.interval_s):
                self.check()

        self._thread = threading.Thread(target=_run, daemon=True, name="obs-watchdog")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
