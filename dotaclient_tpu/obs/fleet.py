"""Fleet telemetry plane: topology-driven aggregation, a continuous
conservation audit, and alert-triggered flight-recorder fan-in.

Every process already exports rich LOCAL telemetry (obs/http.py
/metrics, the PR-2 trace histograms, the flight recorder), and the
soak scripts already assert the frame-conservation ledgers — but only
POST-HOC, after a run ends. This module promotes those invariants to a
standing service: `FleetAggregator` scrapes every /metrics surface the
control plane knows about (GET /topology; literal comma-lists are the
rollback position), keeps bounded per-target rings, and derives three
layers each poll window:

1. **Conservation audit** (`ConservationAuditor`): the producer /
   broker-shard / delivery ledger identities evaluated on WINDOW DELTAS
   of the fleet's existing counter families, accumulated into a
   per-ledger `unaccounted` gauge. Counter resets and scrape outages
   are epoch-fenced: every obs/http.py surface exports
   `obs_boot_epoch_ms`, so a restarted shard re-anchors (its resident
   frames move to the `fenced` gauge — KNOWN restart loss) instead of
   reading as unaccounted loss, and a failed scrape FREEZES the ledger
   window (cumulative counters make the next successful delta span the
   gap, so nothing is missed — only reported late).

2. **SLO rollups**: e2e env-steps/s vs the device-only rate (the
   committed 40x host-wall gap as a first-class gauge), cross-fleet
   staleness and trace-stage means, pipeline_* device-idle, serve
   request rate and occupancy, league match volume.

3. **Alerts → incident fan-in** (`AlertEngine`): `meter,op,thr,for=W`
   clauses (the control-policy grammar discipline) evaluated against
   the fleet_* rollups; a rising firing edge snapshots every process's
   GET /debug/flight ring into ONE correlated incident bundle, indexed
   by trace_id where events carry one.

Deliberately stdlib-only (urllib via control/scrape.py): fleetd is a
standing pod in the controller's weight class and must never drag jax
or the wire stack in. All meter names it emits live under the
registry's `fleet_` family.

Threading: poll_once() runs on the fleetd loop thread; scalars() /
fleet() / debug snapshots are read by obs/http.py handler threads.
Every cross-thread read or write goes through self._lock (graftlint
THR001 discipline); poll_once computes into locals and publishes under
one short critical section.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
import urllib.request
from collections import deque
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Tuple

from dotaclient_tpu.control.scrape import scrape_endpoint

_log = logging.getLogger(__name__)

# Meter exported by every obs/http.py surface since the fleet plane
# landed: wall-clock ms the surface came up. A changed value is a
# process restart — the counter-reset fence.
BOOT_EPOCH_METER = "obs_boot_epoch_ms"


# --------------------------------------------------------------- ledgers


@dataclass(frozen=True)
class LedgerTerm:
    """One signed term of a conservation identity: the window delta of
    `meter`, summed over every target in `tier`, weighted by `sign`.
    kind="gauge" terms are level-valued (resident frames, queue depth) —
    their window DELTA enters the identity exactly like a counter's, but
    on a fence their last level is the restart's known loss.
    required=False terms contribute zero when the meter is absent
    (mode-dependent families like fanin_*)."""

    meter: str
    tier: str
    sign: float
    kind: str = "counter"  # "counter" | "gauge"
    required: bool = True


@dataclass(frozen=True)
class LedgerSpec:
    name: str
    doc: str
    terms: Tuple[LedgerTerm, ...]

    def tiers(self) -> Tuple[str, ...]:
        return tuple(sorted({t.tier for t in self.terms}))


# The three standing identities (units: wire frames — one serialized
# rollout chunk; the broker enqueues, pops, and the staging intake
# counts exactly these). Meter names are the fleet's EXISTING scrape
# scalars — the registry documents every one.
LEDGERS: Tuple[LedgerSpec, ...] = (
    LedgerSpec(
        name="producer",
        doc="actor publish path: attempted = published + shed + publish-failed",
        terms=(
            LedgerTerm("actor_publish_attempted_total", "actor", +1.0),
            LedgerTerm("actor_rollouts_published_total", "actor", -1.0),
            LedgerTerm("broker_shed_observed_total", "actor", -1.0, required=False),
            LedgerTerm(
                "broker_shed_publish_failed_total", "actor", -1.0, required=False
            ),
        ),
    ),
    LedgerSpec(
        name="shard",
        doc="broker shard: enqueued = popped + dropped + evicted_low + resident",
        terms=(
            LedgerTerm("broker_shard_enqueued_total", "broker", +1.0),
            LedgerTerm("broker_shard_popped_total", "broker", -1.0),
            LedgerTerm("broker_shard_dropped_total", "broker", -1.0, required=False),
            LedgerTerm(
                "broker_shard_evicted_low_total", "broker", -1.0, required=False
            ),
            LedgerTerm(
                "broker_shard_resident", "broker", -1.0, kind="gauge"
            ),
        ),
    ),
    LedgerSpec(
        name="delivery",
        doc=(
            "broker → learner: popped - reply_lost - fence/dup drops - "
            "fan-in queue level = consumed at the staging intake"
        ),
        terms=(
            LedgerTerm("broker_shard_popped_total", "broker", +1.0),
            LedgerTerm(
                "broker_shard_reply_lost_total", "broker", -1.0, required=False
            ),
            LedgerTerm("fanin_fence_dropped_total", "learner", -1.0, required=False),
            LedgerTerm("fanin_dup_dropped_total", "learner", -1.0, required=False),
            LedgerTerm(
                "fanin_queue_depth", "learner", -1.0, kind="gauge", required=False
            ),
            LedgerTerm("wire_frames_obs_bf16_total", "learner", -1.0),
            LedgerTerm(
                "wire_frames_obs_f32_total", "learner", -1.0, required=False
            ),
        ),
    ),
    LedgerSpec(
        name="assembled",
        doc=(
            "in-network batch assembly (--broker.assemble): admitted = "
            "packed + reject + bypassed + dropped + resident — every row "
            "a shard admitted while armed is packed into a block, "
            "dead-lettered (reject), popped wire-form by a classic "
            "consumer (bypassed), evicted, or still resident "
            "(assembled-but-unpopped)"
        ),
        terms=(
            LedgerTerm(
                "broker_assemble_rows_admitted_total", "broker", +1.0,
                required=False,
            ),
            LedgerTerm(
                "broker_assemble_rows_packed_total", "broker", -1.0,
                required=False,
            ),
            LedgerTerm(
                "broker_assemble_rows_reject_total", "broker", -1.0,
                required=False,
            ),
            LedgerTerm(
                "broker_assemble_rows_bypassed_total", "broker", -1.0,
                required=False,
            ),
            LedgerTerm(
                "broker_assemble_rows_dropped_total", "broker", -1.0,
                required=False,
            ),
            LedgerTerm(
                "broker_assemble_rows_resident", "broker", -1.0,
                kind="gauge", required=False,
            ),
        ),
    ),
)


@dataclass
class LedgerState:
    """Mutable per-ledger audit state. `anchors` maps (target_key,
    meter) -> the last CONSUMED value; deltas are computed against it
    and it only advances when a window actually accumulates, so frozen
    windows defer (never drop) counter activity."""

    status: str = "absent"  # ok | alarm | stale | fenced | absent
    unaccounted: float = 0.0
    fenced_frames: float = 0.0
    last_residual: float = 0.0
    windows_audited: int = 0
    windows_frozen: int = 0
    anchors: Dict[Tuple[str, str], float] = field(default_factory=dict)


class ConservationAuditor:
    """Evaluates every LedgerSpec each poll window. Pure state machine:
    the caller hands it the window's scrape outcome and it never does
    I/O, so tests drive it with injected counter sets."""

    def __init__(self, ledgers: Tuple[LedgerSpec, ...] = LEDGERS):
        self.ledgers = ledgers
        self.state: Dict[str, LedgerState] = {l.name: LedgerState() for l in ledgers}

    def observe(
        self,
        samples: Dict[str, Optional[Dict[str, float]]],
        tiers: Dict[str, str],
        fenced: set,
    ) -> None:
        """One poll window. `samples`: target_key -> scalar dict (None =
        scrape failed — the ledger window FREEZES: you cannot certify
        conservation you cannot observe, and cumulative counters make
        the next clean delta span the gap). `tiers`: target_key -> tier.
        `fenced`: target keys that restarted this window (boot-epoch
        change / counter regression) — their anchors re-baseline and
        their gauge levels move to fenced_frames. A target's FIRST
        successful scrape simply baselines (anchors default to current):
        audit-from-first-sight, no freeze."""
        for spec in self.ledgers:
            st = self.state[spec.name]
            involved = [k for k, t in tiers.items() if t in spec.tiers()]
            # -- fence accounting first: a fenced target's gauge level is
            # the restart's known loss, and its anchors re-baseline so a
            # reset counter never reads as negative delta.
            for key in involved:
                if key not in fenced:
                    continue
                cur = samples.get(key)
                for term in spec.terms:
                    if term.tier != tiers[key]:
                        continue
                    akey = (key, term.meter)
                    if term.kind == "gauge" and akey in st.anchors:
                        st.fenced_frames += abs(st.anchors[akey])
                    if cur is not None and term.meter in cur:
                        st.anchors[akey] = cur[term.meter]
                    else:
                        st.anchors.pop(akey, None)
            # -- absence: a required meter no involved target reports
            # (and none ever anchored) means this identity has nothing
            # to audit yet — e.g. a smoke fleet with no broker tier.
            def _meter_known(term: LedgerTerm) -> bool:
                for key in involved:
                    if tiers[key] != term.tier:
                        continue
                    cur = samples.get(key)
                    if cur is not None and term.meter in cur:
                        return True
                    if (key, term.meter) in st.anchors:
                        return True
                return False

            required = [t for t in spec.terms if t.required]
            if not involved or not all(_meter_known(t) for t in required):
                st.status = "absent"
                st.last_residual = 0.0
                continue
            # -- freeze: any involved target unobservable or fenced this
            # window → defer (anchors untouched; cumulative counters make
            # the next clean delta span the gap).
            down = [k for k in involved if samples.get(k) is None]
            if down or any(k in fenced for k in involved):
                st.status = "fenced" if any(k in fenced for k in involved) else "stale"
                st.last_residual = 0.0
                st.windows_frozen += 1
                continue
            # -- clean window: signed sum of per-target deltas. First
            # sight of a meter baselines it (anchor defaults to current →
            # delta 0): audit-from-first-sight, never retroactive.
            residual = 0.0
            consumed: Dict[Tuple[str, str], float] = {}
            for term in spec.terms:
                for key in involved:
                    if tiers[key] != term.tier:
                        continue
                    cur = samples[key]
                    if term.meter not in cur:
                        continue
                    akey = (key, term.meter)
                    value = cur[term.meter]
                    residual += term.sign * (value - st.anchors.get(akey, value))
                    consumed[akey] = value
            st.anchors.update(consumed)
            st.unaccounted += residual
            st.last_residual = residual
            st.windows_audited += 1
            st.status = "ok" if abs(st.unaccounted) < 0.5 else "alarm"

    def forget_target(self, key: str, tier: str) -> None:
        """A target left the topology: its gauge levels are known loss
        (like a fence) and its anchors go away."""
        for spec in self.ledgers:
            st = self.state[spec.name]
            for term in spec.terms:
                akey = (key, term.meter)
                if term.kind == "gauge" and akey in st.anchors:
                    st.fenced_frames += abs(st.anchors[akey])
                st.anchors.pop(akey, None)

    def scalars(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        unacc_pos = unacc_neg = fenced = 0.0
        for name, st in self.state.items():
            out[f"fleet_ledger_{name}_unaccounted"] = st.unaccounted
            out[f"fleet_ledger_{name}_fenced_frames"] = st.fenced_frames
            out[f"fleet_ledger_{name}_ok"] = float(st.status in ("ok", "absent"))
            out[f"fleet_ledger_{name}_windows_audited"] = float(st.windows_audited)
            out[f"fleet_ledger_{name}_windows_frozen"] = float(st.windows_frozen)
            unacc_pos += max(st.unaccounted, 0.0)
            unacc_neg += max(-st.unaccounted, 0.0)
            fenced += st.fenced_frames
        # The headline: frames the fleet cannot account for. Positive =
        # produced-but-vanished (loss); the negative side is its own
        # gauge (over-accounting: duplication or a broken term) so the
        # two failure modes never cancel each other silent.
        out["fleet_unaccounted_frames"] = unacc_pos
        out["fleet_overaccounted_frames"] = unacc_neg
        out["fleet_fenced_frames"] = fenced
        return out

    def report(self) -> Dict:
        return {
            spec.name: {
                "doc": spec.doc,
                "status": self.state[spec.name].status,
                "unaccounted": self.state[spec.name].unaccounted,
                "fenced_frames": self.state[spec.name].fenced_frames,
                "last_residual": self.state[spec.name].last_residual,
                "windows_audited": self.state[spec.name].windows_audited,
                "windows_frozen": self.state[spec.name].windows_frozen,
            }
            for spec in self.ledgers
        }


# ---------------------------------------------------------------- alerts

_OPS: Dict[str, Callable[[float, float], bool]] = {
    "gt": lambda a, b: a > b,
    "ge": lambda a, b: a >= b,
    "lt": lambda a, b: a < b,
    "le": lambda a, b: a <= b,
    "eq": lambda a, b: a == b,
    "ne": lambda a, b: a != b,
}


@dataclass
class AlertRule:
    meter: str
    op: str
    threshold: float
    for_windows: int
    raw: str

    def breached(self, value: float) -> bool:
        return _OPS[self.op](value, self.threshold)


def parse_alerts(spec: str) -> List[AlertRule]:
    """`meter,op,threshold,for=W` clauses, ';'-joined — the control
    policy's grammar discipline: fail LOUD at parse time, a silently
    dropped clause is an alert that never fires. op in gt|ge|lt|le|eq|ne;
    W >= 1 consecutive breached windows before firing."""
    rules: List[AlertRule] = []
    for raw in (c.strip() for c in spec.split(";")):
        if not raw:
            continue
        parts = [p.strip() for p in raw.split(",")]
        if len(parts) != 4:
            raise ValueError(
                f"alert clause {raw!r}: want meter,op,threshold,for=W "
                f"(got {len(parts)} fields)"
            )
        meter, op, threshold, for_part = parts
        if op not in _OPS:
            raise ValueError(f"alert clause {raw!r}: op {op!r} not in {sorted(_OPS)}")
        if not for_part.startswith("for="):
            raise ValueError(f"alert clause {raw!r}: fourth field must be for=W")
        thr = float(threshold)  # raises ValueError with the bad literal
        w = int(for_part[len("for="):])
        if w < 1:
            raise ValueError(f"alert clause {raw!r}: for=W must be >= 1")
        rules.append(AlertRule(meter, op, thr, w, raw))
    return rules


@dataclass
class _AlertState:
    streak: int = 0
    firing: bool = False
    fired_total: int = 0
    last_value: Optional[float] = None


class AlertEngine:
    """Consecutive-breach alert evaluation. A missing meter FREEZES the
    streak (no advance, no reset) — an aggregator that briefly loses a
    rollup input must neither page nor forgive. fire edges (not-firing →
    firing transitions) are what trigger incident fan-in."""

    def __init__(self, rules: List[AlertRule]):
        self.rules = rules
        self.state: List[_AlertState] = [_AlertState() for _ in rules]

    def evaluate(self, meters: Dict[str, float]) -> List[AlertRule]:
        edges: List[AlertRule] = []
        for rule, st in zip(self.rules, self.state):
            if rule.meter not in meters:
                continue  # freeze
            value = meters[rule.meter]
            st.last_value = value
            if rule.breached(value):
                st.streak += 1
                if st.streak >= rule.for_windows and not st.firing:
                    st.firing = True
                    st.fired_total += 1
                    edges.append(rule)
            else:
                st.streak = 0
                st.firing = False
        return edges

    def report(self) -> List[Dict]:
        return [
            {
                "clause": rule.raw,
                "streak": st.streak,
                "firing": st.firing,
                "fired_total": st.fired_total,
                "last_value": st.last_value,
            }
            for rule, st in zip(self.rules, self.state)
        ]


# --------------------------------------------------------------- targets


@dataclass
class TargetSeries:
    """Bounded per-target time-series ring + fence bookkeeping."""

    tier: str
    endpoint: str
    ring: deque = field(default_factory=lambda: deque(maxlen=64))
    boot_epoch: Optional[float] = None
    last: Optional[Dict[str, float]] = None
    last_ok_t: float = 0.0
    fences: int = 0
    ever_up: bool = False

    @property
    def key(self) -> str:
        return f"{self.tier}/{self.endpoint}"


def fetch_topology_targets(
    control: str, timeout_s: float = 2.0
) -> Optional[Dict[str, List[str]]]:
    """GET /topology on the control plane → {tier: [metrics endpoints]}.
    None on any failure — the caller keeps its current target set
    (discovery can only improve on the literal lists, the same rollback
    semantics serve/client.py uses)."""
    try:
        with urllib.request.urlopen(
            f"http://{control}/topology", timeout=timeout_s
        ) as resp:
            body = json.loads(resp.read().decode("utf-8", "replace"))
    except Exception as e:
        _log.debug("topology fetch from %s failed: %s", control, e)
        return None
    metrics = body.get("metrics")
    if not isinstance(metrics, dict):
        return None
    return {
        str(tier): [str(e) for e in eps]
        for tier, eps in metrics.items()
        if isinstance(eps, (list, tuple))
    }


def snapshot_flight(endpoint: str, timeout_s: float = 2.0) -> Optional[Dict]:
    """GET /debug/flight → the process's bounded crash-ring snapshot;
    None on any failure (a 404 surface simply has no recorder wired)."""
    try:
        with urllib.request.urlopen(
            f"http://{endpoint}/debug/flight", timeout=timeout_s
        ) as resp:
            return json.loads(resp.read().decode("utf-8", "replace"))
    except Exception as e:
        _log.debug("flight snapshot %s failed: %s", endpoint, e)
        return None


# ------------------------------------------------------------ aggregator


class FleetAggregator:
    """The standing aggregation engine behind `python -m
    dotaclient_tpu.obs.fleetd`. Construct with static targets and/or a
    control-plane address; call poll_once() on the loop cadence;
    scalars() is the /metrics source and fleet() the /fleet JSON body.

    I/O is injectable (scrape_fn / topology_fn / flight_fn) so tests
    drive whole chaos scenarios without sockets."""

    def __init__(
        self,
        targets: Optional[Dict[str, List[str]]] = None,
        control: str = "",
        poll_s: float = 2.0,
        window: int = 64,
        stale_s: float = 10.0,
        alerts: str = "",
        bundle_dir: str = "",
        ledgers: Tuple[LedgerSpec, ...] = LEDGERS,
        scrape_fn: Callable[[str], Optional[Dict[str, float]]] = scrape_endpoint,
        topology_fn: Callable[[str], Optional[Dict[str, List[str]]]] = (
            fetch_topology_targets
        ),
        flight_fn: Callable[[str], Optional[Dict]] = snapshot_flight,
        now_fn: Callable[[], float] = time.time,
        recorder=None,
    ):
        self.control = control
        self.poll_s = poll_s
        self.window = max(int(window), 2)
        self.stale_s = stale_s
        self.bundle_dir = bundle_dir
        self._static_targets = {t: list(e) for t, e in (targets or {}).items()}
        self._scrape = scrape_fn
        self._topology = topology_fn
        self._flight = flight_fn
        self._now = now_fn
        self.auditor = ConservationAuditor(ledgers)
        self.alert_engine = AlertEngine(parse_alerts(alerts))
        # fleetd's own FlightRecorder (optional): fences and alert fires
        # land in ITS ring too, so an incident bundle that includes
        # fleetd's own /debug/flight shows the aggregator's view.
        self.recorder = recorder
        self._lock = threading.Lock()
        self._series: Dict[str, TargetSeries] = {}
        self._rate_anchors: Dict[str, Tuple[float, float]] = {}
        self._scalars: Dict[str, float] = {}
        self._report: Dict = {"ok": True, "polls": 0}
        self._incident_paths: deque = deque(maxlen=32)
        self.polls_total = 0
        self.scrape_errors_total = 0
        self.fences_total = 0
        self.incidents_total = 0
        self.topology_refreshes_total = 0
        self.topology_errors_total = 0

    # -- discovery -------------------------------------------------------

    def _discover(self) -> Dict[str, List[str]]:
        desired = {t: list(e) for t, e in self._static_targets.items()}
        if self.control:
            topo = self._topology(self.control)
            if topo is None:
                self.topology_errors_total += 1
            else:
                self.topology_refreshes_total += 1
                for tier, eps in topo.items():
                    merged = desired.setdefault(tier, [])
                    for ep in eps:
                        if ep not in merged:
                            merged.append(ep)
        return desired

    # -- one poll window -------------------------------------------------

    def poll_once(self) -> Dict:
        """Scrape → fence-detect → audit → rollups → alerts → (maybe)
        incident fan-in. Returns the /fleet report it published."""
        now = self._now()
        desired = self._discover()
        desired_keys = {
            f"{tier}/{ep}" for tier, eps in desired.items() for ep in eps
        }
        # Prune targets that left the topology: their resident levels
        # are known (fenced) loss, not unaccounted loss.
        with self._lock:
            series = dict(self._series)
        for key in list(series):
            if key not in desired_keys:
                ts = series.pop(key)
                self.auditor.forget_target(key, ts.tier)
        for tier, eps in desired.items():
            for ep in eps:
                key = f"{tier}/{ep}"
                if key not in series:
                    ts = TargetSeries(tier=tier, endpoint=ep)
                    ts.ring = deque(maxlen=self.window)
                    series[key] = ts

        samples: Dict[str, Optional[Dict[str, float]]] = {}
        tiers: Dict[str, str] = {}
        fenced: set = set()
        for key, ts in series.items():
            tiers[key] = ts.tier
            sample = self._scrape(ts.endpoint)
            samples[key] = sample
            if sample is None:
                self.scrape_errors_total += 1
                ts.ring.append((now, None))
                continue
            # Fence detection: a new boot epoch, or any cumulative
            # counter running BACKWARD (a restart racing two polls so
            # fast both epochs were scraped from different incarnations
            # still trips the regression check).
            epoch = sample.get(BOOT_EPOCH_METER)
            regressed = ts.last is not None and any(
                name.endswith("_total")
                and name in ts.last
                and value < ts.last[name] - 1e-9
                for name, value in sample.items()
            )
            if ts.ever_up and (
                regressed
                or (
                    epoch is not None
                    and ts.boot_epoch is not None
                    and abs(epoch - ts.boot_epoch) > 0.5
                )
            ):
                fenced.add(key)
                ts.fences += 1
                self.fences_total += 1
                if self.recorder is not None:
                    self.recorder.record("fence", t=now, target=key)
            ts.boot_epoch = epoch if epoch is not None else ts.boot_epoch
            ts.last = sample
            ts.last_ok_t = now
            ts.ever_up = True
            ts.ring.append((now, sample))

        self.auditor.observe(samples, tiers, fenced)
        self.polls_total += 1
        scalars = self._rollups(now, series, samples)
        scalars.update(self.auditor.scalars())
        edges = self.alert_engine.evaluate(scalars)
        scalars["fleet_alerts_firing"] = float(
            sum(1 for st in self.alert_engine.state if st.firing)
        )
        scalars["fleet_alerts_fired_total"] = float(
            sum(st.fired_total for st in self.alert_engine.state)
        )
        for rule in edges:
            if self.recorder is not None:
                self.recorder.record(
                    "alert_fired",
                    t=now,
                    clause=rule.raw,
                    value=scalars.get(rule.meter),
                )
            self._fan_in_incident(rule, now, series, scalars)
        scalars["fleet_incidents_total"] = float(self.incidents_total)
        report = {
            "ok": all(
                st.status in ("ok", "absent") for st in self.auditor.state.values()
            ),
            "time": now,
            "polls": self.polls_total,
            "targets": {
                key: {
                    "tier": ts.tier,
                    "endpoint": ts.endpoint,
                    "up": samples.get(key) is not None,
                    "stale": ts.ever_up and (now - ts.last_ok_t) > self.stale_s,
                    "boot_epoch_ms": ts.boot_epoch,
                    "fences": ts.fences,
                }
                for key, ts in series.items()
            },
            "ledgers": self.auditor.report(),
            "alerts": self.alert_engine.report(),
            "slo": {
                k: v
                for k, v in scalars.items()
                if not k.startswith("fleet_ledger_")
            },
            "incidents": list(self._incident_paths),
        }
        with self._lock:
            self._series = series
            self._scalars = scalars
            self._report = report
        return report

    # -- derived layers --------------------------------------------------

    def _rollups(
        self,
        now: float,
        series: Dict[str, TargetSeries],
        samples: Dict[str, Optional[Dict[str, float]]],
    ) -> Dict[str, float]:
        out: Dict[str, float] = {
            "fleet_targets": float(len(series)),
            "fleet_targets_up": float(
                sum(1 for s in samples.values() if s is not None)
            ),
            "fleet_polls_total": float(self.polls_total),
            "fleet_scrape_errors_total": float(self.scrape_errors_total),
            "fleet_fences_total": float(self.fences_total),
            "fleet_topology_refreshes_total": float(self.topology_refreshes_total),
            "fleet_topology_errors_total": float(self.topology_errors_total),
        }
        by_tier: Dict[str, List[Dict[str, float]]] = {}
        for key, ts in series.items():
            out[f"fleet_tier_up_{ts.tier}"] = out.get(f"fleet_tier_up_{ts.tier}", 0.0)
            sample = samples.get(key)
            if sample is not None:
                out[f"fleet_tier_up_{ts.tier}"] += 1.0
                by_tier.setdefault(ts.tier, []).append(sample)

        def _vals(tier: str, meter: str) -> List[float]:
            return [s[meter] for s in by_tier.get(tier, []) if meter in s]

        # -- SLO layer 1: e2e vs device-only rate (the host-wall gap).
        e2e = sum(_vals("learner", "env_steps_per_sec"))
        out["fleet_e2e_env_steps_per_sec"] = e2e
        device_only = 0.0
        for s in by_tier.get("learner", []):
            rate = s.get("env_steps_per_sec", 0.0)
            wall = s.get("compute_phase_wall_s", 0.0)
            dev = s.get("compute_phase_device_step_s", 0.0)
            if rate > 0.0 and wall > 0.0 and dev > 0.0:
                device_only += rate * (wall / dev)
        if device_only > 0.0:
            out["fleet_device_only_env_steps_per_sec"] = device_only
            if e2e > 0.0:
                out["fleet_host_wall_gap"] = device_only / e2e
        # -- staleness + trace-stage + pipeline-idle distributions.
        for meter, tag in (
            ("trace_e2e_actor_apply_s", "fleet_staleness_e2e_s"),
            ("pipeline_device_idle_s", "fleet_pipeline_device_idle_s"),
            ("pipeline_overlap_ratio", "fleet_pipeline_overlap_ratio"),
        ):
            vals = [
                s[meter] for ss in by_tier.values() for s in ss if meter in s
            ]
            if vals:
                out[f"{tag}_mean"] = sum(vals) / len(vals)
                out[f"{tag}_max"] = max(vals)
        stage_means: Dict[str, List[float]] = {}
        for ss in by_tier.values():
            for s in ss:
                for name, v in s.items():
                    if name.startswith("trace_") and name.endswith("_mean_ms"):
                        stage_means.setdefault(name, []).append(v)
        for name, vals in stage_means.items():
            out[f"fleet_{name}"] = sum(vals) / len(vals)
        # -- serve / league health rollups.
        occ = _vals("serve", "serve_load_occupancy")
        if occ:
            out["fleet_serve_load_occupancy_mean"] = sum(occ) / len(occ)
        out["fleet_serve_carries_resident"] = sum(
            _vals("serve", "serve_carries_resident")
        )
        for tier, meter, tag in (
            ("serve", "serve_requests_total", "fleet_serve_requests_per_sec"),
            ("league", "league_matches_total", "fleet_league_matches_per_sec"),
        ):
            total = sum(_vals(tier, meter))
            if by_tier.get(tier):
                prev = self._rate_anchors.get(tag)
                self._rate_anchors[tag] = (now, total)
                if prev is not None and now > prev[0] and total >= prev[1]:
                    out[tag] = (total - prev[1]) / (now - prev[0])
        out["fleet_league_matches_total"] = sum(
            _vals("league", "league_matches_total")
        )
        return out

    # -- incident fan-in -------------------------------------------------

    def _fan_in_incident(
        self,
        rule: AlertRule,
        now: float,
        series: Dict[str, TargetSeries],
        scalars: Dict[str, float],
    ) -> Optional[str]:
        """A fired alert snapshots EVERY process's /debug/flight ring
        into one correlated bundle, keyed by trace_id where events carry
        one — the cross-process evidence assembled while it is still in
        memory, not after the processes died."""
        flights: Dict[str, Optional[Dict]] = {}
        trace_index: Dict[str, List[str]] = {}
        for key, ts in series.items():
            snap = self._flight(ts.endpoint)
            flights[key] = snap
            if not snap:
                continue
            for ev in snap.get("events", []) or []:
                tid = ev.get("trace")
                if tid is not None:
                    hit = trace_index.setdefault(str(tid), [])
                    if key not in hit:
                        hit.append(key)
        self.incidents_total += 1
        bundle = {
            "alert": rule.raw,
            "meter": rule.meter,
            "value": scalars.get(rule.meter),
            "fired_at": now,
            "fleet": {k: v for k, v in scalars.items()},
            "flights": flights,
            "trace_index": trace_index,
        }
        safe = "".join(
            c if c.isalnum() or c in "-_" else "_" for c in rule.meter
        )[:48]
        directory = self.bundle_dir or os.getcwd()
        stamp = time.strftime("%Y%m%dT%H%M%S", time.gmtime(now))
        path = os.path.join(
            directory, f"incident_{safe}_{stamp}_{self.incidents_total}.json"
        )
        try:
            os.makedirs(directory, exist_ok=True)
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(bundle, f, default=str)
            os.replace(tmp, path)  # never leave a half-written bundle
        except Exception:
            _log.exception("incident bundle write failed (%s)", rule.raw)
            return None
        self._incident_paths.append(path)
        _log.warning(
            "alert %s fired: incident bundle %s (%d flight snapshots)",
            rule.raw,
            path,
            sum(1 for v in flights.values() if v),
        )
        return path

    # -- serving surfaces (read by obs/http.py handler threads) ----------

    def scalars(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._scalars)

    def fleet(self) -> Dict:
        with self._lock:
            return dict(self._report)

    def health(self) -> Dict:
        with self._lock:
            report = self._report
        return {
            "ok": bool(report.get("ok", True)),
            "polls": report.get("polls", 0),
            "ledgers": {
                name: entry.get("status")
                for name, entry in (report.get("ledgers") or {}).items()
            },
        }
