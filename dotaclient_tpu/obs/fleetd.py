"""The fleet telemetry binary: scrape → audit → roll up → alert.

    python -m dotaclient_tpu.obs.fleetd \\
        --fleet.control control-plane:13400 \\
        --fleet.alerts "fleet_unaccounted_frames,gt,0,for=3" \\
        --fleet.port 13420

One standing process (k8s/fleetd.yaml): a poll loop discovers scrape
targets from the control plane's GET /topology "metrics" map (merged
with the literal --fleet.<tier> comma-lists — the rollback position),
scrapes every surface with control/scrape.py's Prometheus-text parser,
and each window runs the conservation audit, computes the fleet SLO
rollups, and evaluates the alert clauses (obs/fleet.py). Its own HTTP
surface serves:

- GET /fleet    — the full JSON rollup (targets, ledgers, alerts, SLO);
- GET /metrics  — the fleet_* registry family, so the CONTROL PLANE can
                  list fleetd as a scrape target and write policy
                  clauses against fleet meters (ROADMAP item 5's named
                  remaining scope: pipeline_* device-idle and audit
                  verdicts as policy inputs);
- GET /healthz  — 503 while any ledger is stale or alarming (the k8s
                  liveness contract: a fleet you cannot audit is a
                  fleet you cannot certify);
- GET /debug/flight — fleetd's own fence/alert event ring.

Deploy order (MIGRATION item 18): AGGREGATOR-LAST — every tier already
serves /metrics (required since the control plane landed), so fleetd
boots against a fully-scrapeable fleet and needs ZERO fleet-side flags.
Stdlib only: never imports jax, numpy, or the wire stack.
"""

from __future__ import annotations

import json
import logging
import threading
import time

from dotaclient_tpu.config import FleetConfig, parse_config
from dotaclient_tpu.obs.fleet import FleetAggregator
from dotaclient_tpu.obs.flight_recorder import FlightRecorder
from dotaclient_tpu.obs.http import MetricsHTTPServer

_log = logging.getLogger(__name__)


def _literal_targets(cfg) -> dict:
    """--fleet.<tier> comma-lists → {tier: [host:port, ...]}. Tier names
    match the control plane's topology vocabulary so merged discovery
    never double-counts a tier under two spellings."""
    out = {}
    for tier, spec in (
        ("broker", cfg.brokers),
        ("server", cfg.servers),
        ("actor", cfg.actors),
        ("store", cfg.stores),
        ("learner", cfg.learners),
        ("league", cfg.leagues),
    ):
        eps = [p.strip() for p in str(spec).split(",") if p.strip()]
        if eps:
            out[tier] = eps
    return out


class FleetDaemon:
    """Aggregator + poll thread + HTTP surface, owned together so tests
    and the soak construct the binary's exact shape in-process."""

    def __init__(self, cfg: FleetConfig):
        self.cfg = cfg.fleet
        self.recorder = FlightRecorder(
            "fleetd", ring_size=cfg.obs.ring_size, dump_dir=cfg.obs.dump_dir
        )
        self.agg = FleetAggregator(
            targets=_literal_targets(self.cfg),
            control=self.cfg.control,
            poll_s=float(self.cfg.poll_s),
            window=int(self.cfg.window),
            stale_s=float(self.cfg.stale_s),
            alerts=self.cfg.alerts,  # parse errors fail boot LOUDLY
            bundle_dir=self.cfg.bundle_dir,
            recorder=self.recorder,
        )
        self._http = None
        self._thread = None
        self._stop = threading.Event()

    def _run(self) -> None:
        while not self._stop.wait(float(self.cfg.poll_s)):
            try:
                self.agg.poll_once()
            except Exception:
                # a broken poll must not kill the standing loop — the
                # next round re-scrapes from scratch
                _log.exception("fleet poll failed")

    @property
    def port(self) -> int:
        return self._http.port if self._http is not None else int(self.cfg.port)

    def start(self) -> "FleetDaemon":
        self._http = MetricsHTTPServer(
            int(self.cfg.port),
            sources=[self.agg.scalars],
            health_provider=self.agg.health,
            json_routes={"/fleet": self.agg.fleet},
            flight_provider=self.recorder.snapshot,
        ).start()
        self._thread = threading.Thread(
            target=self._run, daemon=True, name="fleetd-loop"
        )
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._http is not None:
            self._http.stop()
            self._http = None


def main(argv=None):
    logging.basicConfig(level=logging.INFO)
    cfg = parse_config(FleetConfig(), argv)
    daemon = FleetDaemon(cfg)
    if cfg.obs.install_handlers:
        daemon.recorder.install_handlers()
    daemon.start()
    print(
        json.dumps(
            {
                "serving": True,
                "port": daemon.port,
                "control": cfg.fleet.control,
                "targets": sorted(
                    f"{t}/{e}"
                    for t, eps in _literal_targets(cfg.fleet).items()
                    for e in eps
                ),
                "alerts": len(daemon.agg.alert_engine.rules),
            }
        ),
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        daemon.stop()


if __name__ == "__main__":
    main()
