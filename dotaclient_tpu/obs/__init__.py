"""Pipeline observability: tracing, flight recorder, scrape surface.

The ROADMAP north star is a production-scale deployment, but the
actor → broker → staging → replay → learner pipe had no per-hop timing
and no scrape endpoint — you could see THAT throughput was low
(env_steps_per_sec), never WHERE a rollout spent its time. This package
is the measurement layer:

- obs/trace.py        per-stage latency histograms from trace-stamped
                      rollout chunks (DTR2 wire extension) + the e2e
                      actor→apply scalar that decomposes staleness;
- obs/flight_recorder bounded ring of recent pipeline events, dumped to
                      JSON on crash / BatchLayoutError / SIGTERM;
- obs/http            stdlib-only Prometheus-text /metrics endpoint,
                      structured /healthz (503 when the watchdog trips),
                      POST /profile on-demand trace capture;
- obs/compute         learner compute decomposition: step-phase timer,
                      recompile sentinel, MFU accounting, ProfileCapture;
- obs/watchdog        liveness thread (stall/starvation/NaN/regression →
                      log → dump → 503) behind --obs.watchdog.*;
- obs/registry        the documented scalar-name contract + drift guard.

Everything is opt-in via --obs.* and default-off with zero hot-path
overhead: no tracer/recorder objects exist, wire frames stay
byte-identical DTR1, staging/learner take their pre-obs paths
unchanged (asserted in tests/test_obs.py).

`ObsRuntime` is the per-process bundle the binaries construct:

    self.obs = ObsRuntime.create(cfg.obs, role="learner")  # or None

Actors use stamp() to trace outgoing chunks; the learner hands
`tracer`/`recorder` to its StagingBuffer and starts the scrape server
with live gauge sources.
"""

from __future__ import annotations

import time
from typing import Callable, Dict, List, Optional

from dotaclient_tpu.config import ObsConfig
from dotaclient_tpu.obs.compute import ComputeObserver, ProfileCapture
from dotaclient_tpu.obs.flight_recorder import FlightRecorder
from dotaclient_tpu.obs.http import MetricsHTTPServer
from dotaclient_tpu.obs.trace import LATENCY_EDGES_MS, STAGES, PipelineTracer, TraceRef
from dotaclient_tpu.obs.watchdog import Watchdog

__all__ = [
    "LATENCY_EDGES_MS",
    "STAGES",
    "ComputeObserver",
    "FlightRecorder",
    "MetricsHTTPServer",
    "ObsRuntime",
    "PipelineTracer",
    "ProfileCapture",
    "TraceRef",
    "Watchdog",
]


class ObsRuntime:
    """One process's observability bundle: recorder + tracer (+ scrape
    server for processes that call serve_metrics)."""

    def __init__(self, cfg: ObsConfig, role: str):
        self.cfg = cfg
        self.role = role
        self.recorder = FlightRecorder(
            role, ring_size=cfg.ring_size, dump_dir=cfg.dump_dir
        )
        self.tracer = PipelineTracer(recorder=self.recorder)
        self.server: Optional[MetricsHTTPServer] = None
        self.compute: Optional[ComputeObserver] = None
        self.watchdog: Optional[Watchdog] = None
        self.profiler: Optional[ProfileCapture] = None
        self._trace_seq = 0

    @classmethod
    def create(cls, cfg: ObsConfig, role: str) -> Optional["ObsRuntime"]:
        """None when obs is disabled — callers keep a single `if self.obs
        is None` guard and the disabled path constructs nothing."""
        if not cfg.enabled:
            return None
        rt = cls(cfg, role)
        if cfg.install_handlers:
            rt.recorder.install_handlers()
        return rt

    # ------------------------------------------------------------- actor

    def stamp(self, rollout, actor_id: int):
        """Trace-stamp an outgoing rollout chunk (actor publish path):
        allocates the trace id, stamps birth, records the publish event.
        Returns the stamped Rollout (serialize_rollout then emits DTR2)."""
        self._trace_seq += 1
        # High word = actor, low word = per-process sequence: ids stay
        # unique across the fleet without coordination, and a dump's
        # trace id alone names the publishing actor.
        trace_id = ((actor_id & 0xFFFFFFFF) << 32) | (self._trace_seq & 0xFFFFFFFF)
        birth = time.time()
        self.recorder.record("publish", t=birth, trace=trace_id, actor=actor_id)
        return rollout._replace(trace_id=trace_id, birth_time=birth)

    # ----------------------------------------------------------- compute

    def attach_compute(
        self, flops_per_step: float, peak_flops: Optional[float], overlap: bool = False
    ) -> ComputeObserver:
        """Build the learner's compute bundle (obs/compute.py): phase
        timer (when cfg.step_phases), recompile sentinel factory, MFU
        accounting — all sharing this runtime's flight recorder.
        `overlap` puts the phase timer in the pipelined loop's per-lane
        accounting mode (--learner.prefetch: no per-step fence, lane
        sums + pipeline_* scalars)."""
        self.compute = ComputeObserver(
            flops_per_step,
            peak_flops,
            recorder=self.recorder,
            step_phases=self.cfg.step_phases,
            overlap=overlap,
        )
        return self.compute

    def attach_watchdog(
        self, latest_fn, version_fn, latest_seq_fn=None
    ) -> Optional[Watchdog]:
        """Build + start the liveness watchdog when cfg.watchdog.enabled;
        its verdict feeds the /healthz provider and its scalars the
        scrape surface. Call AFTER checkpoint restore: the watchdog
        treats version advances as train-step heartbeats, and boot grace
        must outlive the restore's version write. latest_seq_fn
        (MetricsLogger.latest_step) identifies the metrics window behind
        latest_fn so per-check detectors can tell a fresh sample from a
        re-read of one already judged."""
        if not self.cfg.watchdog.enabled:
            return None
        self.watchdog = Watchdog(
            self.cfg.watchdog,
            latest_fn,
            version_fn,
            recorder=self.recorder,
            latest_seq_fn=latest_seq_fn,
        ).start()
        return self.watchdog

    # ------------------------------------------------------------ scrape

    def serve_metrics(
        self,
        sources: List[Callable[[], Dict[str, float]]],
        health_provider: Optional[Callable[[], Dict]] = None,
    ) -> Optional[MetricsHTTPServer]:
        """Start the /metrics endpoint when cfg.metrics_port is set (> 0).
        Adds the tracer's scalars as an implicit source, the watchdog's
        gauges when one is attached, and wires /healthz + POST /profile
        (a ProfileCapture is built lazily here — the capture dir falls
        back dump_dir → cwd)."""
        if self.cfg.metrics_port <= 0:
            return None
        sources = list(sources) + [self.tracer.scalars]

        # Late-bound: a watchdog attached AFTER the server starts (no
        # ordering contract on callers) still appears on the scrape. The
        # local rebind inside the closure makes the None-check and the
        # call one atomic observation — close() nulls self.watchdog from
        # another thread while scrape handlers run this.
        def _watchdog_scalars() -> Dict[str, float]:
            wd = self.watchdog
            return wd.scalars() if wd is not None else {}

        sources.append(_watchdog_scalars)
        if self.profiler is None:
            self.profiler = ProfileCapture(
                self.cfg.profile_dir or self.cfg.dump_dir,
                max_seconds=self.cfg.profile_max_seconds,
            )
        self.server = MetricsHTTPServer(
            self.cfg.metrics_port,
            sources,
            health_provider=health_provider,
            # capture() returns (path, clamped-window) atomically — the
            # obs/http.py handler echoes the window actually traced
            profile_handler=self.profiler.capture,
            # GET /debug/flight: every ObsRuntime-served binary exposes
            # its crash ring for fleetd's incident fan-in.
            flight_provider=self.recorder.snapshot,
        ).start()
        return self.server

    def close(self) -> None:
        if self.watchdog is not None:
            self.watchdog.stop()
            self.watchdog = None
        if self.server is not None:
            self.server.stop()
            self.server = None
