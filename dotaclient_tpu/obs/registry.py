"""Metric-name registry: the documented contract between emitters and
dashboards.

Dashboards and alerts select series BY NAME; a rename in learner.py (or
a new scalar nobody documents) silently drops/misses series with no
error anywhere. This registry is the single source of truth for every
scalar the learner/staging/replay/obs pipeline emits, and
tests/test_obs.py::test_emitted_scalars_are_registered drives a real
closed-loop learner and fails tier-1 if an emitted name isn't here —
so a rename must touch this file (and therefore the dashboards note in
README) to land.

Two name classes:
- SCALARS: exact, hand-documented names.
- PREFIXES: documented dynamic families whose tails are data-dependent
  (histogram bucket edges, replay reservoir stats, checkpoint-mirror
  stats, per-stage trace scalars). A family prefix documents the whole
  family; keep these FEW and specific — a catch-all prefix would defeat
  the drift guard.

This contract is enforced THREE ways, and every guard parses THIS file:
- runtime: the tier-1 drift guard above catches any name a real learner
  window emits that isn't registered;
- lint time: graftlint's OBS001 (dotaclient_tpu/analysis/obs_rules.py)
  AST-checks every STRING-LITERAL scalar name passed to
  MetricsLogger.log against SCALARS/PREFIXES before the code ever runs,
  and checks each f-string key by its constant head against the PREFIXES
  families (it reads the two dicts below by AST, never by import — keep
  them literal dicts of constant string keys). Fully-dynamic keys
  (loop-forwarded stats) are the runtime guard's half of the contract;
- fleet lint: graftproto (dotaclient_tpu/analysis/proto_rules.py)
  resolves every meter the SHIPPED k8s autoscaler/alert clauses name
  (SVC002) and every conservation-LEDGERS term (SVC004) against this
  registry AND against what the scraped tier's import closure actually
  emits — so a name here that no tier exports, or a clause naming an
  unregistered meter, fails lint before any pod boots.
"""

from __future__ import annotations

from typing import Dict

# Exact scalar names → one-line meaning. Grouped by emitter.
SCALARS: Dict[str, str] = {
    # --- compiled train step (parallel/train_step.py metric_keys) ------
    "loss": "total PPO objective",
    "policy_loss": "clipped-surrogate policy term",
    "value_loss": "clipped value regression term",
    "entropy": "mean policy entropy over real steps",
    "ratio_mean": "mean importance ratio",
    "ratio_clip_frac": "fraction of ratios clipped",
    "approx_kl": "approximate KL(new || behavior)",
    "advantage_mean": "mean GAE advantage (pre-normalization)",
    "return_mean": "mean bootstrapped return target",
    "value_mean": "mean predicted value",
    "replay_trunc_frac": "fraction of replayed rows with truncated IS ratio",
    "grad_norm": "global gradient norm before clipping",
    "aux_loss": "auxiliary value-head loss (aux_heads only)",
    "ppo_updates_done": "minibatch updates applied (KL early stop aware)",
    "ppo_kl_stopped": "1 if the KL early stop fired for this batch",
    # --- learner loop (runtime/learner.py) -----------------------------
    "env_steps_per_sec": "real (unmasked) env steps trained per second",
    "time_wait_batch_s": (
        "per-step host wait for a packed batch (pipelined loop: paid on "
        "the prefetch lane, hidden behind the device step)"
    ),
    "time_device_put_s": (
        "per-step host→device transfer time (pipelined loop: paid on "
        "the prefetch lane)"
    ),
    "time_step_s": (
        "per-step residual — device step + dispatch (pipelined loop: "
        "wall minus the exposed take-wait)"
    ),
    "active_actors": "actors heard from within the heartbeat window",
    "staleness_dropped": "rollouts dropped for version staleness (cumulative)",
    "staging_quarantined": (
        "frames filed in the staging dead-letter ring (parse/layout "
        "poison — evidence kept, dumped by the flight recorder)"
    ),
    "queue_ready": "packed batches waiting in the staging queue",
    "episodes": "episodes completed (cumulative, from done frames)",
    # --- experience wire (transport/serialize.py DTR3, staged by
    #     runtime/staging.py, emitted by the learner loop) --------------
    "wire_bytes_consumed_total": (
        "serialized experience bytes entering the staging intake "
        "(cumulative; the bf16 wire roughly halves the obs share)"
    ),
    "wire_frames_obs_bf16_total": (
        "frames whose float obs leaves traveled as bf16 (DTR3 quantized "
        "wire, --wire.obs_dtype bf16 producers)"
    ),
    "wire_frames_obs_f32_total": (
        "frames whose float obs leaves traveled as f32 (legacy DTR1/DTR2 "
        "producers) — nonzero during a rolling upgrade"
    ),
    "weights_published": "weight fanout frames actually sent",
    "weights_coalesced": "weight publishes superseded before sending",
    "mean_episode_return": "mean per-episode return over consumed frames",
    # --- evaluator (eval/evaluator.py) ---------------------------------
    "win_rate": "evaluation win rate vs the scripted yardstick",
    "mean_eval_return": "mean evaluation episode return",
    "trueskill_mu": "anchored TrueSkill mean",
    "trueskill_sigma": "anchored TrueSkill uncertainty",
    "skill": "conservative TrueSkill estimate (mu - 3 sigma)",
    # --- obs (dotaclient_tpu/obs/trace.py) -----------------------------
    "trace_e2e_actor_apply_s": "mean actor-publish → train-step-apply latency",
    # --- obs compute (dotaclient_tpu/obs/compute.py) -------------------
    "compute_phase_fetch_s": "mean per-step host wait for a packed batch",
    "compute_phase_pack_s": "mean per-step io.pack fallback time (≈0 on the fused path)",
    "compute_phase_h2d_s": "mean per-step fenced host→device transfer time",
    "compute_phase_device_step_s": "mean per-step fenced device train-step time",
    "compute_phase_host_s": "mean per-step publish/checkpoint/metrics host work",
    "compute_phase_wall_s": "mean loop-iteration wall time (phases sum to ≈ this)",
    "compute_phase_fetch_frac": "fetch share of step wall (watchdog starvation signal)",
    "compute_recompiles_total": "train-step signatures beyond the first (MUST stay 0 steady-state)",
    "compute_compiles_total": "train-step compiles including the first",
    "compute_compile_s": "cumulative train-step compile wall seconds",
    "compute_last_compile_s": "wall seconds of the most recent compile",
    "compute_flops_per_sec": "achieved model FLOP/s (ops/flops.py analytic count)",
    "compute_mfu": "cumulative model-FLOPs utilization vs platform peak (TPU only)",
    # --- vector actor fleet (runtime/actor.py InferenceBatcher) --------
    # Emitted by InferenceBatcher.stats() / VectorActor.stats():
    # bench_actors.py commits them into ACTOR_FLEET.json, and a
    # metrics-serving actor exports them as scrape gauges. The inference
    # service (dotaclient_tpu/serve/) runs the SAME batcher and exports
    # the same family on its own /metrics — deliberately shared names,
    # so fleet and serve dashboards read one distribution.
    "actor_offered_steps_per_sec": "real env steps offered by this process per second",
    "actor_batch_occupancy": "mean real-rows / capacity of the batched inference tick",
    "actor_gather_wait_s": "mean per-tick wait assembling the batch (bounded by --gather_window_s)",
    "actor_jit_step_s": "mean per-tick batched jit inference latency (incl. the one device_get)",
    # Producer conservation ledger (VectorActor.stats; obs/fleet.py
    # audits attempted = published + shed + failed live):
    "actor_publish_attempted_total": (
        "rollout chunks this process tried to publish (published + shed "
        "+ failed, derived from the same reads so the identity is exact)"
    ),
    "actor_rollouts_published_total": "rollout chunks acked by the broker (cumulative)",
    # --- inference service (dotaclient_tpu/serve/server.py) ------------
    "serve_requests_total": "policy-step requests handled (cumulative, all connections)",
    "serve_unknown_client_total": (
        "steps naming a client_key with no resident carry and no "
        "episode-start flag (server restarted/evicted; the client "
        "abandons the episode)"
    ),
    "serve_bad_requests_total": "malformed step requests refused",
    "serve_episode_resets_total": "carry resets on EPISODE_START flags (cumulative)",
    "serve_evictions_total": "carries evicted on client disconnect (cumulative)",
    "serve_weight_swaps_total": "param-tree hot-swaps applied between ticks (cumulative)",
    "serve_version": "model version of the currently-serving param tree",
    "serve_clients_connected": "live client connections",
    "serve_carries_resident": "LSTM carries held server-side across all connections",
    # --- serve placement load (serve/server.py load(), the S_INFO
    #     "load" dict as scrape gauges — what the control plane's
    #     policy loop and load-aware routing read) ----------------------
    "serve_load_clients": "live client connections (the S_INFO load report's clients field)",
    "serve_load_occupancy": "mean real-rows / capacity over the tick-occupancy histogram",
    "serve_load_pending": "step requests queued for the next inference tick",
    "serve_load_capacity": "batched-tick capacity (--serve.max_batch)",
    # --- session continuity, SERVER side (serve/server.py +
    #     serve/handoff.py; zero with --serve.handoff_endpoint unset) --
    "serve_handoff_store_writes_total": (
        "chunk-boundary carries write-ahead-streamed to the shared "
        "store BEFORE the chunk-fill reply (cumulative)"
    ),
    "serve_handoff_store_errors_total": (
        "carry-store RPCs that failed (write or failover read); the "
        "affected sessions degrade to PR-10 abandon-on-failover"
    ),
    "serve_handoff_resumes_total": (
        "sessions restored from the store on failover (S_RESUME "
        "answered OK; the client replays and the episode continues)"
    ),
    "serve_handoff_resume_misses_total": (
        "resume handshakes refused (no store, store miss, or no entry "
        "matching the client's boundary) — the client abandons"
    ),
    "serve_handoff_replayed_steps_total": (
        "FLAG_REPLAY steps served — buffered partial-chunk observations "
        "re-driven to rebuild a resumed session's mid-chunk carry"
    ),
    # --- serve-tier resilience, CLIENT side (serve/client.py
    #     RemoteFleet.stats; scrape-only like actor_*) ------------------
    "serve_failover_endpoints": "configured inference endpoints in the failover list",
    "serve_failover_endpoints_down": "endpoints currently sitting out a health cooldown",
    "serve_failover_total": "failovers to a different endpoint (cumulative)",
    "serve_failover_reconnects_total": "reconnect dials attempted (cumulative)",
    "serve_failover_episodes_abandoned_total": (
        "episodes abandoned on remote-inference failure — connection "
        "loss, reply deadline, UNKNOWN_CLIENT (the serve chaos soak's "
        "explicit abandon ledger)"
    ),
    # --- session continuity + routing tier, CLIENT side
    #     (serve/client.py RemoteFleet.stats; scrape-only) -------------
    "serve_handoff_client_resumes_total": (
        "episodes RESUMED after a remote-inference failure instead of "
        "abandoned (--serve.resume; the zero-abandon soak's ledger)"
    ),
    "serve_handoff_replay_steps_total": (
        "replay steps sent while rebuilding resumed sessions (at most "
        "one chunk per resume — the recompute bound)"
    ),
    "serve_route_load_mode": "1 when --serve.route load is active (0 = PR-10 list order)",
    "serve_route_probes_total": (
        "endpoint load probes issued at (re)connect time (S_INFO dials "
        "across the in-rotation candidates)"
    ),
    "serve_route_picks_total": "connects whose endpoint order came from a load probe pass",
    "serve_topology_refreshes_total": (
        "endpoint lists adopted from the control plane's GET /topology "
        "(--serve.endpoint control:<host:port>; 0 with literal lists)"
    ),
    "serve_topology_errors_total": (
        "failed /topology fetches — the client keeps its current list "
        "(rollback semantics: discovery can only improve on the static list)"
    ),
    "serve_fallback_engaged": "1 while the local-policy fallback is stepping episodes",
    "serve_fallback_engagements_total": (
        "distinct fallback engagements — counted per outage, not per "
        "return-to-remote probe cycle"
    ),
    "serve_fallback_steps_total": "policy steps served by the warm local tree (cumulative)",
    "serve_fallback_version": "model version of the broker-fanout-refreshed local tree",
    # --- multi-model serve tier (serve/server.py, --serve.models > 1) --
    "serve_models_resident": "param-tree slots resident on this server (--serve.models)",
    "serve_league_syncs_total": (
        "league-assignment slot installs applied by the sync loop "
        "(--serve.league_endpoint; cumulative)"
    ),
    "serve_league_sync_errors_total": (
        "failed league assignment/snapshot polls — current slots keep serving"
    ),
    # --- full-state checkpointing (runtime/checkpoint.py aux manifests,
    #     runtime/learner.py CheckpointWorker) — emitted only when
    #     --ckpt.full_state / --ckpt.async_save are on -----------------
    "ckpt_aux_written": "full-state aux manifests written (cumulative)",
    "ckpt_aux_superseded": "aux manifests coalesced away before writing (latest-wins)",
    "ckpt_aux_failures": "aux manifest writes that failed (prior step stays restorable)",
    "ckpt_last_aux_bytes": "size of the newest aux manifest (reservoir + pending + RNG)",
    "ckpt_last_aux_step": "step label of the newest durable aux manifest",
    "ckpt_async_saves_total": "checkpoints written by the off-critical-path saver",
    "ckpt_async_coalesced_total": "async checkpoints superseded before writing",
    # --- resume provenance (runtime/learner.py _restore_full_state):
    #     merged into the FIRST metrics window after a restore ----------
    "resume_restored_step": "checkpoint step label this boot restored (-1 = none)",
    "resume_version_hwm_bump": (
        "versions the counter jumped past the restored step to the "
        "published high-water mark (staleness stamps stay monotonic)"
    ),
    "resume_reservoir_entries": "replay-reservoir entries rehydrated from the aux manifest",
    "resume_pending_frames": "staged-but-untrained frames re-injected from the aux manifest",
    "resume_restore_wall_s": "wall seconds from restore start to full-state rehydration",
    # --- obs watchdog (dotaclient_tpu/obs/watchdog.py) -----------------
    "watchdog_ok": "1 while /healthz serves 200, 0 once tripped",
    "watchdog_strikes": (
        "escalation ladder position: max of consecutive failing checks "
        "(stall/NaN) and consecutive failing metrics windows "
        "(starvation/regression) — window strikes advance per logged "
        "window, not per check"
    ),
    "watchdog_trips_total": "times the watchdog flipped /healthz to 503",
    "watchdog_checks_total": "watchdog checks executed",
}

# Documented dynamic families (prefix → meaning of the family).
PREFIXES: Dict[str, str] = {
    # replay reservoir stats + age histogram, re-prefixed by staging:
    # replay_occupancy, replay_admitted, replay_age_le_<edge>, ...
    "replay_": "replay reservoir health (runtime/staging.py stats passthrough)",
    # checkpoint remote-mirror health: ckpt_mirror_lag_steps, ...
    "ckpt_mirror_": "checkpoint remote-mirror health (runtime/checkpoint.py)",
    # per-stage pipeline latency histograms + means:
    # trace_<stage>_ms_le_<edge>, trace_<stage>_ms_gt_<last>,
    # trace_<stage>_mean_ms (obs/trace.py STAGES)
    "trace_": "pipeline per-stage latency scalars (obs/trace.py)",
    # obs gauges exported only on the scrape surface (not JSONL):
    # obs_broker_experience_depth, obs_staging_*, ...
    "obs_": "live scrape-surface gauges (obs/__init__.py sources)",
    # rows-per-fired-tick occupancy histogram (InferenceBatcher):
    # actor_tick_rows_<k> = cumulative ticks whose batch carried exactly
    # k real rows, k in 1..capacity (k=0 cannot fire — a tick starts
    # from its first request). The capacity-dependent tail is why this
    # is a family, not exact names; the mean lives in
    # actor_batch_occupancy. Exported by vector actors AND the
    # inference service (same batcher, same distribution semantics).
    "actor_tick_rows_": "rows-per-fired-tick occupancy histogram (runtime/actor.py InferenceBatcher)",
    # overlapped learner pipeline (--learner.prefetch, runtime/learner.py
    # PrefetchLane + obs/compute.py StepPhaseTimer overlap mode):
    # pipeline_prefetch_s (prefetch-lane busy seconds per step:
    # fetch+pack+h2d, hidden behind the device step),
    # pipeline_prefetch_fetch_s / _pack_s / _h2d_s (the lane's own phase
    # split, fenced ON THE LANE so attribution costs no overlap),
    # pipeline_device_idle_s (the loop's exposed wait for a prefetched
    # batch — the device-idle-per-step upper bound),
    # pipeline_overlap_ratio (share of lane work hidden behind the
    # device step; 1.0 = the host fully disappeared). Emitted only in
    # pipelined mode — serial runs (--learner.prefetch false) emit
    # nothing new. A family: the lane split can grow phases.
    "pipeline_": "overlapped learner pipeline lane accounting (runtime/learner.py)",
    # parallel host feed scoreboard (runtime/staging.py _PackPool +
    # parallel/fused_io.py TransferRing, emitted by the learner loop
    # only when --staging.pack_workers > 1):
    # staging_pack_workers, staging_pack_worker_busy_s_<i>,
    # staging_pack_worker_stall_s_<i> (per-worker seconds executing /
    # idle — the worker-count sizing signal), staging_pack_ring_depth,
    # staging_pack_ring_occupancy (slots packing/ready/in-transfer),
    # staging_pack_ring_wait_s (assembler blocked on a free slot —
    # nonzero means H2D/device, not pack, is the longest stage),
    # staging_pack_wall_s, staging_pack_rows_per_s (packer-proper rate).
    # The per-worker tail is why this is a family, not exact names.
    "staging_pack_": "parallel host feed scoreboard (sharded pack pool + transfer ring)",
    # broker-fabric fan-in consumer (transport/fabric.py FabricBroker,
    # emitted by the learner loop only when --broker_url is a shard
    # list): fanin_queue_depth, fanin_delivered_total,
    # fanin_fence_dropped_total (epoch-stale deliveries dropped — the
    # stale-shard-resurrection proof counter), fanin_dup_dropped_total,
    # fanin_pop_threads, fanin_keys_tracked,
    # fanin_publish_failovers_total, fanin_publish_failed_total.
    "fanin_": "broker-fabric fan-in consumer ledgers (transport/fabric.py)",
    # per-shard fabric meters, TWO emitters: the learner-side fan-in
    # consumer exports broker_shard_<i>_popped_total,
    # broker_shard_<i>_starved_s (pop thread idle/backing off against
    # shard i — a starving shard index is the page), broker_shard_<i>_up
    # (tail = the consumer's shard-list index); the shard BINARY's own
    # --metrics_port surface exports the un-indexed ledger gauges
    # broker_shard_enqueued_total/_popped_total/_dropped_total/
    # _shed_total/_reply_lost_total/_evicted_low_total/_resident/_depth
    # (transport/fabric.py shard_metrics_source — the fleet auditor's
    # shard-ledger terms).
    "broker_shard_": "per-shard broker-fabric health (transport/fabric.py)",
    # broker admission control + actor publish degradation:
    # broker_shed_observed_total, broker_shed_publish_failed_total,
    # broker_shed_throttle_s (runtime/actor.py ShedThrottle /
    # VectorActor.stats; transport/tcp.py watermarks are the source)
    "broker_shed_": "broker load-shed observability (admission refusals + actor throttle)",
    # in-network batch assembly tier (--broker.assemble; transport/tcp.py
    # BrokerServer.assemble_ledger via transport/fabric.py
    # shard_metrics_source — the shard binary's --metrics_port surface):
    # broker_assemble_rows_admitted_total / _rows_packed_total /
    # _rows_reject_total (frames the classic ingest would also
    # dead-letter) / _rows_bypassed_total (classic CONSUME popped them
    # wire-form while armed) / _rows_dropped_total (drop-oldest +
    # priority eviction) / _rows_resident (assembled-but-unpopped rows,
    # the conservation gauge) / _blocks_built_total / _blocks_served_total
    # / _block_bytes_total / _cpu_s_total (shard-side pack seconds — the
    # CPU the learner host no longer spends). The assembled-rows
    # conservation identity over these terms is a fleet LEDGER
    # (obs/fleet.py) audited by graftproto SVC004 and fleetd.
    "broker_assemble_": "in-network batch assembly ledger (transport/tcp.py assemble tier)",
    # per-configured-endpoint health gauges (serve/client.py
    # RemoteFleet.stats): serve_endpoint_up_<i> (1 = in rotation, 0 =
    # sitting out a cooldown) and serve_endpoint_cooldown_s_<i>
    # (remaining cooldown seconds), i = index into --serve.endpoint.
    # PR 10 tracked health internally; these make WHICH replica a fleet
    # marked down operator-visible. A family because the tail is the
    # endpoint-list index.
    "serve_endpoint_": "per-endpoint client-side health gauges (serve/client.py)",
    # carry-store service gauges (serve/handoff.py CarryStoreServer
    # /metrics): serve_handoff_store_sessions, _puts_total, _gets_total,
    # _hits_total, _misses_total, _stale_total, _requests_total,
    # _bad_requests_total — the store binary's own scrape surface.
    "serve_handoff_store_": "carry-store service health (serve/handoff.py)",
    # seeded fault-injection meters (dotaclient_tpu/chaos/ ChaosBroker):
    # chaos_ops, chaos_corrupted, chaos_truncated, chaos_duplicated,
    # chaos_resets, chaos_sheds, chaos_stall_s, chaos_latency_s —
    # emitted only when --chaos.enabled (never in production)
    "chaos_": "fault-injection layer meters (dotaclient_tpu/chaos/)",
    # control-plane loop health (dotaclient_tpu/control/server.py
    # ControlPlane.stats, served on the controller's own surface):
    # control_polls_total, control_scrapes_total,
    # control_scrape_errors_total, control_scale_ups_total,
    # control_scale_downs_total, control_holds_total,
    # control_actuation_failures_total, control_topology_epoch,
    # control_managed_tiers, control_decisions_ledgered,
    # control_policy_clauses, control_replicas_<tier>. A family because
    # the per-tier tail is data-dependent (the managed-tier set).
    "control_": "control-plane autoscaler loop health (dotaclient_tpu/control/)",
    # per-model-slot serve ledgers (serve/server.py InferenceServer.stats,
    # emitted only at --serve.models > 1): serve_model_requests_total_<m>,
    # serve_model_swaps_total_<m>, serve_model_evictions_total_<m>,
    # serve_model_version_<m>, m = model slot index. A family because the
    # tail is the slot index.
    "serve_model_": "per-model-slot serve tier ledgers (serve/server.py)",
    # league population health (eval/league.py League.stats per-actor
    # pools AND dotaclient_tpu/league/ LeagueService.stats, the standing
    # service): league_pool_size, league_snapshots_total,
    # league_evictions_total, league_opponent_samples_total,
    # league_results_total, league_candidates, league_slots_assigned,
    # league_promotions_total, league_matches_total,
    # league_match_empty_total, league_bad_results_total,
    # league_fanout_snapshots_total, league_fanout_errors_total.
    "league_": "league population health (eval/league.py + dotaclient_tpu/league/)",
    # fleet telemetry plane (dotaclient_tpu/obs/fleet.py FleetAggregator,
    # served by obs/fleetd): fleet_targets(_up), fleet_polls_total,
    # fleet_scrape_errors_total, fleet_fences_total,
    # fleet_unaccounted_frames / fleet_overaccounted_frames /
    # fleet_fenced_frames (the conservation-audit headline),
    # fleet_ledger_<name>_* per ledger identity, fleet_tier_up_<tier>,
    # fleet_e2e_env_steps_per_sec vs fleet_device_only_env_steps_per_sec
    # and fleet_host_wall_gap (the committed 40x scoreboard, live),
    # fleet_staleness_e2e_s_*, fleet_trace_<stage>_mean_ms,
    # fleet_pipeline_*, fleet_serve_*, fleet_league_*, fleet_alerts_*,
    # fleet_incidents_total, fleet_topology_*. A family: ledger names,
    # tier names, and trace stages are data-dependent tails.
    "fleet_": "fleet telemetry rollups + conservation audit (dotaclient_tpu/obs/fleet.py)",
}


def is_registered(name: str) -> bool:
    return name in SCALARS or any(name.startswith(p) for p in PREFIXES)


def unregistered(names) -> list:
    """The subset of `names` no dashboard could know about — the drift
    guard's assertion payload. `step`/`time` are the JSONL record's own
    envelope fields, not scalars."""
    return sorted(n for n in names if n not in ("step", "time") and not is_registered(n))
