"""Rollout pipeline tracing: per-hop latency histograms + e2e decompose.

Each published chunk carries a trace id and a birth timestamp on the
wire (transport/serialize.py DTR2 extension); every pipeline stage that
touches it records a hop. The tracer turns hop deltas into per-stage
latency histograms (flattened to scalars via runtime.metrics
.histogram_scalars, so they ride the existing JSONL/TB/scrape stream)
and an end-to-end actor→apply latency that decomposes the coarse
staleness number the learner already logs.

Hop chain (the pipe's stations, SURVEY.md §1 L3 + the staging/learner
additions):

  publish       actor serializes + hands the chunk to the broker (birth)
  consume       staging consumer receives it off the broker
  staging_admit chunk passed validation/staleness and joined _pending
  replay_admit  would-be-stale chunk retained by the replay reservoir
  replay_reemit reservoir sample mixed the chunk back into a batch
  pack          chunk's batch left the packer
  h2d           learner dispatched the batch's host→device transfer
  apply         learner dispatched the train step consuming the batch

Each hop's histogram measures the delta from the PREVIOUS hop of the
same chunk; `consume` measures from birth, so it covers serialize +
broker queueing + the wire. `h2d` and `apply` are DISPATCH times (the
learner never syncs the device per step — metrics_every governs the
only routine sync), so the residual device time lives in the learner's
existing time_step_s, not here. e2e = apply_dispatch - birth.

Clocks: birth is the PUBLISHING process's time.time(); cross-host skew
therefore biases the `consume` bucket (and e2e) by the skew, exactly
like any wall-clock-stamped distributed trace. Same-host deploys and
the k8s NTP baseline keep this within single-digit ms — noted in the
README Observability section.

Thread model: hops arrive from the staging consumer thread AND the
learner loop thread; one lock guards the histogram state. Every call is
O(#edges) with no allocation beyond the event dict handed to the flight
recorder. The tracer exists only when --obs.enabled — the disabled path
never constructs one.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, List, Optional, Tuple

# Upper edges (milliseconds) of every per-stage latency histogram; the
# last bucket is open-ended. Log-spaced: the pipe's hops span ~0.1ms
# (admit) to multi-second (broker backlog under overload).
LATENCY_EDGES_MS = (1, 3, 10, 30, 100, 300, 1000, 3000, 10000)

STAGES = (
    "publish",
    "consume",
    "staging_admit",
    "replay_admit",
    "replay_reemit",
    "pack",
    "h2d",
    "apply",
)


class TraceRef:
    """One in-flight chunk's trace state as it moves through THIS
    process: identity + birth + the previous hop's timestamp (so each
    stage histograms its own delta, not the cumulative age)."""

    __slots__ = ("trace_id", "birth", "last_t")

    def __init__(self, trace_id: int, birth: float, last_t: Optional[float] = None):
        self.trace_id = trace_id
        self.birth = birth
        self.last_t = birth if last_t is None else last_t


class PipelineTracer:
    """Aggregates hop events into per-stage latency histograms and the
    e2e actor→apply scalar; optionally mirrors every hop into a
    FlightRecorder ring so crash dumps carry the recent trace tail."""

    def __init__(self, recorder=None, edges_ms: Tuple[int, ...] = LATENCY_EDGES_MS):
        self.recorder = recorder
        self.edges_ms = tuple(edges_ms)
        self._lock = threading.Lock()
        # stage -> (bucket counts [len(edges)+1], count, sum_ms)
        self._hist: Dict[str, List[int]] = {}
        self._n: Dict[str, int] = {}
        self._sum_ms: Dict[str, float] = {}
        self._e2e_n = 0
        self._e2e_sum_s = 0.0

    # ------------------------------------------------------------- hops

    def hop(self, stage: str, ref: TraceRef, now: Optional[float] = None) -> None:
        """Record one stage transition for one chunk; advances ref.last_t
        so the next hop measures its own delta."""
        t = time.time() if now is None else now
        delta_ms = max(t - ref.last_t, 0.0) * 1e3
        ref.last_t = t
        b = 0
        edges = self.edges_ms
        while b < len(edges) and delta_ms > edges[b]:
            b += 1
        with self._lock:
            hist = self._hist.get(stage)
            if hist is None:
                hist = self._hist[stage] = [0] * (len(edges) + 1)
                self._n[stage] = 0
                self._sum_ms[stage] = 0.0
            hist[b] += 1
            self._n[stage] += 1
            self._sum_ms[stage] += delta_ms
        if self.recorder is not None:
            self.recorder.record(
                stage, trace=ref.trace_id, ms=round(delta_ms, 3), t=t
            )

    def hop_batch(self, stage: str, refs, now: Optional[float] = None) -> None:
        """One stage transition for every traced chunk of a batch (pack /
        h2d / apply are batch-granular). `refs` may contain None slots
        (untraced rows of a mixed batch)."""
        if not refs:
            return
        t = time.time() if now is None else now
        for ref in refs:
            if ref is not None:
                self.hop(stage, ref, now=t)

    def e2e(self, refs, now: Optional[float] = None) -> None:
        """Close out traced chunks at apply dispatch: actor→apply wall
        seconds from the wire birth stamp."""
        if not refs:
            return
        t = time.time() if now is None else now
        with self._lock:
            for ref in refs:
                if ref is not None and ref.birth > 0:
                    self._e2e_n += 1
                    self._e2e_sum_s += max(t - ref.birth, 0.0)

    # ---------------------------------------------------------- scalars

    def scalars(self) -> Dict[str, float]:
        """Flatten state into MetricsLogger-style scalars. Histogram
        buckets are cumulative counters (Prometheus rate()-able); means
        are cumulative sums/counts. Names: trace_<stage>_ms_le_<edge>,
        trace_<stage>_ms_gt_<last>, trace_<stage>_mean_ms,
        trace_e2e_actor_apply_s."""
        from dotaclient_tpu.runtime.metrics import histogram_scalars

        out: Dict[str, float] = {}
        with self._lock:
            for stage, hist in self._hist.items():
                out.update(
                    histogram_scalars(f"trace_{stage}_ms", self.edges_ms, list(hist))
                )
                n = self._n[stage]
                out[f"trace_{stage}_mean_ms"] = self._sum_ms[stage] / max(n, 1)
            if self._e2e_n:
                out["trace_e2e_actor_apply_s"] = self._e2e_sum_s / self._e2e_n
        return out
