"""Bench/soak host preflight: stray-process detection + disclosure.

Motivation (BENCH host-variance lesson, r10): on the shared 2-core bench
host, an already-running serve server or tcp broker left over from an
earlier run silently eats the very cores the measured arms compute on —
verdicts swung run-to-run until the stray was found BY HAND. Every
bench/soak driver now calls `check()` before measuring: it scans for
listening TCP sockets owned by OTHER processes of this package (and any
explicitly named ports), FAILS LOUDLY with the pid + cmdline, and
returns a host-state disclosure dict the driver embeds in its artifact
verdict — the SERVE_BENCH in-artifact-disclosure pattern, made uniform.

Stdlib + /proc only (the drivers run on Linux CI/bench hosts; anywhere
/proc is missing the scan degrades to an empty disclosure, never a
crash — a preflight must not be able to kill the measurement it
protects). DOTACLIENT_TPU_ALLOW_STRAYS=1 downgrades the failure to a
disclosed warning for deliberately co-located runs.
"""

from __future__ import annotations

import os
import platform
import sys
import time
from typing import Dict, Iterable, List, Optional

# Processes whose cmdline contains any of these are "ours": a stray
# broker/serve/learner from an earlier run competes for the bench cores.
_REPO_MARKERS = ("dotaclient_tpu",)
_LISTEN_STATE = "0A"  # /proc/net/tcp st column, TCP_LISTEN


def _listening_inodes() -> Dict[str, int]:
    """socket-inode → local port for every LISTEN tcp/tcp6 socket."""
    out: Dict[str, int] = {}
    for path in ("/proc/net/tcp", "/proc/net/tcp6"):
        try:
            with open(path) as f:
                lines = f.readlines()[1:]
        except OSError:
            continue
        for line in lines:
            cols = line.split()
            if len(cols) < 10 or cols[3] != _LISTEN_STATE:
                continue
            try:
                port = int(cols[1].rsplit(":", 1)[1], 16)
            except (ValueError, IndexError):
                continue
            out[cols[9]] = port
    return out


def _pid_sockets(pid: str) -> List[str]:
    """Socket inodes held by `pid` (empty on permission/vanished)."""
    inodes = []
    try:
        for fd in os.listdir(f"/proc/{pid}/fd"):
            try:
                target = os.readlink(f"/proc/{pid}/fd/{fd}")
            except OSError:
                continue
            if target.startswith("socket:["):
                inodes.append(target[8:-1])
    except OSError:
        pass
    return inodes


def _cmdline(pid: str) -> str:
    try:
        with open(f"/proc/{pid}/cmdline", "rb") as f:
            return f.read().replace(b"\x00", b" ").decode(errors="replace").strip()
    except OSError:
        return ""


def _ancestors() -> set:
    """This process and its ancestry — a pytest/driver parent holding a
    metrics port must not read as a stray of its own child run."""
    pids = set()
    pid = os.getpid()
    for _ in range(32):  # bounded walk; /proc chains are short
        pids.add(pid)
        try:
            with open(f"/proc/{pid}/status") as f:
                ppid = next(
                    (int(l.split()[1]) for l in f if l.startswith("PPid:")), 0
                )
        except (OSError, ValueError):
            break
        if ppid <= 1:
            pids.add(ppid)
            break
        pid = ppid
    return pids


def scan_listeners(ports: Iterable[int] = ()) -> List[dict]:
    """Listening sockets that would contaminate a measurement: any
    OTHER process of this package holding a LISTEN socket, plus ANY
    process listening on an explicitly named port. Each entry carries
    the pid, port, and cmdline — the fail-loudly payload."""
    ports = set(int(p) for p in ports)
    inode_port = _listening_inodes()
    if not inode_port:
        return []
    own = _ancestors()
    strays = []
    for pid in os.listdir("/proc"):
        if not pid.isdigit() or int(pid) in own:
            continue
        held = [i for i in _pid_sockets(pid) if i in inode_port]
        if not held:
            continue
        cmd = _cmdline(pid)
        repo_proc = any(m in cmd for m in _REPO_MARKERS)
        for inode in held:
            port = inode_port[inode]
            if repo_proc or port in ports:
                strays.append({"pid": int(pid), "port": port, "cmdline": cmd[:200]})
    return sorted(strays, key=lambda s: (s["port"], s["pid"]))


def host_disclosure() -> dict:
    """The host-state block bench/soak artifacts embed next to their
    verdict (the SERVE_BENCH disclosure pattern): enough to judge
    whether a number came from a quiet host."""
    try:
        load1, load5, _ = os.getloadavg()
    except OSError:
        load1 = load5 = -1.0
    return {
        "cpus": os.cpu_count(),
        "loadavg_1m": round(load1, 2),
        "loadavg_5m": round(load5, 2),
        "platform": platform.platform(),
        "python": platform.python_version(),
        "checked_at": time.strftime("%Y-%m-%dT%H:%M:%SZ", time.gmtime()),
    }


def check(label: str, ports: Iterable[int] = ()) -> dict:
    """Driver preflight: scan for strays and FAIL LOUDLY (SystemExit
    naming every pid/port/cmdline) if any are found — a measurement on
    a contaminated host is worse than no measurement. Returns the
    disclosure dict (host state + the stray scan result) for the
    artifact verdict. DOTACLIENT_TPU_ALLOW_STRAYS=1 downgrades to a
    stderr warning with the strays still disclosed in the artifact."""
    strays = scan_listeners(ports)
    out = host_disclosure()
    out["preflight"] = {
        "label": label,
        "ports_checked": sorted(int(p) for p in ports),
        "strays": strays,
        "ok": not strays,
    }
    if strays:
        lines = "\n".join(
            f"  pid {s['pid']} listening on :{s['port']} — {s['cmdline']}"
            for s in strays
        )
        msg = (
            f"[{label}] preflight: {len(strays)} stray already-listening "
            f"process(es) would contaminate this measurement:\n{lines}\n"
            f"Kill them (or set DOTACLIENT_TPU_ALLOW_STRAYS=1 to proceed "
            f"with the contamination disclosed in the artifact)."
        )
        if os.environ.get("DOTACLIENT_TPU_ALLOW_STRAYS", "") not in ("", "0"):
            print(f"WARNING: {msg}", file=sys.stderr)
        else:
            raise SystemExit(msg)
    return out
