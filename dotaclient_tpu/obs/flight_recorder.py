"""Flight recorder: bounded ring of recent pipeline events per process,
dumped to a JSON artifact when something dies.

Soak/nightly failures were unreproducible because the evidence — which
chunks were in flight, what the staging consumer was doing, when the
last weight broadcast landed — evaporates with the process. The ring
keeps the last `ring_size` events in memory at O(1) cost per event and
writes them out on: a crash (sys.excepthook / threading.excepthook), a
BatchLayoutError (the staging consumer's fatal path calls dump before
dying), SIGTERM (the k8s eviction signal), or an explicit dump() call.

Dump artifacts are JSON: {reason, role, pid, time, events: [...]} at
`<dump_dir>/flight_<role>_<pid>_<reason>_<stamp>.json`. Events are
whatever record() was handed — pipeline trace hops (obs/trace.py
mirrors every hop here), staging admissions, weight swaps — each with a
wall-clock `t`.

Handler installation is opt-in (ObsConfig.install_handlers) and
chaining: the previous excepthook/signal handler still runs, so the
recorder never eats a crash or a termination another component owns.
"""

from __future__ import annotations

import json
import logging
import os
import signal
import sys
import threading
import time
from collections import deque
from typing import Optional

_log = logging.getLogger(__name__)


class FlightRecorder:
    def __init__(self, role: str, ring_size: int = 2048, dump_dir: str = ""):
        self.role = role
        self.dump_dir = dump_dir
        self._ring: deque = deque(maxlen=max(int(ring_size), 1))
        self._lock = threading.Lock()
        self._dumped_reasons = set()  # one artifact per distinct reason
        # name -> provider() of extra dump payload (e.g. the staging
        # quarantine ring): state that is too bulky to mirror into the
        # event ring per occurrence but essential in a post-mortem.
        self._sections: dict = {}
        self.events_recorded = 0
        self.last_dump_path: Optional[str] = None

    # ------------------------------------------------------------ record

    def record(self, event: str, t: Optional[float] = None, **fields) -> None:
        rec = {"t": time.time() if t is None else t, "ev": event}
        if fields:
            rec.update(fields)
        with self._lock:
            self._ring.append(rec)
            self.events_recorded += 1

    def add_section(self, name: str, provider) -> None:
        """Register a named dump section: `provider()` is called at dump
        time and its (JSON-serializable) return lands under
        payload["sections"][name]. Used by owners of bounded evidence
        rings — the staging quarantine — whose full contents belong in a
        post-mortem but not in the per-event ring."""
        with self._lock:
            self._sections[name] = provider

    # ---------------------------------------------------------- snapshot

    def snapshot(self, max_events: int = 256, max_bytes: int = 262144) -> dict:
        """Live, bounded view of the ring for the GET /debug/flight
        route: the newest `max_events` events plus the dump sections,
        trimmed (oldest-first) until the JSON encoding fits `max_bytes`.
        Unlike dump() this never touches disk and never marks a reason —
        fleetd's incident fan-in may hit every process in the fleet at
        once and the route must stay O(bounded) per request."""
        with self._lock:
            events = list(self._ring)[-max(int(max_events), 0):]
            recorded = self.events_recorded
            providers = list(self._sections.items())
        sections = {}
        for name, provider in providers:
            try:
                sections[name] = provider()
            except Exception:  # a recorder must never add a second failure
                sections[name] = "<section provider failed>"
        payload = {
            "role": self.role,
            "pid": os.getpid(),
            "time": time.time(),
            "events_recorded": recorded,
            "truncated": False,
            "events": events,
            "sections": sections,
        }
        # Enforce the byte cap on the encoded form: drop oldest events
        # first, then sections (events carry the incident timeline).
        while len(json.dumps(payload, default=str)) > max_bytes:
            if payload["events"]:
                half = len(payload["events"]) // 2
                payload["events"] = payload["events"][-half:] if half else []
                payload["truncated"] = True
            elif payload["sections"]:
                payload["sections"] = {}
                payload["truncated"] = True
            else:
                break
        return payload

    # -------------------------------------------------------------- dump

    def dump(self, reason: str, once: bool = True) -> Optional[str]:
        """Write the ring to a JSON artifact; returns its path (None when
        an identical-reason dump already happened and once=True, or the
        write failed — a recorder must never add a second failure)."""
        with self._lock:
            if once and reason in self._dumped_reasons:
                return None
            self._dumped_reasons.add(reason)
            events = list(self._ring)
            providers = list(self._sections.items())
        sections = {}
        for name, provider in providers:
            try:
                sections[name] = provider()
            except Exception:  # a recorder must never add a second failure
                sections[name] = "<section provider failed>"
        stamp = time.strftime("%Y%m%dT%H%M%S")
        safe_reason = "".join(c if c.isalnum() or c in "-_" else "_" for c in reason)[:64]
        directory = self.dump_dir or os.getcwd()
        path = os.path.join(
            directory, f"flight_{self.role}_{os.getpid()}_{safe_reason}_{stamp}.json"
        )
        try:
            os.makedirs(directory, exist_ok=True)
            payload = {
                "reason": reason,
                "role": self.role,
                "pid": os.getpid(),
                "time": time.time(),
                "events_recorded": self.events_recorded,
                "events": events,
            }
            if sections:
                payload["sections"] = sections
            tmp = f"{path}.tmp"
            with open(tmp, "w") as f:
                json.dump(payload, f)
            os.replace(tmp, path)  # never leave a half-written artifact
        except Exception:
            _log.exception("flight recorder dump failed (%s)", reason)
            return None
        self.last_dump_path = path
        _log.warning("flight recorder dumped %d events to %s", len(events), path)
        return path

    # ----------------------------------------------------- dump triggers

    def install_handlers(self) -> None:
        """Chain SIGTERM + excepthook + threading.excepthook dump
        triggers. SIGTERM only installs from the main thread (signal
        module restriction); the hooks install anywhere."""
        prev_excepthook = sys.excepthook

        def _excepthook(tp, val, tb):
            self.dump(f"crash_{tp.__name__}")
            prev_excepthook(tp, val, tb)

        sys.excepthook = _excepthook

        prev_thread_hook = threading.excepthook

        def _thread_hook(args):
            if args.exc_type is not SystemExit:
                self.dump(f"thread_crash_{args.exc_type.__name__}")
            prev_thread_hook(args)

        threading.excepthook = _thread_hook

        if threading.current_thread() is threading.main_thread():
            try:
                prev_term = signal.getsignal(signal.SIGTERM)

                def _on_term(signum, frame):
                    self.dump("sigterm")
                    if prev_term is signal.SIG_IGN:
                        return  # an explicitly IGNORED signal must stay ignored
                    if callable(prev_term):
                        prev_term(signum, frame)
                    else:  # default disposition: re-raise for termination
                        signal.signal(signal.SIGTERM, signal.SIG_DFL)
                        os.kill(os.getpid(), signal.SIGTERM)

                signal.signal(signal.SIGTERM, _on_term)
            except (ValueError, OSError):  # non-main thread race / exotic env
                pass
