"""Scrape surface: stdlib-only HTTP /metrics in Prometheus text format.

The k8s deploy had no way to scrape the learner — MetricsLogger writes
local JSONL/TB only. This serves the latest logged scalars plus live
gauges (broker queue depth, staging occupancy, replay reservoir stats)
over plain http.server: no prometheus_client dependency (the container
constraint), no new threadpools beyond one daemon serving thread.

Exposition rules (the subset of the Prometheus text format scrapers
need): one `# TYPE <name> gauge` line then `<name> <value>` per metric,
names sanitized to [a-zA-Z_:][a-zA-Z0-9_:]* and prefixed `dotaclient_`,
non-finite values skipped (Prometheus rejects NaN lines from some
ingest paths, and a NaN gauge carries no information anyway).

Sources are zero-arg callables returning {name: number}; each scrape
calls them fresh so gauges are live, and a source that throws is
skipped for that scrape (a broken stats provider must not take the
whole endpoint down with it).
"""

from __future__ import annotations

import logging
import math
import re
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional

_log = logging.getLogger(__name__)

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str, prefix: str = "dotaclient_") -> str:
    name = _NAME_OK.sub("_", name)
    if name and name[0].isdigit():
        name = f"_{name}"
    return f"{prefix}{name}"


def render_prometheus(scalars: Dict[str, float], prefix: str = "dotaclient_") -> str:
    lines: List[str] = []
    for name in sorted(scalars):
        try:
            v = float(scalars[name])
        except (TypeError, ValueError):
            continue
        if not math.isfinite(v):
            continue
        pname = sanitize_metric_name(name, prefix)
        lines.append(f"# TYPE {pname} gauge")
        # .10g, not %g: cumulative counters (consumed, bucket counts)
        # outgrow %g's 6 significant digits within hours and rate()
        # over a rounded counter produces flat-then-jump artifacts.
        lines.append(f"{pname} {v:.10g}")
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """One daemon thread serving GET /metrics (and /healthz) until
    stop(). Sources are sampled per scrape; port=0 binds an ephemeral
    port (tests), read back via `.port`."""

    def __init__(self, port: int, sources: Optional[List[Callable[[], Dict[str, float]]]] = None):
        self._sources: List[Callable[[], Dict[str, float]]] = list(sources or [])
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._requested_port = port

    def add_source(self, source: Callable[[], Dict[str, float]]) -> None:
        self._sources.append(source)

    def collect(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        for source in self._sources:
            try:
                out.update(source())
            except Exception:
                _log.exception("metrics source failed; skipping for this scrape")
        return out

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._requested_port

    def start(self) -> "MetricsHTTPServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):
                if self.path.split("?", 1)[0] not in ("/metrics", "/healthz"):
                    self.send_error(404)
                    return
                if self.path.startswith("/healthz"):
                    body = b"ok\n"
                else:
                    body = render_prometheus(server.collect()).encode()
                self.send_response(200)
                self.send_header(
                    "Content-Type", "text/plain; version=0.0.4; charset=utf-8"
                )
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):  # scrape spam stays out of stderr
                pass

        self._httpd = ThreadingHTTPServer(("", self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="obs-metrics-http"
        )
        self._thread.start()
        _log.info("obs /metrics serving on port %d", self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
