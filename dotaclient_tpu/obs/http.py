"""Scrape surface: stdlib-only HTTP /metrics in Prometheus text format,
a structured /healthz, and on-demand POST /profile capture.

The k8s deploy had no way to scrape the learner — MetricsLogger writes
local JSONL/TB only. This serves the latest logged scalars plus live
gauges (broker queue depth, staging occupancy, replay reservoir stats)
over plain http.server: no prometheus_client dependency (the container
constraint), no new threadpools beyond one daemon serving thread.

/healthz returns a JSON body from the optional `health_provider` —
{"ok": bool, ...} with HTTP 200 when ok and 503 when not (the k8s
liveness-probe contract: probes key on the status code, humans read the
body's watchdog verdict). With no provider it is a plain 200 {"ok":
true} — a serving process is the only health there is to report.

POST /profile?seconds=N runs the optional `profile_handler(seconds)`
(obs/compute.py ProfileCapture → jax.profiler.trace) and returns the
trace-dir path as JSON; 409 while a capture is in flight, 404 when no
handler is wired. The handler blocks ITS request thread for the window
(ThreadingHTTPServer: scrapes keep flowing meanwhile).

Exposition rules (the subset of the Prometheus text format scrapers
need): one `# TYPE <name> gauge` line then `<name> <value>` per metric,
names sanitized to [a-zA-Z_:][a-zA-Z0-9_:]* and prefixed `dotaclient_`,
non-finite values skipped (Prometheus rejects NaN lines from some
ingest paths, and a NaN gauge carries no information anyway).

Sources are zero-arg callables returning {name: number}; each scrape
calls them fresh so gauges are live, and a source that throws is
skipped for that scrape (a broken stats provider must not take the
whole endpoint down with it).
"""

from __future__ import annotations

import json
import logging
import math
import re
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Callable, Dict, List, Optional
from urllib.parse import parse_qs, urlparse

# compute.py is import-light at module level (jax only inside functions),
# so this does not drag an accelerator runtime into the HTTP module.
from dotaclient_tpu.obs.compute import CaptureBusyError

_log = logging.getLogger(__name__)

_NAME_OK = re.compile(r"[^a-zA-Z0-9_:]")


def sanitize_metric_name(name: str, prefix: str = "dotaclient_") -> str:
    name = _NAME_OK.sub("_", name)
    if name and name[0].isdigit():
        name = f"_{name}"
    return f"{prefix}{name}"


def render_prometheus(scalars: Dict[str, float], prefix: str = "dotaclient_") -> str:
    lines: List[str] = []
    for name in sorted(scalars):
        try:
            v = float(scalars[name])
        except (TypeError, ValueError):
            continue
        if not math.isfinite(v):
            continue
        pname = sanitize_metric_name(name, prefix)
        lines.append(f"# TYPE {pname} gauge")
        # .10g, not %g: cumulative counters (consumed, bucket counts)
        # outgrow %g's 6 significant digits within hours and rate()
        # over a rounded counter produces flat-then-jump artifacts.
        lines.append(f"{pname} {v:.10g}")
    return "\n".join(lines) + "\n"


class MetricsHTTPServer:
    """One daemon thread serving GET /metrics + GET /healthz (+ POST
    /profile when a handler is wired) until stop(). Sources are sampled
    per scrape; port=0 binds an ephemeral port (tests), read back via
    `.port`.

    `health_provider` is a zero-arg callable returning a JSON-able dict;
    its "ok" key (default True) selects 200 vs 503. `profile_handler`
    takes seconds and returns the capture path — or (path, seconds) to
    report the window it ACTUALLY traced after clamping; it may raise —
    the exception type name "CaptureBusyError" maps to 409, anything
    else to 500."""

    def __init__(
        self,
        port: int,
        sources: Optional[List[Callable[[], Dict[str, float]]]] = None,
        health_provider: Optional[Callable[[], Dict]] = None,
        profile_handler: Optional[Callable[[float], str]] = None,
        json_routes: Optional[Dict[str, Callable[[], Dict]]] = None,
        query_routes: Optional[Dict[str, Callable[[Dict], Dict]]] = None,
        post_routes: Optional[Dict[str, Callable[[bytes], Dict]]] = None,
        flight_provider: Optional[Callable[..., Dict]] = None,
    ):
        self._sources: List[Callable[[], Dict[str, float]]] = list(sources or [])
        self._httpd: Optional[ThreadingHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._requested_port = port
        self.health_provider = health_provider
        self.profile_handler = profile_handler
        # Extra GET routes ("/topology" on the control plane): path →
        # zero-arg callable returning a JSON-able dict, served 200; a
        # throwing provider is a 500, never a crashed serving thread.
        self.json_routes: Dict[str, Callable[[], Dict]] = dict(json_routes or {})
        # Parameterized GET routes ("/match", "/snapshot?name=x" on the
        # league service): path → callable({param: [values]}) → dict. A
        # provider raising KeyError/ValueError is the caller's fault →
        # 400; anything else is a 500.
        self.query_routes: Dict[str, Callable[[Dict], Dict]] = dict(query_routes or {})
        # POST routes ("/result" ingestion): path → callable(body bytes)
        # → dict, same 400/500 error split as query_routes.
        self.post_routes: Dict[str, Callable[[bytes], Dict]] = dict(post_routes or {})
        # GET /debug/flight: bounded JSON view of the process's
        # FlightRecorder ring (FlightRecorder.snapshot, or any callable
        # with the same (max_events=, max_bytes=) keywords). 404 when no
        # recorder is wired — same contract as POST /profile.
        self.flight_provider = flight_provider
        # Boot-epoch fence for aggregators: every surface exports the
        # wall-clock millisecond it came up, so a scraper can tell a
        # counter RESET (process restart → epoch changed) from counter
        # LOSS. Milliseconds because .10g rendering keeps them exact.
        self._boot_epoch_ms = float(int(time.time() * 1000.0))

    def add_source(self, source: Callable[[], Dict[str, float]]) -> None:
        self._sources.append(source)

    def health(self) -> Dict:
        """The /healthz body: provider's dict, or the serving-only
        default. A provider that throws reads as unhealthy — a broken
        health source must fail the probe, not mask it."""
        provider = self.health_provider  # one read: rebindable attribute
        if provider is None:
            return {"ok": True}
        try:
            body = dict(provider())
        except Exception as e:
            _log.exception("health provider failed")
            return {"ok": False, "error": f"health provider failed: {type(e).__name__}"}
        body.setdefault("ok", True)
        return body

    def collect(self) -> Dict[str, float]:
        out: Dict[str, float] = {}
        # Snapshot: handler threads iterate while the owner may still
        # add_source; tuple() is one GIL-atomic copy of the list.
        for source in tuple(self._sources):
            try:
                out.update(source())
            except Exception:
                _log.exception("metrics source failed; skipping for this scrape")
        out["obs_boot_epoch_ms"] = self._boot_epoch_ms
        return out

    @property
    def port(self) -> int:
        return self._httpd.server_address[1] if self._httpd else self._requested_port

    def start(self) -> "MetricsHTTPServer":
        server = self

        class Handler(BaseHTTPRequestHandler):
            def _reply(self, code: int, body: bytes, ctype: str) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, code: int, payload: Dict) -> None:
                self._reply(
                    code, (json.dumps(payload) + "\n").encode(), "application/json"
                )

            def do_GET(self):
                route = self.path.split("?", 1)[0]
                if route == "/metrics":
                    self._reply(
                        200,
                        render_prometheus(server.collect()).encode(),
                        "text/plain; version=0.0.4; charset=utf-8",
                    )
                elif route == "/healthz":
                    body = server.health()
                    self._reply_json(200 if body.get("ok", True) else 503, body)
                elif route == "/debug/flight":
                    provider = server.flight_provider  # one atomic read
                    if provider is None:
                        self._reply_json(
                            404, {"error": "no flight recorder wired on this surface"}
                        )
                        return
                    params = parse_qs(urlparse(self.path).query)
                    try:
                        max_events = int(params.get("max_events", ["256"])[0])
                    except ValueError:
                        max_events = 256
                    try:
                        body = dict(provider(max_events=max_events))
                    except Exception as e:
                        _log.exception("flight snapshot failed")
                        self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                        return
                    self._reply_json(200, body)
                elif route in server.json_routes:
                    try:
                        body = dict(server.json_routes[route]())
                    except Exception as e:
                        _log.exception("json route %s failed", route)
                        self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                        return
                    self._reply_json(200, body)
                elif route in server.query_routes:
                    params = parse_qs(urlparse(self.path).query)
                    try:
                        body = dict(server.query_routes[route](params))
                    except (KeyError, ValueError) as e:
                        self._reply_json(400, {"error": f"{type(e).__name__}: {e}"})
                        return
                    except Exception as e:
                        _log.exception("query route %s failed", route)
                        self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                        return
                    self._reply_json(200, body)
                else:
                    self.send_error(404)

            def do_POST(self):
                parsed = urlparse(self.path)
                if parsed.path in server.post_routes:
                    try:
                        length = int(self.headers.get("Content-Length") or 0)
                    except ValueError:
                        length = 0
                    data = self.rfile.read(length) if length > 0 else b""
                    try:
                        body = dict(server.post_routes[parsed.path](data))
                    except (KeyError, ValueError) as e:
                        self._reply_json(400, {"error": f"{type(e).__name__}: {e}"})
                        return
                    except Exception as e:
                        _log.exception("post route %s failed", parsed.path)
                        self._reply_json(500, {"error": f"{type(e).__name__}: {e}"})
                        return
                    self._reply_json(200, body)
                    return
                if parsed.path != "/profile":
                    self.send_error(404)
                    return
                if server.profile_handler is None:
                    self._reply_json(
                        404, {"error": "no profiler wired (obs profile capture is learner-only)"}
                    )
                    return
                try:
                    seconds = float(parse_qs(parsed.query).get("seconds", ["5"])[0])
                except ValueError:
                    seconds = math.nan  # "nan"/"inf" parse as floats; unify below
                if not math.isfinite(seconds):
                    self._reply_json(400, {"error": "seconds must be a finite number"})
                    return
                try:
                    path = server.profile_handler(seconds)
                except Exception as e:
                    busy = isinstance(e, CaptureBusyError)
                    if not busy:
                        _log.exception("profile capture failed")
                    self._reply_json(
                        409 if busy else 500, {"error": f"{type(e).__name__}: {e}"}
                    )
                    return
                # Echo what was actually traced: a (path, seconds) handler
                # reports its clamped window — echoing the raw request
                # would misdescribe the artifact.
                if isinstance(path, tuple):
                    path, seconds = path
                self._reply_json(200, {"trace_dir": path, "seconds": seconds})

            def log_message(self, fmt, *args):  # scrape spam stays out of stderr
                pass

        self._httpd = ThreadingHTTPServer(("", self._requested_port), Handler)
        self._httpd.daemon_threads = True
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True, name="obs-metrics-http"
        )
        self._thread.start()
        _log.info("obs /metrics serving on port %d", self.port)
        return self

    def stop(self) -> None:
        if self._httpd is not None:
            self._httpd.shutdown()
            self._httpd.server_close()
            self._httpd = None
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
