"""Learner COMPUTE observability: step-phase timing, recompile sentinel,
MFU accounting, on-demand profiler capture.

PR 2 made the pipeline legible (where a rollout spends its time); the
learner's compute was still a black box — a silent XLA recompile, a
shrinking device/host ratio, or a stalled loop all looked identical on
the scrape surface. This module decomposes the steps/s headline into
causes:

- StepPhaseTimer   every learner iteration split into
                   fetch / pack / h2d / device_step / host wall time.
                   Exists only under --obs.enabled + --obs.step_phases;
                   the disabled path constructs nothing. In the SERIAL
                   loop it fences per step (block_until_ready) for
                   causal attribution; under the pipelined loop
                   (--learner.prefetch) it runs in OVERLAP mode — the
                   prefetch lane records its own fetch/pack/h2d, the
                   loop lane reports the exposed wait/residual/host,
                   and the pipeline_* family carries the overlap
                   accounting with no per-step fence.
- RecompileSentinel wraps the jitted train step, hashes the abstract
                   avals + treedef of every call, counts signatures
                   beyond the first as recompiles, records compile wall
                   time, and dumps the offending shape-diff to the
                   flight recorder. Steady-state training must hold
                   compute_recompiles_total at 0 — any increment is a
                   batch-shape bug upstream.
- MfuAccountant    cumulative model-FLOPs utilization from the
                   ops/flops.py analytic cost model against the
                   per-platform peak table (TPU only; no peak entry →
                   no compute_mfu, achieved FLOP/s still reported).
- ProfileCapture   on-demand jax.profiler.trace windows for the obs
                   HTTP server's POST /profile?seconds=N — replaces the
                   always-on-or-nothing cfg.profile_port server.

Everything logs through the existing MetricsLogger stream under the
compute_* names documented in obs/registry.py.
"""

from __future__ import annotations

import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger(__name__)


# --------------------------------------------------------------- phases


class StepPhaseTimer:
    """Per-iteration wall-time decomposition of the learner loop.

    Phases (the loop's stations, in order):
      fetch        host wait for a packed batch off staging
      pack         io.pack fallback when staging didn't pre-pack (≈0 on
                   the production fused path — pack runs on the staging
                   thread and is charged to fetch's queue wait)
      h2d          host→device transfer, FENCED (block_until_ready on
                   the device batch) so it is the real transfer time,
                   not the dispatch time
      device_step  train-step dispatch + device execution, FENCED on the
                   step's metrics
      host         publish dispatch / checkpoint / metrics-window work

    Single-writer contract: only the learner loop thread calls add() and
    step(); window_scalars() is called from that same thread at each
    metrics window. The scrape thread reads the RESULT via
    MetricsLogger.latest(), never this object.

    The warm-up fetch and empty-wait retries record fetch time with no
    closing step(), so a STARVED window's fetch mean can exceed its wall
    mean — starvation is exactly when that should read loud. In a fed
    window the phases tile the wall (the acceptance property).

    OVERLAP mode (``overlap=True`` — the pipelined loop,
    ``--learner.prefetch``): the host side of batch N+1 runs on a
    dedicated prefetch lane WHILE the device executes step N, so
    fencing the loop per step would destroy exactly what it measures.
    Instead the accounting splits into two lanes:

    - the LOOP lane keeps the single-writer add()/step() contract, but
      ``fetch`` now means the loop's wait for a prefetched batch (the
      exposed, un-hidden host time — the device-idle upper bound),
      ``pack``/``h2d`` stay 0 there, ``device_step`` is the UNFENCED
      residual (the in-flight device window from the loop's clock), and
      ``host`` is publish/checkpoint work as before — phases still tile
      the wall, by construction rather than by fencing;
    - the PREFETCH lane records its own fetch/pack/h2d wall via
      add_overlap() — called from the lane thread, so those sums live
      under a lock (``overlap_s`` accounting) — and window_scalars()
      reports them as the ``pipeline_*`` family: per-lane means,
      ``pipeline_prefetch_s`` (lane busy per step),
      ``pipeline_device_idle_s`` (the exposed loop wait), and
      ``pipeline_overlap_ratio`` (share of lane work hidden behind the
      device step).
    """

    PHASES = ("fetch", "pack", "h2d", "device_step", "host")
    LANE_PHASES = ("fetch", "pack", "h2d")

    def __init__(self, overlap: bool = False):
        self.overlap = overlap
        self._sums: Dict[str, float] = dict.fromkeys(self.PHASES, 0.0)
        self._wall = 0.0
        self._steps = 0
        # Prefetch-lane sums (overlap mode only): written by the lane
        # thread, read by the loop thread at window close — the one
        # cross-thread surface, so it gets its own lock (a handful of
        # acquisitions per step against a multi-ms step).
        self._lane_lock = threading.Lock()
        self._lane_sums: Dict[str, float] = dict.fromkeys(self.LANE_PHASES, 0.0)

    def add(self, phase: str, seconds: float) -> None:
        self._sums[phase] += max(float(seconds), 0.0)

    def add_overlap(self, phase: str, seconds: float) -> None:
        """Prefetch-lane attribution (overlap mode): fetch/pack/h2d time
        the lane paid for a batch, hidden behind the device step. Called
        from the lane thread — the only writer of these sums."""
        with self._lane_lock:
            self._lane_sums[phase] += max(float(seconds), 0.0)

    def step(self, wall_seconds: float) -> None:
        """Close one loop iteration: its total wall time."""
        self._wall += max(float(wall_seconds), 0.0)
        self._steps += 1

    def window_scalars(self, reset: bool = True) -> Dict[str, float]:
        """Mean seconds per step for each phase over the window, the
        mean iteration wall, and the fetch fraction (the watchdog's
        starvation signal). Overlap mode adds the pipeline_* lane
        scalars. Resets the window by default (the learner logs once
        per metrics window, like its win_* accumulators)."""
        n = max(self._steps, 1)
        out = {f"compute_phase_{p}_s": self._sums[p] / n for p in self.PHASES}
        out["compute_phase_wall_s"] = self._wall / n
        if self._wall > 0:
            out["compute_phase_fetch_frac"] = self._sums["fetch"] / self._wall
        if self.overlap:
            with self._lane_lock:
                lane = dict(self._lane_sums)
                if reset:
                    self._lane_sums = dict.fromkeys(self.LANE_PHASES, 0.0)
            lane_total = sum(lane.values())
            exposed = self._sums["fetch"]  # loop wait for a prefetched batch
            for p in self.LANE_PHASES:
                out[f"pipeline_prefetch_{p}_s"] = lane[p] / n
            out["pipeline_prefetch_s"] = lane_total / n
            out["pipeline_device_idle_s"] = exposed / n
            out["pipeline_overlap_ratio"] = (
                max(0.0, min(1.0, 1.0 - exposed / lane_total)) if lane_total > 0 else 1.0
            )
        if reset:
            self._sums = dict.fromkeys(self.PHASES, 0.0)
            self._wall = 0.0
            self._steps = 0
        return out


# ------------------------------------------------------------- sentinel


def abstract_signature(tree) -> Tuple:
    """Hashable (treedef, per-leaf (shape, dtype)) summary of a pytree —
    exactly the cache key axes jax.jit re-traces on (plus sharding,
    which the learner pins via in_shardings). Non-array leaves hash by
    type, matching jit's weak-type/static treatment closely enough for a
    sentinel."""
    import jax

    leaves, treedef = jax.tree_util.tree_flatten(tree)
    return (
        treedef,
        tuple(
            (tuple(getattr(l, "shape", ())), str(getattr(l, "dtype", type(l).__name__)))
            for l in leaves
        ),
    )


def _described_leaves(tree) -> List[Tuple[str, Tuple, str]]:
    """[(path, shape, dtype)] — the human-readable form of the signature,
    computed only on cache misses (tree_flatten_with_path costs more than
    the plain flatten the hot path pays)."""
    import jax

    out = []
    for path, leaf in jax.tree_util.tree_flatten_with_path(tree)[0]:
        name = "".join(str(p) for p in path)
        out.append(
            (name, tuple(getattr(leaf, "shape", ())), str(getattr(leaf, "dtype", type(leaf).__name__)))
        )
    return out


def signature_diff(old: List[Tuple], new: List[Tuple], limit: int = 12) -> List[str]:
    """Human-readable shape-diff between two described signatures — the
    payload of the flight-recorder recompile event. Bounded: a treedef
    change can differ in hundreds of leaves and the ring must not bloat."""
    old_map = {p: (s, d) for p, s, d in old}
    new_map = {p: (s, d) for p, s, d in new}
    diffs = []
    for p, (s, d) in new_map.items():
        if p not in old_map:
            diffs.append(f"+{p}: {s} {d}")
        elif old_map[p] != (s, d):
            os_, od = old_map[p]
            diffs.append(f"{p}: {os_} {od} -> {s} {d}")
    for p, (s, d) in old_map.items():
        if p not in new_map:
            diffs.append(f"-{p}: {s} {d}")
    if len(diffs) > limit:
        diffs = diffs[:limit] + [f"... {len(diffs) - limit} more"]
    return diffs


class RecompileSentinel:
    """Wraps a jitted callable; every call whose abstract signature was
    never seen before is counted as a compile (and, beyond the first, a
    RECOMPILE) and its wall time recorded — on a cache miss the call
    blocks through trace+lower+compile, so the call duration IS the
    compile wall time to within dispatch noise. Known signatures pay one
    tree_flatten + dict probe (~µs against a multi-ms train step).

    The shape-diff between the new signature and the previous one goes
    to the flight recorder (event "recompile"), so a dump answers WHICH
    leaf changed shape, not just that something did.
    """

    def __init__(self, fn, label: str = "train_step", recorder=None):
        self._fn = fn
        self._label = label
        self._recorder = recorder
        self._seen: Dict = {}  # signature -> described leaves
        self._last_desc: Optional[List[Tuple]] = None
        self.compiles = 0
        self.recompiles = 0
        self.compile_s = 0.0  # cumulative wall across all compiles
        self.last_compile_s = 0.0

    def __call__(self, *args):
        sig = abstract_signature(args)
        if sig in self._seen:
            return self._fn(*args)
        t0 = time.perf_counter()
        out = self._fn(*args)
        dt = time.perf_counter() - t0
        desc = _described_leaves(args)
        self.compiles += 1
        self.compile_s += dt
        self.last_compile_s = dt
        if self._last_desc is not None:
            self.recompiles += 1
            diff = signature_diff(self._last_desc, desc)
            _log.warning(
                "%s RECOMPILED (#%d, %.2fs): signature changed: %s",
                self._label,
                self.recompiles,
                dt,
                "; ".join(diff) or "<treedef-only change>",
            )
            if self._recorder is not None:
                self._recorder.record(
                    "recompile",
                    label=self._label,
                    n=self.recompiles,
                    compile_s=round(dt, 3),
                    diff=diff,
                )
        else:
            _log.info("%s compiled in %.2fs (first signature)", self._label, dt)
            if self._recorder is not None:
                self._recorder.record("compile", label=self._label, compile_s=round(dt, 3))
        self._seen[sig] = desc
        self._last_desc = desc
        return out

    def scalars(self) -> Dict[str, float]:
        return {
            "compute_recompiles_total": float(self.recompiles),
            "compute_compiles_total": float(self.compiles),
            "compute_compile_s": self.compile_s,
            "compute_last_compile_s": self.last_compile_s,
        }


# ------------------------------------------------------------------ MFU


class MfuAccountant:
    """Cumulative model-FLOPs utilization. `flops_per_step` comes from
    ops/flops.py's analytic matmul model (fwd+bwd, reuse-aware);
    `peak_flops` is the AGGREGATE peak over the learner's devices from
    the per-platform table (None — e.g. CPU smoke — suppresses
    compute_mfu; achieved FLOP/s is still reported so regressions stay
    visible even where utilization is meaningless)."""

    def __init__(self, flops_per_step: float, peak_flops: Optional[float]):
        self.flops_per_step = float(flops_per_step)
        self.peak_flops = peak_flops
        self._steps = 0
        self._seconds = 0.0

    def add_window(self, steps: int, seconds: float) -> None:
        self._steps += int(steps)
        self._seconds += max(float(seconds), 0.0)

    def scalars(self) -> Dict[str, float]:
        if self._seconds <= 0 or self._steps == 0:
            return {}
        achieved = self.flops_per_step * self._steps / self._seconds
        out = {"compute_flops_per_sec": achieved}
        if self.peak_flops:
            out["compute_mfu"] = achieved / self.peak_flops
        return out


# ------------------------------------------------------------- profiler


class CaptureBusyError(RuntimeError):
    """A jax.profiler capture is already in flight (jax supports one)."""


class ProfileCapture:
    """On-demand device/host trace windows. One capture at a time —
    jax.profiler owns process-global state — and each capture lands in
    its own TensorBoard-loadable dir under `out_dir`. The HTTP handler
    thread blocks inside capture() for the window; the learner loop is
    untouched (the profiler samples it from the side)."""

    def __init__(self, out_dir: str, max_seconds: float = 60.0):
        self.out_dir = out_dir or os.getcwd()
        self.max_seconds = max_seconds
        self._lock = threading.Lock()
        self.captures_done = 0
        self.last_path: Optional[str] = None

    def capture(self, seconds: float) -> Tuple[str, float]:
        """Trace for `seconds` (clamped to (0, max_seconds]) and return
        (trace dir, window actually traced) — one atomic result, so the
        HTTP handler echoes the clamped window of THIS capture, never a
        concurrent one's. Raises ValueError on a non-finite request and
        CaptureBusyError when a capture is in flight."""
        import jax
        import math

        seconds = float(seconds)
        if not math.isfinite(seconds):
            # NaN slides through min/max (both return nan) and would
            # reach time.sleep mid-trace — reject before touching the
            # profiler.
            raise ValueError(f"seconds must be finite, got {seconds!r}")
        seconds = min(max(seconds, 0.1), self.max_seconds)
        if not self._lock.acquire(blocking=False):
            raise CaptureBusyError("a profiler capture is already running")
        try:
            stamp = time.strftime("%Y%m%dT%H%M%S")
            path = os.path.join(self.out_dir, f"profile_{stamp}")
            os.makedirs(path, exist_ok=True)
            jax.profiler.start_trace(path)
            try:
                time.sleep(seconds)
            finally:
                jax.profiler.stop_trace()
            self.captures_done += 1
            self.last_path = path
            _log.info("profiler capture (%.1fs) written to %s", seconds, path)
            return path, seconds
        finally:
            self._lock.release()


# ----------------------------------------------------------- the bundle


class ComputeObserver:
    """One learner's compute-observability bundle: phase timer (optional,
    it costs the overlap), recompile sentinel, MFU accounting. Built by
    ObsRuntime.attach_compute(); everything funnels into window_scalars()
    on the learner's metrics cadence."""

    def __init__(
        self,
        flops_per_step: float,
        peak_flops: Optional[float],
        recorder=None,
        step_phases: bool = True,
        overlap: bool = False,
    ):
        self.timer = StepPhaseTimer(overlap=overlap) if step_phases else None
        self.mfu = MfuAccountant(flops_per_step, peak_flops)
        self.sentinel: Optional[RecompileSentinel] = None
        self._recorder = recorder

    def wrap_train_step(self, fn, label: str = "train_step"):
        """Returns the sentinel-wrapped step; the learner swaps its
        train_step for this. Idempotent per ComputeObserver."""
        self.sentinel = RecompileSentinel(fn, label=label, recorder=self._recorder)
        return self.sentinel

    def window_scalars(self, steps: int, seconds: float) -> Dict[str, float]:
        """Everything compute_* for one metrics window: phase means (and
        reset), cumulative recompile/compile counters, cumulative
        MFU/FLOP-rate over windows seen so far."""
        self.mfu.add_window(steps, seconds)
        out: Dict[str, float] = {}
        if self.timer is not None:
            out.update(self.timer.window_scalars())
        if self.sentinel is not None:
            out.update(self.sentinel.scalars())
        out.update(self.mfu.scalars())
        return out
