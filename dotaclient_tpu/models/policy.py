"""Flax LSTM actor-critic — the TPU-native re-design of the reference's
policy.py (SURVEY.md §2 "Policy net", §3.3 call stack).

Reference architecture (PyTorch): per-unit MLP embeddings pooled over
nearby units + hero stats → LSTM(~128) → heads {action-enum, move-x,
move-y (9-way grids), target-unit via dot-product attention over unit
embeddings, value}, with invalid-action masking and a joint log-prob over
selected sub-heads. TPU-first decisions here:

- **One module, two modes.** The actor needs a stateful single step, the
  learner a teacher-forced full unroll; both are the same `PolicyCore`
  applied directly or through `nn.scan` over the time axis (params
  broadcast), so step-vs-unroll equivalence is structural, not tested-in.
- **`lax.scan` over time, batch over devices.** The LSTM family's time
  axis stays inside one device (chunk length ~16, the reference regime —
  SURVEY.md §5); scaling is over the batch via the mesh. Long chunks are
  the transformer family's job (models/transformer_policy.py), where the
  time axis itself shards over an `sp` mesh axis.
- **bfloat16 compute, float32 params and heads.** Matmuls hit the MXU in
  bf16; logits/value are cast to f32 before masking/sampling/loss so the
  distribution math is stable.
- **Masks flow in as data** (from the featurizer) — no data-dependent
  Python control flow under jit.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn

from dotaclient_tpu.config import PolicyConfig
from dotaclient_tpu.env import featurizer as F
from dotaclient_tpu.ops import lstm as L
from dotaclient_tpu.ops.action_dist import BIG_NEG, Dist, masked_log_softmax

LSTMState = Tuple[jnp.ndarray, jnp.ndarray]  # (c, h), each [B, H]


class AuxOutputs(NamedTuple):
    """Auxiliary value heads (benchmark config 5): win-prob logit,
    predicted last-hit rate, predicted net-worth (both normalized)."""

    win_logit: jnp.ndarray  # [...]
    last_hit: jnp.ndarray  # [...]
    net_worth: jnp.ndarray  # [...]


class PolicyOutput(NamedTuple):
    dist: Dist
    value: jnp.ndarray  # [...] f32
    aux: Optional[AuxOutputs]


def _dtype(cfg: PolicyConfig):
    return jnp.dtype(cfg.dtype)


class LSTMCell(nn.Module):
    """LSTM with a split gate matmul: x and h project separately so the
    x half hoists out of the time loop entirely (ONE [B·T, in]×[in, 4H]
    MXU matmul per unroll), and the sequential remainder — the [B, H]
    hidden projection + gate tail — runs through ops/lstm.py, where a
    fused Pallas kernel serves the TPU path and lax.scan everything
    else. Forget-gate bias +1; gate math f32, matmuls in `dtype`.
    """

    features: int
    dtype: jnp.dtype = jnp.bfloat16
    impl: str = "auto"  # ops/lstm.py dispatcher: auto|scan|pallas|pallas_interpret

    @nn.compact
    def __call__(
        self, carry: LSTMState, x: jnp.ndarray, unroll: bool = False
    ) -> Tuple[LSTMState, jnp.ndarray]:
        H = self.features
        dt = self.dtype
        w_x = self.param("w_x", nn.initializers.lecun_normal(), (x.shape[-1], 4 * H))
        w_h = self.param("w_h", nn.initializers.lecun_normal(), (H, 4 * H))
        bias = self.param("bias", nn.initializers.zeros_init(), (4 * H,))
        c, h = carry
        x_proj = x.astype(dt) @ w_x.astype(dt) + bias.astype(dt)
        if not unroll:
            z = x_proj + h.astype(dt) @ w_h.astype(dt)
            new_c, new_h = L.gates(z, c)
            return (new_c, new_h), new_h
        h_seq, (c_T, h_T) = L.lstm_recurrence(x_proj, w_h.astype(dt), c, h, impl=self.impl)
        return (c_T, h_T), h_seq


def obs_trunk(cfg: PolicyConfig, obs: F.Observation):
    """Embeddings + pooling + trunk MLP, shared by both policy families.

    Must be called inside a compact scope (Flax registers the Dense
    layers on the module whose scope is active), so layer names stay
    flat ("unit_mlp1", …) and the LSTM family's param tree is identical
    to the pre-refactor layout. Returns (trunk [.., H], unit_emb
    [.., U, D]) — position-independent, so in unroll mode everything
    here is one [B·T]-batched MXU matmul.
    """
    dt = _dtype(cfg)
    D = cfg.unit_embed_dim

    unit_mask = obs.unit_mask
    units = obs.unit_feats.astype(dt)
    x = nn.Dense(cfg.mlp_hidden, dtype=dt, name="unit_mlp1")(units)
    x = nn.relu(x)
    unit_emb = nn.Dense(D, dtype=dt, name="unit_mlp2")(x)  # [B, U, D]

    # Masked max+mean pooling to a fixed-size neighbourhood context.
    m = unit_mask[..., None]
    neg = jnp.asarray(BIG_NEG, dt)
    pool_max = jnp.max(jnp.where(m, unit_emb, neg), axis=-2)
    any_unit = jnp.any(unit_mask, axis=-1, keepdims=True)
    pool_max = jnp.where(any_unit, pool_max, 0.0)
    denom = jnp.maximum(jnp.sum(m, axis=-2), 1).astype(dt)
    pool_mean = jnp.sum(jnp.where(m, unit_emb, 0.0), axis=-2) / denom

    hero = nn.Dense(cfg.mlp_hidden, dtype=dt, name="hero_mlp")(obs.hero_feats.astype(dt))
    glob = nn.Dense(cfg.mlp_hidden // 4, dtype=dt, name="global_mlp")(obs.global_feats.astype(dt))
    trunk = jnp.concatenate([nn.relu(hero), nn.relu(glob), pool_max, pool_mean], axis=-1)
    trunk = nn.relu(nn.Dense(cfg.lstm_hidden, dtype=dt, name="trunk")(trunk))
    return trunk, unit_emb


def action_heads(
    cfg: PolicyConfig, out: jnp.ndarray, unit_emb: jnp.ndarray, obs: F.Observation
) -> PolicyOutput:
    """Masked action heads + value (+aux), shared by both families.
    `out` is the temporal core's output in f32; logits compute in f32
    for stable masking/softmax."""
    D = cfg.unit_embed_dim
    type_logits = nn.Dense(F.N_ACTION_TYPES, dtype=jnp.float32, name="type_head")(out)
    move_x = nn.Dense(cfg.n_move_bins, dtype=jnp.float32, name="move_x_head")(out)
    move_y = nn.Dense(cfg.n_move_bins, dtype=jnp.float32, name="move_y_head")(out)
    # Target selection = dot-product attention of a core-output query
    # against the unit embeddings (reference's target head).
    query = nn.Dense(D, dtype=jnp.float32, name="target_query")(out)
    target_logits = jnp.einsum("...d,...ud->...u", query, unit_emb.astype(jnp.float32))
    target_logits = target_logits / jnp.sqrt(jnp.asarray(D, jnp.float32))

    dist = Dist(
        type_logp=masked_log_softmax(type_logits, obs.action_mask),
        move_x_logp=jax.nn.log_softmax(move_x, axis=-1),
        move_y_logp=jax.nn.log_softmax(move_y, axis=-1),
        target_logp=masked_log_softmax(target_logits, obs.target_mask),
    )
    value = nn.Dense(1, dtype=jnp.float32, name="value_head")(out)[..., 0]

    aux = None
    if cfg.aux_heads:
        aux = AuxOutputs(
            win_logit=nn.Dense(1, dtype=jnp.float32, name="aux_win")(out)[..., 0],
            last_hit=nn.Dense(1, dtype=jnp.float32, name="aux_lh")(out)[..., 0],
            net_worth=nn.Dense(1, dtype=jnp.float32, name="aux_nw")(out)[..., 0],
        )
    return PolicyOutput(dist=dist, value=value, aux=aux)


class PolicyCore(nn.Module):
    """The LSTM policy network: featurized obs + LSTM state → action dist
    + value. One module, both modes — single step (obs leaves [B, ...])
    and teacher-forced unroll (obs leaves [B, T, ...]). Every layer here
    except the LSTM recurrence is position-independent, so in unroll mode
    the embeddings, trunk, and heads all run as single [B·T] batched MXU
    matmuls; only the recurrence (ops/lstm.py) walks the time axis."""

    cfg: PolicyConfig

    @nn.compact
    def __call__(
        self, carry: LSTMState, obs: F.Observation, unroll: bool = False
    ) -> Tuple[LSTMState, PolicyOutput]:
        cfg = self.cfg
        trunk, unit_emb = obs_trunk(cfg, obs)

        # LSTM output stays f32: every head computes in f32, so a bf16
        # round-trip here would be pure precision loss.
        carry, out = LSTMCell(cfg.lstm_hidden, dtype=_dtype(cfg), impl=cfg.lstm_impl, name="lstm")(
            carry, trunk, unroll=unroll
        )
        return carry, action_heads(cfg, out, unit_emb, obs)


class PolicyNet(nn.Module):
    """Public policy module — family-agnostic front door.

    - `apply(params, state, obs)` — single step, obs leaves [B, ...].
    - `apply(params, state, obs_seq, unroll=True)` — teacher-forced unroll,
      obs leaves [B, T, ...]; returns outputs with a [B, T] time axis and
      the final temporal state.
    Params are identical between the two modes (every layer is shared;
    the time axis only exists inside the temporal core). cfg.arch picks
    the core: "lstm" (flagship) or "transformer" (long-context family —
    models/transformer_policy.py; its unroll ignores `state`, context is
    chunk-local). `sp_mesh` is only read by the transformer family's
    unroll, to ring-shard the time axis over cfg.tf_sp_axis.
    """

    cfg: PolicyConfig
    sp_mesh: Optional[object] = None  # jax.sharding.Mesh; None = no SP

    def _assert_shapes(self, obs: F.Observation) -> None:
        assert obs.unit_feats.shape[-2:] == (F.MAX_UNITS, F.UNIT_FEATURES)

    @nn.compact
    def __call__(self, state, obs: F.Observation, unroll: bool = False):
        self._assert_shapes(obs)
        if self.cfg.arch == "transformer":
            # Import here: transformer_policy imports this module's
            # shared trunk/heads.
            from dotaclient_tpu.models.transformer_policy import TransformerPolicyCore

            return TransformerPolicyCore(self.cfg, self.sp_mesh, name="core")(state, obs, unroll)
        return PolicyCore(self.cfg, name="core")(state, obs, unroll)

def initial_state(cfg: PolicyConfig, batch_shape):
    """Fresh temporal state without needing a module instance (host-side
    use): LSTM (c, h) zeros, or the transformer family's empty KVCache.
    Every leaf is batch-leading in both families."""
    if cfg.arch == "transformer":
        from dotaclient_tpu.models.transformer_policy import init_cache

        return init_cache(cfg, batch_shape)
    shape = tuple(batch_shape) + (cfg.lstm_hidden,)
    return (jnp.zeros(shape, jnp.float32), jnp.zeros(shape, jnp.float32))


def wire_state(cfg: PolicyConfig, state):
    """The (c, h) [B, H] f32 pair the fixed wire format ships with each
    rollout (transport/serialize.py). The LSTM's state IS that pair; a
    transformer KVCache maps to zeros — the learner's unroll is
    chunk-local and ignores initial state, so nothing real is lost and
    the wire format stays family-agnostic."""
    if cfg.arch == "transformer":
        import numpy as np

        B = state.idx.shape[0]
        z = np.zeros((B, cfg.lstm_hidden), np.float32)
        return (z, z)
    return state


def reset_between_chunks(cfg: PolicyConfig, state):
    """Chunk-boundary state transition for the actor. The LSTM carries
    its state across chunks (the learner receives it on the wire —
    SURVEY.md §7 "LSTM state handoff"); the transformer family resets to
    an empty cache so acting context matches the learner's chunk-local
    teacher-forced re-eval exactly."""
    if cfg.arch == "transformer":
        from dotaclient_tpu.models.transformer_policy import init_cache

        return init_cache(cfg, (state.idx.shape[0],))
    return state


def init_params(cfg: PolicyConfig, rng: jax.Array):
    """Initialize parameters with a dummy single-step batch of 1."""
    net = PolicyNet(cfg)
    obs = jax.tree.map(lambda x: jnp.asarray(x)[None], F.zeros_observation())
    state = initial_state(cfg, (1,))
    return net.init(rng, state, obs)
