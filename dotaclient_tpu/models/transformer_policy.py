"""Transformer actor-critic — the long-context policy family.

The flagship family is the LSTM (models/policy.py), matching the
reference's architecture (SURVEY.md §3.3 "Policy forward"). This family
exists for the scale regime the reference never reached: observation
histories of hundreds-to-thousands of steps, where an LSTM's fixed-width
carry is the bottleneck and the TPU-right design is a causal transformer
over the time axis with the O(T²) attention sharded over an `sp` mesh
axis (ops/ring_attention.py).

Interface contract — identical to the LSTM family, so the actor loop,
train step, staging and wire format are all family-agnostic:

- `unroll=False` (actor): the carried state is a `KVCache`; one step
  writes the new token's K/V at each row's slot and attends over the
  cache. Per-row write indices mean batched actors at different episode
  phases share one compiled step.
- `unroll=True` (learner): teacher-forced causal attention over the
  whole [B, T, ...] chunk; the passed state is IGNORED — context is
  chunk-local by design, and the actor resets its cache at every chunk
  boundary (models.policy.reset_between_chunks) so acting-time and
  re-eval-time distributions are identical. This is the transformer's
  analogue of shipping the LSTM carry with each chunk (SURVEY.md §7
  "LSTM state handoff"); the trade — no cross-chunk memory — is bought
  back by making chunks long (seq_len 128+), which is exactly the
  regime attention wants and sequence parallelism pays for.

The observation trunk and every action head are the shared functions in
models/policy.py (`obs_trunk` / `action_heads`), so the two families
differ only in their temporal core.
"""

from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp
from flax import linen as nn
from jax.sharding import Mesh

from dotaclient_tpu.config import PolicyConfig
from dotaclient_tpu.ops import attention as A
from dotaclient_tpu.ops import ring_attention as RA


class KVCache(NamedTuple):
    """Actor-side attention state. Every leaf is BATCH-LEADING (like the
    LSTM's (c, h)) so the generic state plumbing — selfplay's per-side
    concat/slice batching, the actor's row resets — works unchanged:
    k/v [B, L, C, N, Dh]; pos [B, C] holds absolute positions with
    EMPTY_POS in unwritten slots (shared across layers — every layer
    sees the same timeline); idx [B] is each row's next write slot."""

    k: jnp.ndarray
    v: jnp.ndarray
    pos: jnp.ndarray
    idx: jnp.ndarray


def init_cache(cfg: PolicyConfig, batch_shape) -> KVCache:
    B = int(batch_shape[0]) if len(batch_shape) else 1
    L, C, N = cfg.tf_layers, cfg.tf_context, cfg.tf_heads
    # Fail at config time, not as a confusing shape error deep in a later
    # trace: a host-side init_cache with indivisible width would silently
    # build a mis-shaped cache (ADVICE r3 item 1). RoPE additionally
    # needs an even head dim.
    if cfg.lstm_hidden % N:
        raise ValueError(
            f"transformer width lstm_hidden={cfg.lstm_hidden} must divide by "
            f"tf_heads={N}"
        )
    Dh = cfg.lstm_hidden // N
    if Dh % 2:
        raise ValueError(f"head dim {Dh} must be even (RoPE rotates half-pairs)")
    # K/V live in the COMPUTE dtype: the values written are Dense outputs
    # in that dtype anyway, so f32 storage was pure memory/H2D overhead
    # (2x actor cache bytes); scores still accumulate in f32 inside
    # attention (ADVICE r3 item 3). pos/idx stay int32.
    dt = jnp.dtype(cfg.dtype)
    return KVCache(
        k=jnp.zeros((B, L, C, N, Dh), dt),
        v=jnp.zeros((B, L, C, N, Dh), dt),
        pos=jnp.full((B, C), A.EMPTY_POS, jnp.int32),
        idx=jnp.zeros((B,), jnp.int32),
    )


class Block(nn.Module):
    """Pre-LN transformer block: LN → causal MHA (+residual) → LN →
    GELU MLP (+residual). Matmuls in `dtype` (MXU); LN, softmax and the
    residual stream in f32."""

    d_model: int
    n_heads: int
    dtype: jnp.dtype = jnp.bfloat16
    sp_mesh: Optional[Mesh] = None
    sp_axis: str = ""
    sp_mode: str = "ring"
    kv_block: int = 0

    @nn.compact
    def __call__(
        self,
        x: jnp.ndarray,  # [B, T, D] f32 residual stream
        positions: jnp.ndarray,  # [B, T] int32 absolute positions
        cache: Optional[Tuple[jnp.ndarray, jnp.ndarray, jnp.ndarray, jnp.ndarray]] = None,
    ):
        """cache=None: causal self-attention over the T axis (unroll
        mode; ring-sharded when sp_mesh/sp_axis are set). Otherwise
        cache=(k_cache [B,C,N,Dh], v_cache, cache_pos [B,C] ALREADY
        including this token's position, write_onehot [B,C]): T==1
        stepping — the block writes its fresh K/V into the cache at
        write_onehot and attends over the merged cache. Returns
        (x_out, None) in unroll mode, (x_out, (k_cache', v_cache')) in
        step mode."""
        D, N = self.d_model, self.n_heads
        Dh = D // N
        dt = self.dtype

        h = nn.LayerNorm(dtype=jnp.float32, name="ln1")(x)
        qkv = nn.Dense(3 * D, dtype=dt, name="qkv")(h.astype(dt))
        q, k, v = jnp.split(qkv, 3, axis=-1)
        # RoPE at this token's absolute position; cached K were rotated
        # at write time, so angles are consistent across modes.
        q = A.rope(q.reshape(q.shape[:-1] + (N, Dh)), positions)
        k = A.rope(k.reshape(k.shape[:-1] + (N, Dh)), positions)
        v = v.reshape(v.shape[:-1] + (N, Dh))

        new_cache = None
        if cache is None:
            attn = RA.attend(
                q, k, v, positions, positions,
                mesh=self.sp_mesh, sp_axis=self.sp_axis, sp_mode=self.sp_mode,
                kv_block=self.kv_block,
            )
        else:
            k_cache, v_cache, cache_pos, onehot = cache
            # Write in the cache's own dtype (compute dtype — init_cache):
            # jnp.where avoids the f32 promotion a mask-blend would cause.
            sel = onehot[:, :, None, None]  # [B, C, 1, 1] bool
            k_cache = jnp.where(sel, k.astype(k_cache.dtype), k_cache)
            v_cache = jnp.where(sel, v.astype(v_cache.dtype), v_cache)
            attn = RA.attend(q, k_cache, v_cache, positions, cache_pos)
            new_cache = (k_cache, v_cache)
        out = nn.Dense(D, dtype=dt, name="attn_out")(
            attn.astype(dt).reshape(attn.shape[:-2] + (D,))
        )
        x = x + out.astype(jnp.float32)

        h = nn.LayerNorm(dtype=jnp.float32, name="ln2")(x)
        h = nn.Dense(4 * D, dtype=dt, name="mlp_up")(h.astype(dt))
        h = nn.gelu(h)
        h = nn.Dense(D, dtype=dt, name="mlp_down")(h)
        return x + h.astype(jnp.float32), new_cache


class TransformerCore(nn.Module):
    """Temporal core: trunk features → context features.

    Unroll: x [B, T, D] → [B, T, D], carry passed through untouched
    (chunk-local context). Step: x [B, D] → [B, D], carry is a KVCache.
    """

    cfg: PolicyConfig
    sp_mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, carry, x: jnp.ndarray, unroll: bool = False):
        cfg = self.cfg
        D, N, L = cfg.lstm_hidden, cfg.tf_heads, cfg.tf_layers
        if D % N:
            raise ValueError(f"lstm_hidden={D} not divisible by tf_heads={N}")
        if (D // N) % 2:
            raise ValueError(
                f"head dim {D // N} (lstm_hidden={D} / tf_heads={N}) must be "
                f"even — RoPE rotates feature pairs"
            )
        dt = jnp.dtype(cfg.dtype)

        if unroll:
            B, T = x.shape[0], x.shape[1]
            positions = jnp.broadcast_to(jnp.arange(T, dtype=jnp.int32), (B, T))
            h = x.astype(jnp.float32)
            # cfg.tf_remat: recompute each block's activations in the
            # backward instead of storing them (jax.checkpoint) —
            # O(T·D) residuals per block instead of every intermediate.
            block_cls = nn.remat(Block) if cfg.tf_remat else Block
            for i in range(L):
                h, _ = block_cls(
                    D, N, dt, self.sp_mesh, cfg.tf_sp_axis, cfg.tf_sp_mode,
                    cfg.tf_attn_block, name=f"block{i}"
                )(h, positions)
            return carry, h

        assert isinstance(carry, KVCache), "transformer step mode needs a KVCache carry"
        C = carry.pos.shape[1]
        positions = carry.idx[:, None]  # [B, 1] — this step's absolute position
        # Ring-buffer write: past capacity the oldest slot is overwritten,
        # degrading gracefully to sliding-window attention over the last C
        # tokens (absolute positions keep the causal mask and RoPE exact).
        # The shipping actor never wraps — it resets the cache every chunk
        # and tf_context >= chunk frames — but an unconditional one-hot of
        # an out-of-range index would silently DROP the write instead.
        onehot = jax.nn.one_hot(carry.idx % C, C, dtype=jnp.float32)  # [B, C]
        new_pos = jnp.where(onehot > 0, positions, carry.pos).astype(jnp.int32)

        h = x.astype(jnp.float32)[:, None, :]  # [B, 1, D]
        ks, vs = [], []
        for i in range(L):
            h, (k_i, v_i) = Block(D, N, dt, name=f"block{i}")(
                h, positions, cache=(carry.k[:, i], carry.v[:, i], new_pos, onehot)
            )
            ks.append(k_i)
            vs.append(v_i)
        new_carry = KVCache(
            k=jnp.stack(ks, axis=1), v=jnp.stack(vs, axis=1), pos=new_pos, idx=carry.idx + 1
        )
        return new_carry, h[:, 0, :]


class TransformerPolicyCore(nn.Module):
    """Shared trunk + transformer temporal core + shared heads — the
    drop-in alternative to models.policy.PolicyCore."""

    cfg: PolicyConfig
    sp_mesh: Optional[Mesh] = None

    @nn.compact
    def __call__(self, carry, obs, unroll: bool = False):
        from dotaclient_tpu.models.policy import action_heads, obs_trunk

        trunk, unit_emb = obs_trunk(self.cfg, obs)
        carry, out = TransformerCore(self.cfg, self.sp_mesh, name="tf")(carry, trunk, unroll)
        return carry, action_heads(self.cfg, out, unit_emb, obs)
