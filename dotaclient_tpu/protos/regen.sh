#!/bin/sh
# Regenerate the protobuf Python modules. Run from this directory.
# grpc_tools is not available in the image, so only message classes are
# generated; the gRPC service stubs are hand-written in
# dotaclient_tpu/env/service.py using grpc's generic handler API.
set -e
protoc --python_out=. -I. worldstate.proto dotaservice.proto
protoc --python_out=. -I. valve_worldstate.proto valve_dotaservice.proto
# protoc emits absolute sibling imports; make them package-relative.
sed -i 's/^import worldstate_pb2 as/from . import worldstate_pb2 as/' dotaservice_pb2.py
sed -i 's/^import valve_worldstate_pb2 as/from . import valve_worldstate_pb2 as/' valve_dotaservice_pb2.py
