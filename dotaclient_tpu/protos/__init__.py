"""Generated protobuf modules (worldstate, dotaservice).

Regenerate with ./regen.sh (protoc only; gRPC stubs are hand-written in
dotaclient_tpu/env/service.py because grpc_tools is not in the image).
"""

from . import worldstate_pb2, dotaservice_pb2  # noqa: F401
