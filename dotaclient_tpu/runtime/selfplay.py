"""Self-play actor: all controlled heroes of a game driven by one process
(1v1 mirror/league up to full 5v5 team play, cfg.team_size).

The reference's self-play opponent is the latest (or lagged) copy of the
learner's weights (SURVEY.md §2 "Eval / rating", BASELINE configs 3/5);
here one asyncio process controls both player_ids of a single env
session, which keeps the two sides in lockstep without any cross-process
game synchronization:

- **mirror** (`opponent="self"`): both sides play the live weights and
  BOTH publish experience — every game yields 2× trajectories, and the
  policy sees both the radiant and dire views of the same states (the
  team-indicator feature differs, so one shared LSTM learns both sides —
  exactly the "shared LSTM self-play" of BASELINE config 3).
- **league** (`opponent="league"`): the dire side plays a frozen PFSP
  snapshot from the local league pool (eval/league.py); only the live
  (radiant) side publishes experience. Snapshots are taken from the
  weight broadcasts the actor receives anyway — no extra transport.

TPU-first detail: ALL controlled heroes' observations are stacked into
batched jit calls per tick — 5v5 mirror is one B=10 policy step, league
mode one B=5 step per team's params. The policy step is a single
compiled program at every team size; per-hero trajectories publish
independently (team play = BASELINE configs 4-5).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import ActorConfig
from dotaclient_tpu.env import featurizer as F
from dotaclient_tpu.env import heroes
from dotaclient_tpu.env import rewards as R
from dotaclient_tpu.env.service import AsyncDotaServiceStub
from dotaclient_tpu.eval.league import League, Snapshot
from dotaclient_tpu.models import policy as P
from dotaclient_tpu.ops import action_dist as ad
from dotaclient_tpu.protos import dotaservice_pb2 as ds
from dotaclient_tpu.protos import worldstate_pb2 as ws
from dotaclient_tpu.runtime.actor import (
    apply_weight_frame,
    build_action,
    check_weight_freshness,
    connect_env_async,
    make_actor_step,
    next_chunk,
    reset_env_stub,
)
from dotaclient_tpu.transport.base import Broker, BrokerShedError
from dotaclient_tpu.transport.serialize import (
    serialize_rollout,
    unflatten_params,
    wire_cast_fn,
)

_log = logging.getLogger(__name__)

RADIANT_PLAYER, DIRE_PLAYER = 0, 5
TEAM_RADIANT, TEAM_DIRE = 2, 3


def _slice_action(action: ad.Action, i: int) -> ad.Action:
    """Row i of a batched Action, kept as a length-1 batch (chunk format)."""
    return ad.Action(
        type=action.type[i : i + 1],
        move_x=action.move_x[i : i + 1],
        move_y=action.move_y[i : i + 1],
        target=action.target[i : i + 1],
    )


class _Side:
    """Per-player episode state (view, LSTM carry, chunk, reward memory)."""

    def __init__(self, player_id: int, team_id: int, cfg: ActorConfig):
        self.player_id = player_id
        self.team_id = team_id
        self.state, self.chunk = next_chunk(cfg.policy, P.initial_state(cfg.policy, (1,)))
        self.world: Optional[ws.World] = None
        self.obs: Optional[F.Observation] = None
        self.handles: Optional[np.ndarray] = None
        self.last_hero: Optional[ws.Unit] = None
        self.episode_return = 0.0
        # Remote-opponent session continuity (--serve.resume; the
        # RemoteActor protocol, per opponent side): completed remote
        # steps, the last OBSERVED chunk boundary (durably restorable —
        # the server's write-ahead lands before the reply that vouches
        # for it), the [1, H] boundary carry the resume handshake
        # fingerprints, and the obs replay set since that boundary.
        self.remote_steps = 0
        self.remote_boundary = 0
        self.remote_boundary_carry = None
        self.remote_chunk_obs: list = []


class SelfPlayActor:
    """Drives both sides of a self-play episode through one env session."""

    def __init__(
        self,
        cfg: ActorConfig,
        broker: Broker,
        actor_id: int = 0,
        stub: Optional[AsyncDotaServiceStub] = None,
    ):
        if cfg.opponent not in ("self", "league"):
            raise ValueError(f"SelfPlayActor wants opponent 'self' or 'league', got {cfg.opponent!r}")
        self.cfg = cfg
        self.broker = broker
        self.actor_id = actor_id
        self._stub = stub
        self.params = P.init_params(cfg.policy, jax.random.PRNGKey(cfg.seed))
        self.version = 0
        self.step_fn = make_actor_step(cfg)
        self.rng = jax.random.PRNGKey(cfg.seed * 9973 + actor_id)
        self.np_rng = np.random.RandomState(cfg.seed * 1000003 + actor_id)
        self.steps_done = 0
        self.episodes_done = 0
        self.rollouts_published = 0
        self.rollouts_shed = 0  # publishes refused at admission, chunk dropped
        self.rollouts_failed = 0  # publishes lost to transport failure
        self.last_win: Optional[float] = None  # radiant (live) perspective
        self.last_heroes: list = []  # live side's pool draws, last episode
        self.last_weight_time = time.monotonic()  # kill-switch clock
        # Same cast-at-source wire quantization as Actor (identity under
        # the default --wire.obs_dtype f32).
        wire_cfg = getattr(cfg, "wire", None)
        self._wire_cast = wire_cast_fn(wire_cfg.obs_dtype if wire_cfg is not None else "f32")
        # Fabric priority stamp, same resolution as Actor (None against
        # classic brokers) — without it, self-play chunks would publish
        # at priority 0 and be the FIRST evicted by every shard's
        # priority shed, silently starving the league of its own data.
        from dotaclient_tpu.runtime.actor import rollout_priority_fn

        self._priority_fn = rollout_priority_fn(broker)
        # Same opt-in trace stamping as Actor (runtime/actor.py): None
        # when --obs.enabled is off, and frames stay legacy DTR1.
        from dotaclient_tpu.obs import ObsRuntime

        self.obs = ObsRuntime.create(cfg.obs, role=f"selfplay{actor_id}")
        # Remote league mode (--serve.league <host:port> + --serve.endpoint):
        # the standing league service owns the opponent pool — matches come
        # from GET /match, opponent sessions step the serve tier's resident
        # model slots, and results post back to the rating service. The
        # LOCAL League pool is only built when this mode is off (the two
        # pools must never compete for the same episodes).
        serve_cfg = getattr(cfg, "serve", None)
        self._league_endpoint = ""
        if serve_cfg is not None and getattr(serve_cfg, "endpoint", ""):
            self._league_endpoint = str(getattr(serve_cfg, "league", "") or "")
        self._remote_clients: Dict[tuple, object] = {}
        self._opp_remote = None  # this episode's RemotePolicyClient
        self._opp_model = 0
        self._opp_role = "main"
        self.remote_matches = 0
        self.remote_match_errors = 0
        self.remote_results_posted = 0
        self.remote_result_errors = 0
        self.remote_fallbacks = 0  # episodes degraded to mirror mid-flight
        self.remote_resumes = 0  # opponent sessions restored via the store
        self.remote_replay_steps = 0  # FLAG_REPLAY steps issued on resume
        self.league: Optional[League] = None
        if cfg.opponent == "league" and not self._league_endpoint:
            self.league = League(
                capacity=cfg.league_capacity,
                snapshot_every=cfg.league_snapshot_every,
                mode=cfg.pfsp_mode,
                seed=cfg.seed * 31 + actor_id,
            )
        # frozen opponent params for the current episode (league mode)
        self._opp_params = None
        self._opp_name: Optional[str] = None

    # ------------------------------------------------------------- weights

    def maybe_update_weights(self) -> bool:
        frame = self.broker.poll_weights()
        if frame is None:
            return False
        on_applied = None
        if self.league is not None:
            on_applied = lambda named, version: self.league.maybe_snapshot(version, named)
        return apply_weight_frame(
            self, frame, f"selfplay actor {self.actor_id}", on_applied=on_applied
        )

    # ------------------------------------------------------------- episode

    @property
    def stub(self) -> AsyncDotaServiceStub:
        if self._stub is None:
            self._stub = connect_env_async(self.cfg)
        return self._stub

    def _pick_opponent(self) -> None:
        """League: sample a frozen snapshot (falls back to mirror while the
        pool is empty). Remote league: ask the standing service for a match
        (falls back to mirror when the service is unreachable or the pool
        empty). Mirror: live weights both sides."""
        self._opp_params = None
        self._opp_name = None
        self._opp_remote = None
        self._opp_model = 0
        self._opp_role = "main"
        if self._league_endpoint:
            self._pick_remote_opponent()
            return
        if self.league is None:
            return
        snap: Optional[Snapshot] = self.league.sample_opponent()
        if snap is not None:
            self._opp_params = unflatten_params(snap.named_params, self.params)
            self._opp_name = snap.name

    def _pick_remote_opponent(self) -> None:
        """GET /match off the league service → {model, name, serve, role}.
        Any failure (service down, empty pool) degrades to mirror for this
        episode — a league outage must never stall the env session. Plain
        stdlib HTTP (the /topology precedent): matchmaking is a wire
        contract, not a code dependency."""
        import json as _json
        from urllib.request import urlopen

        try:
            with urlopen(
                f"http://{self._league_endpoint}/match", timeout=2.0
            ) as resp:
                match = _json.loads(resp.read().decode("utf-8", "replace"))
        except Exception:
            self.remote_match_errors += 1
            return
        name = match.get("name")
        if not name:
            return  # empty pool: mirror this episode
        self.remote_matches += 1
        self._opp_name = str(name)
        self._opp_model = int(match.get("model", 0))
        self._opp_role = str(match.get("role", "main"))
        endpoint = str(match.get("serve") or self.cfg.serve.endpoint)
        self._opp_remote = self._remote_client(endpoint, self._opp_model)

    def _remote_client(self, endpoint: str, model: int):
        """One connection per (endpoint, model slot), cached for the
        process lifetime: the model id binds at the S_INFO handshake, so
        different opponents on the same server still need distinct
        sockets. Gated import (the chaos/ckpt precedent)."""
        key = (endpoint, model)
        cli = self._remote_clients.get(key)
        if cli is None:
            from dotaclient_tpu.serve.client import RemotePolicyClient
            from dotaclient_tpu.transport.base import RetryPolicy

            cfg = self.cfg
            cli = RemotePolicyClient(
                endpoint,
                cfg.policy,
                wire_obs_dtype=getattr(getattr(cfg, "wire", None), "obs_dtype", "f32"),
                timeout_s=cfg.serve.timeout_s,
                connect_timeout_s=cfg.serve.connect_timeout_s,
                cooldown_s=cfg.serve.cooldown_s,
                retry=RetryPolicy.from_config(cfg.retry),
                route=cfg.serve.route,
                model=model,
            )
            self._remote_clients[key] = cli
        return cli

    async def _remote_opp_step(self, group: list, episode_start: bool) -> bool:
        """One serve-tier step per opponent hero (concurrent, one socket —
        the server gathers them into its per-model tick batch). With
        `--serve.resume` armed, a replica loss mid-episode re-establishes
        each side's session on the reborn server — store-backed boundary
        restore keyed by (client_key, model_id) plus FLAG_REPLAY of the
        partial chunk, the RemoteActor choreography — before this method
        reports failure. Returns False only on unrecoverable remote
        failure (resume disarmed, refused, or window exhausted): the
        episode then degrades to mirror (a zero-carry mirror finish
        beats abandoning the env session)."""
        from dotaclient_tpu.serve.client import RemoteInferenceError

        cli = self._opp_remote
        resume_armed = bool(getattr(self.cfg.serve, "resume", False))
        rollout_len = max(1, int(self.cfg.rollout_len))

        async def one(s: _Side) -> None:
            # Boundary cadence mirrors the chunk protocol: the carry
            # rides the reply on chunk-fill steps, and the server's
            # write-ahead makes exactly those boundaries restorable.
            want_carry = resume_armed and (s.remote_steps + 1) % rollout_len == 0
            try:
                res = await cli.step(
                    s.remote_key,
                    s.obs,
                    s.remote_rng,
                    episode_start=episode_start,
                    want_carry=want_carry,
                )
            except RemoteInferenceError as e:
                if not resume_armed:
                    raise
                res = await self._resume_opp_side(
                    cli, s, episode_start, want_carry, e
                )
                self.remote_resumes += 1
            if resume_armed:
                s.remote_steps += 1
                if want_carry and res.carry is not None:
                    c, h = res.carry
                    s.remote_boundary = s.remote_steps
                    s.remote_boundary_carry = (
                        np.ascontiguousarray(c, np.float32)[None],
                        np.ascontiguousarray(h, np.float32)[None],
                    )
                    s.remote_chunk_obs = []
                else:
                    s.remote_chunk_obs.append(s.obs)
            s.remote_rng = res.rng
            a = res.action
            action = ad.Action(
                type=np.asarray([a[0]], np.int32),
                move_x=np.asarray([a[1]], np.int32),
                move_y=np.asarray([a[2]], np.int32),
                target=np.asarray([a[3]], np.int32),
            )
            s._step_record = (action, float(res.logp), float(res.value))
            s._action_h, s._batch_index = action, 0

        try:
            await asyncio.gather(*(one(s) for s in group))
            return True
        except (RemoteInferenceError, RuntimeError) as e:
            _log.warning(
                "selfplay actor %d: remote opponent %s lost (%s); finishing "
                "episode as mirror",
                self.actor_id,
                self._opp_name,
                type(e).__name__,
            )
            return False

    async def _resume_opp_side(
        self, cli, s: _Side, episode_start: bool, want_carry: bool, first_err
    ):
        """One opponent side's resume-and-retry (the RemoteActor
        _resume_and_retry choreography, per side): reconnect, S_RESUME
        the boundary carry — the store key composes (client_key,
        model_id) server-side, so sibling slots on the same server never
        cross — replay the buffered partial-chunk obs (outputs
        discarded; the carry update is rng-independent), then re-issue
        the failed step for real. A SessionResumeRefused is
        authoritative (store miss/stale) and propagates — the caller's
        mirror-degrade path takes over; transport failures retry with
        backoff until `--serve.resume_window_s` runs out."""
        from dotaclient_tpu.serve.client import (
            RemoteInferenceError,
            SessionResumeRefused,
        )

        deadline = time.monotonic() + float(self.cfg.serve.resume_window_s)
        backoff = 0.05
        err = first_err
        while True:
            if getattr(cli, "_closed", False):
                raise err  # teardown, not an outage: fail fast
            try:
                if s.remote_boundary > 0:
                    from dotaclient_tpu.serve.handoff import carry_fingerprint

                    fp = carry_fingerprint(
                        s.remote_boundary_carry[0], s.remote_boundary_carry[1]
                    )
                    await cli.resume(s.remote_key, s.remote_boundary, fp)
                for i, o in enumerate(s.remote_chunk_obs):
                    await cli.step(
                        s.remote_key,
                        o,
                        s.remote_rng,
                        episode_start=(s.remote_boundary == 0 and i == 0),
                        replay=True,
                    )
                    self.remote_replay_steps += 1
                res = await cli.step(
                    s.remote_key,
                    s.obs,
                    s.remote_rng,
                    episode_start=episode_start,
                    want_carry=want_carry,
                )
            except SessionResumeRefused:
                raise
            except RemoteInferenceError as e:
                err = e
                now = time.monotonic()
                if now >= deadline:
                    raise err
                await asyncio.sleep(min(backoff, max(0.0, deadline - now)))
                backoff = min(backoff * 2.0, 1.0)
                continue
            _log.info(
                "selfplay actor %d: opponent %s session %d RESUMED at "
                "boundary %d (+%d replayed steps)",
                self.actor_id,
                self._opp_name,
                s.remote_key,
                s.remote_boundary,
                len(s.remote_chunk_obs),
            )
            return res

    def _post_result(self) -> None:
        """POST the finished match to the league rating service. The live
        side is the canonical AGENT name (eval/league.py); failure only
        counts — ratings tolerate a lost game, the env session must not."""
        import json as _json
        from urllib.request import Request, urlopen

        win = self.last_win
        if win is None or self._opp_name is None:
            return
        body = {"winner": "agent", "loser": self._opp_name, "draw": win == 0.0}
        if win < 0:
            body["winner"], body["loser"] = body["loser"], body["winner"]
        try:
            req = Request(
                f"http://{self._league_endpoint}/result",
                data=_json.dumps(body).encode("utf-8"),
                headers={"Content-Type": "application/json"},
                method="POST",
            )
            with urlopen(req, timeout=2.0) as resp:
                resp.read()
            self.remote_results_posted += 1
        except Exception:
            self.remote_result_errors += 1

    def _publish(self, side: _Side, win: float, done: bool) -> None:
        rollout = side.chunk.to_rollout(
            side.obs,
            self.version,
            self.actor_id,
            side.episode_return if done else 0.0,
            win,
            self.cfg.policy.aux_heads,
        )
        if self.obs is not None:
            rollout = self.obs.stamp(rollout, self.actor_id)
        try:
            data = serialize_rollout(self._wire_cast(rollout))
            if self._priority_fn is not None:
                self.broker.publish_experience_prioritized(
                    data, self._priority_fn(rollout)
                )
            else:
                self.broker.publish_experience(data)
            self.rollouts_published += 1
        except BrokerShedError:
            # Admission refusal: drop the chunk and continue the episode.
            # _publish is sync (called mid-tick for whichever side's
            # chunk filled), so the jittered backoff the scripted fleet
            # awaits (runtime/actor.py ShedThrottle) can't be paid here
            # without stalling BOTH sides' env session; the shed itself
            # is already the broker protecting itself, and self-play
            # actors are a tiny minority of the publish load.
            self.rollouts_shed += 1
        except (ConnectionError, OSError) as e:
            _log.warning(
                "selfplay actor %d: publish failed (%s); dropping chunk",
                self.actor_id,
                type(e).__name__,
            )
            # NOT rollouts_shed: a transport failure is no admission
            # refusal, and the conservation ledger's shed cross-check
            # (publish_stats "shed" vs broker refusals) must not see one.
            self.rollouts_failed += 1
        side.state, side.chunk = next_chunk(self.cfg.policy, side.state)

    def _batched_step(self, params, group: list) -> None:
        """ONE jit call for a group of sides (B = len(group)) — this is
        the TPU-first scaling story for team play: 5v5 mirror is a single
        B=10 policy step per tick, not ten B=1 steps. The rng carry
        (self.rng) advances inside the compiled step."""
        obs_b = jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x) for x in xs]), *[s.obs for s in group])
        state_b = jax.tree.map(lambda *xs: jnp.concatenate(xs), *[s.state for s in group])
        state_b, action_b, logp_b, value_b, self.rng = self.step_fn(params, state_b, obs_b, self.rng)
        action_h = jax.device_get(action_b)
        logp_h = jax.device_get(logp_b)
        value_h = jax.device_get(value_b)
        for i, s in enumerate(group):
            s.state = jax.tree.map(lambda x: x[i : i + 1], state_b)
            s._step_record = (_slice_action(action_h, i), float(logp_h[i]), float(value_h[i]))
            s._action_h, s._batch_index = action_h, i

    async def run_episode(self) -> float:
        cfg = self.cfg
        self.last_win = None
        self._pick_opponent()
        # also league-mode fallback (empty pool / service unreachable)
        mirror = self._opp_params is None and self._opp_remote is None
        pool = heroes.parse_pool(cfg.hero)
        n = max(1, min(int(getattr(cfg, "team_size", 1)), 5))
        rad_pids = [RADIANT_PLAYER + i for i in range(n)]
        dire_pids = [DIRE_PLAYER + i for i in range(n)]
        config = ds.GameConfig(
            host_timescale=cfg.host_timescale,
            ticks_per_observation=cfg.ticks_per_observation,
            max_dota_time=cfg.max_dota_time,
            seed=self.np_rng.randint(1 << 30),
            hero_picks=[
                ds.HeroPick(
                    team_id=team,
                    hero_name=pool[self.np_rng.randint(len(pool))],
                    control_mode=1,
                )
                for team in (TEAM_RADIANT, TEAM_DIRE)
                for _ in range(n)
            ],
        )
        resp = await self.stub.reset(config)
        # Telemetry: which pool heroes the LIVE side drew this episode
        # (hero-pool runs attribute per-hero returns — BASELINE config 3).
        self.last_heroes = [
            p.hero_name for p in config.hero_picks if p.team_id == TEAM_RADIANT
        ]
        sides: Dict[int, _Side] = {}
        for pid in rad_pids:
            sides[pid] = _Side(pid, TEAM_RADIANT, cfg)
        for pid in dire_pids:
            sides[pid] = _Side(pid, TEAM_DIRE, cfg)
        live_team = [sides[p] for p in rad_pids]
        opp_team = [sides[p] for p in dire_pids]
        live = live_team[0]  # reporting anchor (return/win bookkeeping)
        rad_world = resp.world_state
        dire_world = (await self.stub.observe(ds.ObserveRequest(team_id=TEAM_DIRE))).world_state
        for s in sides.values():
            s.world = rad_world if s.team_id == TEAM_RADIANT else dire_world
            s.obs, s.handles = F.featurize_with_handles(s.world, s.player_id)
        if self._opp_remote is not None:
            # Serve-tier sessions for the opponent heroes: client_key is
            # (actor, player) — stable across the fleet — and the model id
            # composes in server-side (compose_store_key), so per-opponent
            # resume state never collides across slots.
            for s in opp_team:
                s.remote_key = self.actor_id * 100 + s.player_id
                s.remote_rng = np.asarray(
                    self.np_rng.randint(0, 1 << 31, size=2), np.uint32
                )

        done = False
        first_tick = True
        while not done:
            if mirror:
                # every controlled hero, both teams, one compiled call
                self._batched_step(self.params, live_team + opp_team)
            elif self._opp_remote is not None:
                self._batched_step(self.params, live_team)
                ok = await self._remote_opp_step(opp_team, episode_start=first_tick)
                if not ok:
                    # Degrade: the rest of the episode is a mirror for the
                    # opponent team (zero-ish carry restart from whatever
                    # local state the sides hold — a quality dip, not an
                    # abandon). Result will NOT post (_opp_name cleared):
                    # a half-remote game must not move ratings.
                    self.remote_fallbacks += 1
                    self._opp_remote = None
                    self._opp_name = None
                    self._batched_step(self.params, opp_team)
            else:
                self._batched_step(self.params, live_team)
                self._batched_step(self._opp_params, opp_team)
            first_tick = False

            actions: Dict[int, ds.Action] = {}
            for s in sides.values():
                hero = F.find_hero(s.world, s.player_id)
                if hero is not None:
                    snap = ws.Unit()
                    snap.CopyFrom(hero)
                    s.last_hero = snap
                actions[s.player_id] = build_action(
                    cfg, s._action_h, s.handles, hero, s.player_id, batch_index=s._batch_index
                )

            # one act() per team, team_id set: a real dotaservice routes
            # orders per team — mixing both teams in one call only happens
            # to work against the fake env (which keys on player_id)
            await self.stub.act(
                ds.Actions(
                    actions=[actions[p] for p in rad_pids],
                    dota_time=live.world.dota_time,
                    team_id=TEAM_RADIANT,
                )
            )
            await self.stub.act(
                ds.Actions(
                    actions=[actions[p] for p in dire_pids],
                    dota_time=live.world.dota_time,
                    team_id=TEAM_DIRE,
                )
            )
            r2 = await self.stub.observe(ds.ObserveRequest(team_id=TEAM_RADIANT))
            if r2.status == ds.Observation.RESOURCE_EXHAUSTED:
                _log.warning("selfplay actor %d: env session lost; abandoning", self.actor_id)
                self.episodes_done += 1
                return live.episode_return
            r3 = await self.stub.observe(ds.ObserveRequest(team_id=TEAM_DIRE))
            done = r2.status == ds.Observation.EPISODE_DONE

            for s in sides.values():
                next_world = (r2 if s.team_id == TEAM_RADIANT else r3).world_state
                next_obs, next_handles = F.featurize_with_handles(next_world, s.player_id)
                rew = R.reward(s.world, next_world, s.player_id, s.last_hero)
                s.episode_return += rew
                action_rec, logp_rec, value_rec = s._step_record
                hero = F.find_hero(s.world, s.player_id)
                s.chunk.obs.append(s.obs)
                s.chunk.actions.append(action_rec)
                s.chunk.logp.append(logp_rec)
                s.chunk.value.append(value_rec)
                s.chunk.rewards.append(rew)
                s.chunk.dones.append(1.0 if done else 0.0)
                if cfg.policy.aux_heads:
                    s.chunk.aux_lh.append(F.norm_last_hits(hero.last_hits) if hero else 0.0)
                    s.chunk.aux_nw.append(F.norm_gold(hero.gold) if hero else 0.0)
                s.world = next_world
                s.obs, s.handles = next_obs, next_handles
                self.steps_done += 1

            if len(live.chunk) >= cfg.rollout_len or done:
                winning = live.world.winning_team
                for s in sides.values():
                    win = 0.0
                    if done and winning:
                        win = 1.0 if winning == s.team_id else -1.0
                    # mirror publishes every hero (2n trajectories/chunk
                    # window); league publishes only the live team's n —
                    # the frozen opponent yields no data
                    publish = s.team_id == TEAM_RADIANT or mirror
                    if publish:
                        self._publish(s, win, done)
                    else:
                        s.state, s.chunk = next_chunk(cfg.policy, s.state)
                    if s is live and done:
                        self.last_win = win
                self.maybe_update_weights()

        if self.league is not None and self._opp_name is not None and self.last_win is not None:
            self.league.record_result(self._opp_name, self.last_win)
        elif self._league_endpoint and self._opp_name is not None:
            self._post_result()
        self.episodes_done += 1
        return live.episode_return

    async def run(self, num_episodes: Optional[int] = None) -> None:
        backoff = 1.0
        while num_episodes is None or self.episodes_done < num_episodes:
            check_weight_freshness(self)  # same kill switch as Actor
            try:
                ret = await self.run_episode()
                backoff = 1.0
            except grpc.aio.AioRpcError as e:
                _log.warning(
                    "selfplay actor %d: env rpc failed (%s); retrying in %.1fs",
                    self.actor_id,
                    e.code(),
                    backoff,
                )
                await reset_env_stub(self)  # drop the dead subchannel
                self.maybe_update_weights()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2.0, 30.0)
                continue
            _log.info(
                "selfplay actor %d: episode %d return %.2f (version %d, opp %s)",
                self.actor_id,
                self.episodes_done,
                ret,
                self.version,
                self._opp_name or "mirror",
            )
