"""Self-play actor: both heroes of a 1v1 game driven by one process.

The reference's self-play opponent is the latest (or lagged) copy of the
learner's weights (SURVEY.md §2 "Eval / rating", BASELINE configs 3/5);
here one asyncio process controls both player_ids of a single env
session, which keeps the two sides in lockstep without any cross-process
game synchronization:

- **mirror** (`opponent="self"`): both sides play the live weights and
  BOTH publish experience — every game yields 2× trajectories, and the
  policy sees both the radiant and dire views of the same states (the
  team-indicator feature differs, so one shared LSTM learns both sides —
  exactly the "shared LSTM self-play" of BASELINE config 3).
- **league** (`opponent="league"`): the dire side plays a frozen PFSP
  snapshot from the local league pool (eval/league.py); only the live
  (radiant) side publishes experience. Snapshots are taken from the
  weight broadcasts the actor receives anyway — no extra transport.

TPU-first detail: in mirror mode the two sides' observations are stacked
into ONE batched jit call per tick (B=2) — the policy step is a single
compiled program either way; batching players is how 5v5 scales too.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import Dict, Optional

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import ActorConfig
from dotaclient_tpu.env import featurizer as F
from dotaclient_tpu.env import heroes
from dotaclient_tpu.env import rewards as R
from dotaclient_tpu.env.service import AsyncDotaServiceStub, connect_async
from dotaclient_tpu.eval.league import League, Snapshot
from dotaclient_tpu.models import policy as P
from dotaclient_tpu.ops import action_dist as ad
from dotaclient_tpu.protos import dotaservice_pb2 as ds
from dotaclient_tpu.protos import worldstate_pb2 as ws
from dotaclient_tpu.runtime.actor import (
    _Chunk,
    build_action,
    check_weight_freshness,
    make_actor_step,
    reset_env_stub,
)
from dotaclient_tpu.transport.base import Broker
from dotaclient_tpu.transport.serialize import (
    deserialize_weights,
    serialize_rollout,
    unflatten_params,
)

_log = logging.getLogger(__name__)

RADIANT_PLAYER, DIRE_PLAYER = 0, 5
TEAM_RADIANT, TEAM_DIRE = 2, 3


def _slice_action(action: ad.Action, i: int) -> ad.Action:
    """Row i of a batched Action, kept as a length-1 batch (chunk format)."""
    return ad.Action(
        type=action.type[i : i + 1],
        move_x=action.move_x[i : i + 1],
        move_y=action.move_y[i : i + 1],
        target=action.target[i : i + 1],
    )


class _Side:
    """Per-player episode state (view, LSTM carry, chunk, reward memory)."""

    def __init__(self, player_id: int, team_id: int, cfg: ActorConfig):
        self.player_id = player_id
        self.team_id = team_id
        self.state = P.initial_state(cfg.policy, (1,))
        self.chunk = _Chunk(self.state)
        self.world: Optional[ws.World] = None
        self.obs: Optional[F.Observation] = None
        self.handles: Optional[np.ndarray] = None
        self.last_hero: Optional[ws.Unit] = None
        self.episode_return = 0.0


class SelfPlayActor:
    """Drives both sides of a self-play episode through one env session."""

    def __init__(
        self,
        cfg: ActorConfig,
        broker: Broker,
        actor_id: int = 0,
        stub: Optional[AsyncDotaServiceStub] = None,
    ):
        if cfg.opponent not in ("self", "league"):
            raise ValueError(f"SelfPlayActor wants opponent 'self' or 'league', got {cfg.opponent!r}")
        self.cfg = cfg
        self.broker = broker
        self.actor_id = actor_id
        self._stub = stub
        self.params = P.init_params(cfg.policy, jax.random.PRNGKey(cfg.seed))
        self.version = 0
        self.step_fn = make_actor_step(cfg)
        self.rng = jax.random.PRNGKey(cfg.seed * 9973 + actor_id)
        self.np_rng = np.random.RandomState(cfg.seed * 1000003 + actor_id)
        self.steps_done = 0
        self.episodes_done = 0
        self.rollouts_published = 0
        self.last_win: Optional[float] = None  # radiant (live) perspective
        self.last_weight_time = time.monotonic()  # kill-switch clock
        self.league: Optional[League] = None
        if cfg.opponent == "league":
            self.league = League(
                capacity=cfg.league_capacity,
                snapshot_every=cfg.league_snapshot_every,
                mode=cfg.pfsp_mode,
                seed=cfg.seed * 31 + actor_id,
            )
        # frozen opponent params for the current episode (league mode)
        self._opp_params = None
        self._opp_name: Optional[str] = None

    # ------------------------------------------------------------- weights

    def maybe_update_weights(self) -> bool:
        frame = self.broker.poll_weights()
        if frame is None:
            return False
        try:
            named, version = deserialize_weights(frame)
            self.params = unflatten_params(named, self.params)
            self.version = version
            self.last_weight_time = time.monotonic()
            if self.league is not None:
                self.league.maybe_snapshot(version, named)
            return True
        except Exception as e:  # a bad broadcast must never kill the actor
            _log.warning("selfplay actor %d: bad weight frame: %s", self.actor_id, e)
            return False

    # ------------------------------------------------------------- episode

    @property
    def stub(self) -> AsyncDotaServiceStub:
        if self._stub is None:
            if getattr(self.cfg, "env_dialect", "internal") == "valve":
                from dotaclient_tpu.env.valve_adapter import connect_valve_async

                self._stub = connect_valve_async(self.cfg.env_addr)
            else:
                self._stub = connect_async(self.cfg.env_addr)
        return self._stub

    def _pick_opponent(self) -> None:
        """League: sample a frozen snapshot (falls back to mirror while the
        pool is empty). Mirror: live weights both sides."""
        self._opp_params = None
        self._opp_name = None
        if self.league is None:
            return
        snap: Optional[Snapshot] = self.league.sample_opponent()
        if snap is not None:
            self._opp_params = unflatten_params(snap.named_params, self.params)
            self._opp_name = snap.name

    def _publish(self, side: _Side, win: float, done: bool) -> None:
        rollout = side.chunk.to_rollout(
            side.obs,
            self.version,
            self.actor_id,
            side.episode_return if done else 0.0,
            win,
            self.cfg.policy.aux_heads,
        )
        self.broker.publish_experience(serialize_rollout(rollout))
        self.rollouts_published += 1
        side.chunk = _Chunk(side.state)

    async def run_episode(self) -> float:
        cfg = self.cfg
        self.last_win = None
        self._pick_opponent()
        mirror = self._opp_params is None  # also league-mode fallback
        pool = heroes.parse_pool(cfg.hero)
        config = ds.GameConfig(
            host_timescale=cfg.host_timescale,
            ticks_per_observation=cfg.ticks_per_observation,
            max_dota_time=cfg.max_dota_time,
            seed=self.np_rng.randint(1 << 30),
            hero_picks=[
                ds.HeroPick(
                    team_id=TEAM_RADIANT,
                    hero_name=pool[self.np_rng.randint(len(pool))],
                    control_mode=1,
                ),
                ds.HeroPick(
                    team_id=TEAM_DIRE,
                    hero_name=pool[self.np_rng.randint(len(pool))],
                    control_mode=1,
                ),
            ],
        )
        resp = await self.stub.reset(config)
        sides: Dict[int, _Side] = {
            RADIANT_PLAYER: _Side(RADIANT_PLAYER, TEAM_RADIANT, cfg),
            DIRE_PLAYER: _Side(DIRE_PLAYER, TEAM_DIRE, cfg),
        }
        live, opp = sides[RADIANT_PLAYER], sides[DIRE_PLAYER]
        live.world = resp.world_state
        opp.world = (await self.stub.observe(ds.ObserveRequest(team_id=TEAM_DIRE))).world_state
        for s in sides.values():
            s.obs, s.handles = F.featurize_with_handles(s.world, s.player_id)

        done = False
        while not done:
            actions: Dict[int, ds.Action] = {}
            if mirror:
                # one batched policy step for both sides
                obs_b = jax.tree.map(
                    lambda a, b: jnp.stack([jnp.asarray(a), jnp.asarray(b)]),
                    live.obs,
                    opp.obs,
                )
                state_b = jax.tree.map(lambda a, b: jnp.concatenate([a, b]), live.state, opp.state)
                self.rng, key = jax.random.split(self.rng)
                state_b, action_b, logp_b, value_b = self.step_fn(self.params, state_b, obs_b, key)
                action_h = jax.device_get(action_b)
                logp_h = jax.device_get(logp_b)
                value_h = jax.device_get(value_b)
                for i, s in enumerate((live, opp)):
                    s.state = jax.tree.map(lambda x: x[i : i + 1], state_b)
                    hero = F.find_hero(s.world, s.player_id)
                    actions[s.player_id] = build_action(
                        cfg, action_h, s.handles, hero, s.player_id, batch_index=i
                    )
                    s._step_record = (_slice_action(action_h, i), float(logp_h[i]), float(value_h[i]))
            else:
                for s, params in ((live, self.params), (opp, self._opp_params)):
                    obs_b = jax.tree.map(lambda x: jnp.asarray(x)[None], s.obs)
                    self.rng, key = jax.random.split(self.rng)
                    s.state, action, logp, value = self.step_fn(params, s.state, obs_b, key)
                    action_h = jax.device_get(action)
                    hero = F.find_hero(s.world, s.player_id)
                    actions[s.player_id] = build_action(cfg, action_h, s.handles, hero, s.player_id)
                    s._step_record = (action_h, float(logp[0]), float(value[0]))

            for s in sides.values():
                hero = F.find_hero(s.world, s.player_id)
                if hero is not None:
                    snap = ws.Unit()
                    snap.CopyFrom(hero)
                    s.last_hero = snap

            await self.stub.act(
                ds.Actions(
                    actions=[actions[RADIANT_PLAYER], actions[DIRE_PLAYER]],
                    dota_time=live.world.dota_time,
                )
            )
            r2 = await self.stub.observe(ds.ObserveRequest(team_id=TEAM_RADIANT))
            if r2.status == ds.Observation.RESOURCE_EXHAUSTED:
                _log.warning("selfplay actor %d: env session lost; abandoning", self.actor_id)
                self.episodes_done += 1
                return live.episode_return
            r3 = await self.stub.observe(ds.ObserveRequest(team_id=TEAM_DIRE))
            done = r2.status == ds.Observation.EPISODE_DONE

            for s, resp_s in ((live, r2), (opp, r3)):
                next_world = resp_s.world_state
                next_obs, next_handles = F.featurize_with_handles(next_world, s.player_id)
                rew = R.reward(s.world, next_world, s.player_id, s.last_hero)
                s.episode_return += rew
                action_rec, logp_rec, value_rec = s._step_record
                hero = F.find_hero(s.world, s.player_id)
                s.chunk.obs.append(s.obs)
                s.chunk.actions.append(action_rec)
                s.chunk.logp.append(logp_rec)
                s.chunk.value.append(value_rec)
                s.chunk.rewards.append(rew)
                s.chunk.dones.append(1.0 if done else 0.0)
                if cfg.policy.aux_heads:
                    s.chunk.aux_lh.append(F.norm_last_hits(hero.last_hits) if hero else 0.0)
                    s.chunk.aux_nw.append(F.norm_gold(hero.gold) if hero else 0.0)
                s.world = next_world
                s.obs, s.handles = next_obs, next_handles
                self.steps_done += 1

            if len(live.chunk) >= cfg.rollout_len or done:
                winning = live.world.winning_team
                for s in sides.values():
                    win = 0.0
                    if done and winning:
                        win = 1.0 if winning == s.team_id else -1.0
                    publish = s is live or mirror  # frozen opponent: no data
                    if publish:
                        self._publish(s, win, done)
                    else:
                        s.chunk = _Chunk(s.state)
                    if s is live and done:
                        self.last_win = win
                self.maybe_update_weights()

        if self.league is not None and self._opp_name is not None and self.last_win is not None:
            self.league.record_result(self._opp_name, self.last_win)
        self.episodes_done += 1
        return live.episode_return

    async def run(self, num_episodes: Optional[int] = None) -> None:
        backoff = 1.0
        while num_episodes is None or self.episodes_done < num_episodes:
            check_weight_freshness(self)  # same kill switch as Actor
            try:
                ret = await self.run_episode()
                backoff = 1.0
            except grpc.aio.AioRpcError as e:
                _log.warning(
                    "selfplay actor %d: env rpc failed (%s); retrying in %.1fs",
                    self.actor_id,
                    e.code(),
                    backoff,
                )
                await reset_env_stub(self)  # drop the dead subchannel
                self.maybe_update_weights()
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2.0, 30.0)
                continue
            _log.info(
                "selfplay actor %d: episode %d return %.2f (version %d, opp %s)",
                self.actor_id,
                self.episodes_done,
                ret,
                self.version,
                self._opp_name or "mirror",
            )
