"""In-process closed-loop harness: N actor threads, each with a private
asyncio loop, around one learner.

Every local driver (scripts/train_north_star.py, train_league.py,
train_hero_pool.py, ab_ppo_reuse.py, ab_cast.py) and the learning smokes
(tests/test_learning.py) run the same shape: spawn N daemon threads,
each building one actor and looping run_episode until a stop event,
with its own event loop (actors are asyncio; threads may not share
loops), then join with a bounded timeout so a wedged episode can't hang
teardown. That scaffold used to be copy-pasted per driver — five
drifting copies of the one piece where a fix MUST propagate (r4 review
finding). This is the single copy.

The parts that legitimately differ per driver — configs, which Actor
class, what to record per episode — stay in the drivers: `make_actor(i)`
builds the actor, `on_episode(i, actor, ret)` observes each completed
episode (called from the actor's thread; synchronize your own state).
"""

from __future__ import annotations

import asyncio
import logging
import threading
from typing import Callable, List, Optional

_log = logging.getLogger(__name__)


class ActorPool:
    """N actor threads looping run_episode() until stop().

    `make_actor(i) -> actor` runs INSIDE thread i (actors build jit
    closures; building them on the owning thread keeps any thread-local
    state sane). Actors are appended to `self.actors` as they come up.
    A crashed actor thread logs its traceback and exits — the pool
    never silently swallows a death (`dead` counts them for drivers
    that want to fail loudly).
    """

    def __init__(
        self,
        make_actor: Callable[[int], object],
        n_actors: int,
        on_episode: Optional[Callable[[int, object, float], None]] = None,
        envs_per_actor: Optional[int] = None,
    ):
        self._make_actor = make_actor
        self._on_episode = on_episode
        # Vectorized fleet mode (runtime/actor.py VectorActor): when the
        # built actor's cfg carries envs_per_process > 1 (or the driver
        # passes envs_per_actor explicitly), each worker thread wraps its
        # classic Actor into a VectorActor driving that many envs through
        # one batched jit call per tick — every existing driver inherits
        # batching from the --envs_per_process flag with no code change.
        self._envs_per_actor = envs_per_actor
        self._stop = threading.Event()
        self.actors: List[object] = []
        # `dead` is incremented from N worker threads — a bare += is a
        # read-modify-write that loses updates when two actors die in the
        # same tick, so the counter is lock-guarded on both sides.
        self._lock = threading.Lock()
        self.dead = 0
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True, name=f"actor-{i}")
            for i in range(n_actors)
        ]

    def _maybe_vectorize(self, actor):
        """Wrap a classic Actor into a VectorActor when envs-per-actor is
        in play. Exact-type check: SelfPlayActor (not an Actor subclass)
        already batches its own heroes, and a VectorActor / env worker
        must never be double-wrapped."""
        M = self._envs_per_actor
        if M is None:
            M = int(getattr(getattr(actor, "cfg", None), "envs_per_process", 1) or 1)
        if getattr(actor, "remote_policy", None) is not None:
            # Serve-tier actor (dotaclient_tpu/serve/client.py): the
            # SERVER batches, so local VectorActor wrapping would be a
            # second (pointless) batching layer. RemoteFleet drives M
            # env slots over the shared connection — and even at M=1 it
            # supplies the episode-retry loop a bare run_episode worker
            # lacks (a server blip must not count as a dead actor).
            from dotaclient_tpu.serve.client import RemoteFleet

            return RemoteFleet.from_actor(actor, envs=max(M, 1))
        if M <= 1:
            return actor
        from dotaclient_tpu.runtime.actor import Actor, VectorActor

        if type(actor) is not Actor:
            _log.warning(
                "envs_per_actor=%d ignored for %s (only the scripted Actor batches across envs)",
                M,
                type(actor).__name__,
            )
            return actor
        return VectorActor.from_actor(actor, envs=M)

    def _run(self, i: int) -> None:
        loop = asyncio.new_event_loop()
        try:
            actor = self._maybe_vectorize(self._make_actor(i))
            self.actors.append(actor)

            async def go():
                if hasattr(actor, "episode_stream"):
                    # VectorActor: episodes complete per-env inside one
                    # process; the stream yields each as it lands.
                    async for ret in actor.episode_stream():
                        if self._on_episode is not None:
                            self._on_episode(i, actor, float(ret))
                        if self._stop.is_set():
                            return
                    return
                while not self._stop.is_set():
                    ret = await actor.run_episode()
                    if self._on_episode is not None:
                        self._on_episode(i, actor, float(ret))

            loop.run_until_complete(go())
        except Exception:
            with self._lock:
                self.dead += 1
            _log.exception("actor thread %d died", i)
        finally:
            loop.close()

    def start(self) -> "ActorPool":
        for t in self._threads:
            t.start()
        return self

    def publish_stats(self) -> dict:
        """Fleet-aggregated publish/degradation counters — what the
        chaos soak's conservation ledger reads from the producer side.
        `actors` is appended by worker threads; the list() is one
        GIL-atomic snapshot and counters may trail by an in-flight
        publish, which a ledger read after stop() never observes."""
        published = shed = failed = 0
        for a in list(self.actors):  # graftlint: disable=THR001(one GIL-atomic list-snapshot; exact after stop() joined the workers)
            published += int(getattr(a, "rollouts_published", 0))
            shed += int(getattr(a, "rollouts_shed", 0))
            failed += int(getattr(a, "rollouts_failed", 0))
        with self._lock:
            dead = self.dead
        return {
            "published": published,
            "shed": shed,
            "failed": failed,
            "dead_actors": dead,
        }

    def stop(self, timeout: float = 30.0, raise_on_dead: bool = False) -> None:
        """Signal and join with a bounded per-thread timeout — a wedged
        episode must not hang driver teardown (threads are daemons).

        `raise_on_dead=True`: fail loudly if any actor thread died — for
        drivers whose RESULTS would silently degrade with fewer actors
        (A/B arms, artifact generators). Leave False only where the
        caller folds `pool.dead` into its own success bar."""
        self._stop.set()
        for t in self._threads:
            t.join(timeout=timeout)
        with self._lock:
            dead = self.dead
        if raise_on_dead and dead:
            raise RuntimeError(
                f"{dead} actor thread(s) died during the run "
                f"(tracebacks in the log) — results would be degraded"
            )
