"""Asyncio actor loop — the re-design of the reference's agent.py
(SURVEY.md §2 "Actor loop", §3.1 call stack).

Per-step hot loop, exactly the reference's shape: observe() over gRPC →
featurize → policy step with carried LSTM state → mask/sample →
act() over gRPC → shaped reward from worldstate deltas → append to the
rollout chunk; every `rollout_len` steps (or at episode end) the chunk
ships to the broker with the chunk-start LSTM state and the model
version; fresh weights hot-swap in from the weight fanout at chunk
boundaries.

TPU-first differences from the reference:
- inference is ONE jit-compiled function (featurized obs + LSTM state +
  rng → action ints, log-prob, value, new state) — sampling happens
  inside jit so no logits ever cross the host boundary;
- the actor initializes params deterministically from the same seed as
  the learner, so it can act from step zero without waiting for the
  first weight broadcast (the reference downloads a pretrained
  state_dict or waits);
- rollouts go out in the pickle-free wire format (transport/serialize).

Vectorized fleet mode (`--envs_per_process M`, the SEED RL / Sample
Factory inference-server move): one process drives M env sessions on a
single asyncio loop. Each env runs the SAME episode loop as the classic
actor, but its per-tick policy step is submitted to a shared
`InferenceBatcher` that gathers up to M requests (bounded by
`--gather_window_s` so one slow observe() can't stall the batch), pads
partial batches to capacity, and runs ONE jit call per tick — the
batch-1 dispatch overhead that dominates the classic path amortizes
across all M envs. Per-env rng streams and a lax.map row layout keep
the batched step bit-identical to stepping each env alone
(tests/test_actor_fleet.py); scripts/bench_actors.py measures the
offered-rate curve into ACTOR_FLEET.json.
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Tuple

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import ActorConfig
from dotaclient_tpu.env import featurizer as F
from dotaclient_tpu.env import heroes
from dotaclient_tpu.env import rewards as R
from dotaclient_tpu.env.service import AsyncDotaServiceStub, connect_async
from dotaclient_tpu.models import policy as P
from dotaclient_tpu.ops import action_dist as ad
from dotaclient_tpu.protos import dotaservice_pb2 as ds
from dotaclient_tpu.protos import worldstate_pb2 as ws
from dotaclient_tpu.transport.base import Broker, BrokerShedError, RetryPolicy
from dotaclient_tpu.transport.serialize import (
    Rollout,
    RolloutAux,
    deserialize_weights,
    serialize_rollout,
    unflatten_params,
    wire_cast_fn,
)

_log = logging.getLogger(__name__)


class StaleWeightsError(RuntimeError):
    """Raised by the actor kill switch: no weight broadcast arrived for
    longer than `max_weight_age_s`. The actor exits non-zero so its
    supervisor (k8s) replaces it with a fresh pod that re-subscribes —
    on-policy data from an ancient policy is worse than none
    (SURVEY.md §5 "stale-version kill switch")."""


def apply_weight_frame(agent, frame: bytes, log_name: str, on_applied=None) -> bool:
    """Shared weight hot-swap for Actor / SelfPlayActor / Evaluator.

    - malformed frames are logged and ignored (a bad broadcast must
      never kill a subscriber);
    - within one learner boot (same frame boot_epoch), frames OLDER than
      what the agent runs are rejected — a publish that sat blocked
      through a broker outage must not regress weights;
    - a boot_epoch CHANGE is the deterministic learner-restart signal
      (the epoch is drawn once at learner boot and stamped into every
      DTW2 frame): the agent resyncs to the new boot's version
      unconditionally, even if lower. This replaced the r3
      consecutive-older-frames counter, whose threshold a jittery broker
      at publish_every=1 could reach with merely-delayed frames
      (VERDICT r3 weak item 5). Worst case under the epoch scheme: ONE
      delayed frame from a dead previous boot swaps in once, and the
      next live broadcast (epoch differs again) swaps it right back;
    - `on_applied(named_params, version)` runs after a successful swap
      (league snapshotting hook).
    """
    try:
        named, version, boot_epoch = deserialize_weights(frame)
    except Exception as e:  # truncated frames raise struct.error etc.
        _log.warning("%s: bad weight frame: %s", log_name, e)
        return False
    last_epoch = getattr(agent, "weight_epoch", None)
    if last_epoch is not None and boot_epoch != last_epoch:
        _log.warning(
            "%s: weight boot_epoch %d -> %d — learner restarted, resyncing to v%d",
            log_name,
            last_epoch,
            boot_epoch,
            version,
        )
    elif version < agent.version:
        _log.warning(
            "%s: ignoring stale weight frame v%d (< v%d, same boot)",
            log_name,
            version,
            agent.version,
        )
        return False
    try:
        # a frame that deserializes but doesn't match the agent's param
        # template (learner restarted with a different PolicyConfig)
        # must ALSO never kill the subscriber
        agent.params = unflatten_params(named, agent.params)
    except Exception as e:
        _log.warning("%s: weight frame does not fit params (%s); ignoring", log_name, e)
        return False
    agent.version = version
    agent.weight_epoch = boot_epoch
    agent.last_weight_time = time.monotonic()
    if on_applied is not None:
        on_applied(named, version)
    return True


def check_weight_freshness(actor) -> None:
    """Shared kill-switch check for Actor and SelfPlayActor (both carry
    cfg.max_weight_age_s and last_weight_time)."""
    age = time.monotonic() - actor.last_weight_time
    if 0 < actor.cfg.max_weight_age_s < age:
        raise StaleWeightsError(
            f"actor {actor.actor_id}: no weight update for {age:.0f}s "
            f"(limit {actor.cfg.max_weight_age_s:.0f}s) — exiting for restart"
        )


class ShedThrottle:
    """Adaptive publish throttle: honor broker admission control
    (BrokerShedError — transport/tcp.py watermarks) and survive transient
    broker failures with jittered exponential backoff instead of either
    crashing the actor or hammering an overloaded broker in lockstep
    with 255 siblings.

    Policy on refusal/failure: the CHUNK IS DROPPED, not queued for
    retry — by the time an overloaded broker would accept it the chunk
    is staler (and the learner's staleness filter or the drop-oldest
    eviction would eat it anyway); what matters is that the PRODUCER
    slows down, which the backoff does. Backoff resets on the first
    accepted publish. One instance per publishing agent; counters feed
    the broker_shed_* scalars (obs/registry.py).

    Backoff state is PER ENDPOINT (the broker-fabric surgery): against
    a routing broker (one exposing `route_endpoint`, transport/fabric),
    a shed/failure arms a not-before stamp for THAT shard only, paid
    just before the next publish that routes there — so one shedding
    shard never pauses publishes to healthy shards (regression-pinned
    in tests/test_fabric.py with two in-process brokers). Against a
    classic single broker there is no routing key: the one shared
    ladder pays its backoff immediately, byte-for-byte the pre-fabric
    behavior.
    """

    def __init__(self, retry: Optional[RetryPolicy] = None):
        self.retry = retry if retry is not None else RetryPolicy()
        # endpoint key (None = the classic unrouted broker) → ladder
        # position / earliest next publish to that endpoint.
        self._backoff: dict = {}
        self._not_before: dict = {}
        self.published = 0
        self.shed = 0
        self.failed = 0
        self.throttle_s = 0.0

    def _endpoint_key(self, broker: Broker, data: bytes):
        route = getattr(broker, "route_endpoint", None)
        if route is None:
            return None
        try:
            return route(data)
        except Exception:  # routing must never break publishing
            return None

    async def publish(
        self, broker: Broker, data: bytes, priority: Optional[float] = None
    ) -> bool:
        """True = accepted; False = shed/failed (chunk dropped, backoff
        paid/armed). Raising is reserved for programming errors —
        transport failure must degrade the actor, not kill it (the
        broker outlives no one in the k8s model; an actor that dies on
        every broker hiccup turns one restart into a fleet crashloop).
        `priority` is the |TD-error| admission stamp, forwarded when the
        broker wants it (fabric priority-shed admission)."""
        key = self._endpoint_key(broker, data)
        pending = self._not_before.get(key, 0.0) - time.monotonic()
        if pending > 0:
            # this endpoint's armed backoff comes due now — healthy
            # endpoints' publishes never enter this branch
            self.throttle_s += pending
            await asyncio.sleep(pending)
        try:
            if priority is not None and getattr(broker, "wants_priority", False):
                broker.publish_experience_prioritized(data, priority)
            else:
                broker.publish_experience(data)
        except BrokerShedError as e:
            self.shed += 1
            await self._pay_backoff(getattr(e, "endpoint", key))
            return False
        except (ConnectionError, OSError) as e:
            self.failed += 1
            _log.warning("publish failed (%s: %s); dropping chunk and backing off", type(e).__name__, e)
            await self._pay_backoff(key)
            return False
        self.published += 1
        self._backoff.pop(key, None)
        self._not_before.pop(key, None)
        return True

    async def _pay_backoff(self, key) -> None:
        backoff = self._backoff.get(key, self.retry.backoff_base_s)
        delay = self.retry.sleep_for(backoff)
        self._backoff[key] = self.retry.next_backoff(backoff)
        if key is None:
            # classic broker: the pre-fabric immediate await
            self.throttle_s += delay
            await asyncio.sleep(delay)
        else:
            # routed broker: arm the endpoint's not-before; the next
            # publish routed THERE pays it, siblings stay at full rate
            self._not_before[key] = time.monotonic() + delay

    def stats(self) -> dict:
        return {
            "broker_shed_observed_total": float(self.shed),
            "broker_shed_publish_failed_total": float(self.failed),
            "broker_shed_throttle_s": self.throttle_s,
        }


# Discount used for the publish-time |TD-error| admission priority. The
# stamp is a RANKING heuristic consumed by the fabric shards' priority
# shed (transport/fabric.py), not a loss term — the PPOConfig default is
# close enough that actors need not carry the learner's gamma.
_PRIORITY_GAMMA = 0.98


def rollout_priority_fn(broker: Broker):
    """The publish-time priority stamp, resolved ONCE at agent boot:
    None against classic brokers (no replay import, zero per-chunk
    work); against a fabric broker (`wants_priority`), the PR-1
    |TD-error| priority computed from the chunk the agent just built —
    the producer holds the arrays, so the transport never parses a
    frame to rank it."""
    if not getattr(broker, "wants_priority", False):
        return None
    from dotaclient_tpu.replay import td_error_priority

    def fn(rollout: Rollout) -> float:
        return float(
            td_error_priority(
                rollout.rewards, rollout.behavior_value, rollout.dones, _PRIORITY_GAMMA
            )
        )

    return fn


def connect_env_async(cfg: ActorConfig) -> AsyncDotaServiceStub:
    """Dialect-aware env stub factory shared by Actor and SelfPlayActor:
    'valve' speaks a real dotaservice's wire schema through the adapter,
    anything else the internal protos."""
    if getattr(cfg, "env_dialect", "internal") == "valve":
        from dotaclient_tpu.env.valve_adapter import connect_valve_async

        return connect_valve_async(cfg.env_addr)
    return connect_async(cfg.env_addr)


async def reset_env_stub(actor) -> None:
    """Tear down the env channel after an RPC failure so the next episode
    reconnects from scratch (shared by Actor and SelfPlayActor; both keep
    the lazily-created stub in `_stub`).

    Required for convergent recovery: a kept channel reuses its dead
    subchannel, whose internal gRPC reconnect backoff grows to ~2 min —
    far past our own retry cadence — so a revived env server would sit
    unused while the actor's "retries" all fail against the stale
    subchannel."""
    stub = actor._stub
    actor._stub = None
    if stub is not None:
        try:
            await stub.channel.close()
        except Exception:  # a half-dead aio channel may throw on close
            pass


def _check_actor_policy(cfg: ActorConfig) -> None:
    """Shared validation for both actor-step builders."""
    if cfg.policy.arch == "transformer" and cfg.policy.tf_context < cfg.rollout_len:
        # The cache is reset every chunk (next_chunk), so a capacity >=
        # rollout_len means it never wraps mid-chunk. A wrap would slide
        # the acting context window while the learner re-evaluates with
        # full chunk context — silently wrong PPO ratios, so refuse.
        raise ValueError(
            f"tf_context={cfg.policy.tf_context} < rollout_len={cfg.rollout_len}: "
            f"the KV cache would wrap mid-chunk and acting context would no "
            f"longer match the learner's chunk-local re-eval"
        )


def _actor_step_row(net):
    """The per-tick inference body shared by the B=1 step and the
    vectorized fleet's batched step: rng split + policy apply + masked
    sample + joint log-prob, all inside the compiled program."""

    def row(params, state, obs, rng):
        rng, key = jax.random.split(rng)
        new_state, out = net.apply(params, state, obs)
        action = ad.sample(key, out.dist)
        logp = ad.log_prob(out.dist, action)
        return new_state, action, logp, out.value, rng

    return row


def make_actor_step(cfg: ActorConfig):
    """jit'd single-step inference: sampling stays on device.

    The rng split happens INSIDE the compiled program and the advanced
    rng is returned as a carry — a host-side jax.random.split per tick
    is a second compiled dispatch that costs ~35% of the whole actor
    step at B=1 (measured r3: 925 → 1,424 steps/s fused, 1 CPU core).
    """
    _check_actor_policy(cfg)
    step = jax.jit(_actor_step_row(P.PolicyNet(cfg.policy)))
    return step


def make_batched_actor_step(cfg: ActorConfig):
    """jit'd M-row inference tick for the vectorized fleet: stacked
    per-env (state, obs, rng) rows in, per-row (state', action, logp,
    value, rng') out, ONE dispatch for the whole fleet.

    Rows keep the single-path's exact [1, ...] inner shapes and run
    through `lax.map` — sequentially INSIDE one compiled program — so
    every row is bit-identical to make_actor_step's B=1 call on the same
    inputs regardless of which other envs share the tick (the
    occupancy-invariance partial batches rely on). vmap was measured
    ~25% faster at M=8 but shifts f32 matmul accumulation by last-ULP
    per batch size on CPU, breaking that contract; the dominant win —
    amortizing the batch-1 dispatch overhead M× — survives lax.map
    (539 → 3,512 steps/s at flagship shapes, M=8, 1 CPU core).
    """
    _check_actor_policy(cfg)
    row = _actor_step_row(P.PolicyNet(cfg.policy))

    @jax.jit
    def step(params, state, obs, rngs):
        return jax.lax.map(lambda sor: row(params, *sor), (state, obs, rngs))

    return step


def build_action(
    cfg: ActorConfig,
    action: ad.Action,
    handles: np.ndarray,
    hero: Optional[ws.Unit],
    player_id: int,
    batch_index: int = 0,
) -> ds.Action:
    """Map one batch row of sampled head indices to an Action proto."""
    a = ds.Action(player_id=player_id)
    i = batch_index
    atype = int(action.type[i])
    if atype == F.ACT_MOVE and hero is not None:
        n = cfg.policy.n_move_bins
        grid = (np.arange(n) - n // 2) / max(n // 2, 1)
        a.type = ds.Action.MOVE
        a.move_x = hero.x + float(grid[int(action.move_x[i])]) * cfg.policy.move_step
        a.move_y = hero.y + float(grid[int(action.move_y[i])]) * cfg.policy.move_step
    elif atype == F.ACT_ATTACK:
        a.type = ds.Action.ATTACK
        a.target_handle = int(handles[int(action.target[i])])
    elif atype == F.ACT_CAST:
        a.type = ds.Action.CAST
        a.ability_slot = 0
        a.target_handle = int(handles[int(action.target[i])])
    else:
        a.type = ds.Action.NOOP
    return a


def build_actions_proto(
    cfg: ActorConfig,
    action: ad.Action,
    handles: np.ndarray,
    hero: Optional[ws.Unit],
    team_id: int,
    player_id: int,
    dota_time: float,
) -> ds.Actions:
    """Map sampled head indices back to a concrete Actions proto."""
    a = build_action(cfg, action, handles, hero, player_id)
    return ds.Actions(actions=[a], team_id=team_id, dota_time=dota_time)


def next_chunk(policy_cfg, state):
    """Chunk-boundary transition shared by Actor and SelfPlayActor:
    returns (state', fresh chunk). The LSTM carries state across chunks
    (shipped on the wire as the learner's initial carry); the
    transformer family resets its KV cache here so acting context is
    chunk-local, exactly like the learner's re-eval
    (models.policy.reset_between_chunks)."""
    state = P.reset_between_chunks(policy_cfg, state)
    return state, _Chunk(P.wire_state(policy_cfg, state))


class _Chunk:
    """Accumulates one rollout chunk between broker publishes. Takes the
    wire-format (c, h) [1, H] pair (models.policy.wire_state)."""

    def __init__(self, initial_state: Tuple[np.ndarray, np.ndarray]):
        self.initial_state = (np.asarray(initial_state[0][0]), np.asarray(initial_state[1][0]))
        self.obs: List[F.Observation] = []
        self.actions: List[ad.Action] = []
        self.logp: List[float] = []
        self.value: List[float] = []
        self.rewards: List[float] = []
        self.dones: List[float] = []
        self.aux_lh: List[float] = []
        self.aux_nw: List[float] = []

    def __len__(self) -> int:
        return len(self.actions)

    def to_rollout(
        self,
        bootstrap_obs: F.Observation,
        version: int,
        actor_id: int,
        episode_return: float,
        win: float,
        with_aux: bool,
    ) -> Rollout:
        L = len(self)
        obs = F.stack(self.obs + [bootstrap_obs])
        acts = ad.Action(
            type=np.asarray([int(a.type[0]) for a in self.actions], np.int32),
            move_x=np.asarray([int(a.move_x[0]) for a in self.actions], np.int32),
            move_y=np.asarray([int(a.move_y[0]) for a in self.actions], np.int32),
            target=np.asarray([int(a.target[0]) for a in self.actions], np.int32),
        )
        aux = None
        if with_aux:
            aux = RolloutAux(
                win=np.full(L, win, np.float32),
                last_hit=np.asarray(self.aux_lh, np.float32),
                net_worth=np.asarray(self.aux_nw, np.float32),
            )
        return Rollout(
            obs=obs,
            actions=acts,
            behavior_logp=np.asarray(self.logp, np.float32),
            behavior_value=np.asarray(self.value, np.float32),
            rewards=np.asarray(self.rewards, np.float32),
            dones=np.asarray(self.dones, np.float32),
            initial_state=self.initial_state,
            version=version,
            actor_id=actor_id,
            episode_return=episode_return,
            aux=aux,
        )


class Actor:
    """One self-play actor process (player_id 0 on team radiant)."""

    # Episode failures the run loop retries with backoff instead of
    # dying: env RPC outages for the local paths; the serve tier's
    # RemoteActor extends this with its RemoteInferenceError (a lost
    # server carry abandons the episode exactly like a lost env
    # session). Class attr so subclasses extend without forking run().
    _RETRYABLE_EPISODE_ERRORS: tuple = (grpc.aio.AioRpcError,)

    def __init__(
        self,
        cfg: ActorConfig,
        broker: Broker,
        actor_id: int = 0,
        stub: Optional[AsyncDotaServiceStub] = None,
        params=None,
    ):
        self.cfg = cfg
        self.broker = broker
        self.actor_id = actor_id
        # grpc.aio channels bind to the running event loop — create lazily
        # inside run_episode, not here (__init__ runs outside the loop).
        self._stub = stub
        # `params` lets an owning VectorActor share one param tree across
        # its env workers instead of re-tracing init_params per env.
        self.params = (
            params if params is not None else P.init_params(cfg.policy, jax.random.PRNGKey(cfg.seed))
        )
        self.version = 0
        self.step_fn = make_actor_step(cfg)
        self.rng = jax.random.PRNGKey(cfg.seed * 9973 + actor_id)
        # all host-side randomness (per-episode env seeds) flows from here,
        # so identical --seed/--actor_id replays identical episode sequences
        self.np_rng = np.random.RandomState(cfg.seed * 1000003 + actor_id)
        self.player_id = 0
        self.team_id = 2
        self.steps_done = 0
        self.episodes_done = 0
        self.rollouts_published = 0
        # Publish degradation: honors broker SHED + transient failures
        # with jittered backoff (config.py RetryConfig is the policy).
        retry_cfg = getattr(cfg, "retry", None)
        self.publish_throttle = ShedThrottle(
            RetryPolicy.from_config(retry_cfg) if retry_cfg is not None else None
        )
        # Quantized experience wire (--wire.obs_dtype): resolved ONCE at
        # boot so a bad value fails the actor loudly at startup, not per
        # chunk. "f32" (default) is the identity — byte-identical legacy
        # frames, no ml_dtypes import on the publish path.
        wire_cfg = getattr(cfg, "wire", None)
        self._wire_cast = wire_cast_fn(wire_cfg.obs_dtype if wire_cfg is not None else "f32")
        # Fabric priority stamp (None against classic brokers).
        self._priority_fn = rollout_priority_fn(broker)
        self.obs = self._make_obs_runtime()
        # ±1 result of the last finished episode, 0.0 for a decided draw
        # (episode ended with no winning team), None while in flight or
        # after an abandoned episode — read by the evaluator and the
        # self-play league.
        self.last_win: Optional[float] = None
        # kill-switch clock: boot counts as "fresh" so a learner that is
        # still compiling doesn't kill its actors
        self.last_weight_time = time.monotonic()

    @property
    def rollouts_shed(self) -> int:
        """Chunks refused by broker admission control (dropped + backoff
        paid) — the producer side of the conservation ledger."""
        return self.publish_throttle.shed

    @property
    def rollouts_failed(self) -> int:
        """Chunks dropped on transport failure (broker down past the
        retry window, injected resets)."""
        return self.publish_throttle.failed

    def _make_obs_runtime(self):
        """Observability (--obs.*, dotaclient_tpu/obs/): when enabled the
        actor trace-stamps each published chunk (DTR2 wire extension)
        and keeps a flight-recorder ring; None = byte-identical legacy
        DTR1 frames and zero extra work. The vector fleet's env workers
        override this to share their owner's single runtime (one ring,
        one set of process handlers — not M)."""
        from dotaclient_tpu.obs import ObsRuntime

        return ObsRuntime.create(self.cfg.obs, role=f"actor{self.actor_id}")

    # ------------------------------------------------------------- weights

    def maybe_update_weights(self) -> bool:
        frame = self.broker.poll_weights()
        if frame is None:
            return False
        return apply_weight_frame(self, frame, f"actor {self.actor_id}")

    def check_weight_freshness(self) -> None:
        """Kill switch: raise if broadcasts stopped (cfg.max_weight_age_s
        > 0 enables it)."""
        check_weight_freshness(self)

    # ------------------------------------------------------------- episode

    @property
    def stub(self) -> AsyncDotaServiceStub:
        if self._stub is None:
            self._stub = connect_env_async(self.cfg)
        return self._stub

    def _featurize(self, world):
        """The ONE featurization choke point for this actor: worldstate →
        (Observation, handles), with per-actor observation policy (the
        disable_cast ablation mask) applied here so every consumer of an
        observation — step, chunk, bootstrap frame — sees the same view."""
        obs, handles = F.featurize_with_handles(world, self.player_id)
        if self.cfg.disable_cast:
            obs.action_mask[F.ACT_CAST] = False
        return obs, handles

    async def _policy_step(
        self, state, obs: F.Observation, chunk_len: int = 0, episode_start: bool = False
    ):
        """ONE policy inference for the current (unbatched) obs →
        (state', action, logp, value), each with the [1, ...] batch axis
        the chunk format stores. The base actor dispatches its own B=1
        jit call and advances its own rng carry; the vector fleet's env
        workers override this to await the shared InferenceBatcher, and
        the serve tier's RemoteActor routes it over the wire —
        run_episode is otherwise identical in all modes.

        `chunk_len`/`episode_start` describe the loop position (steps
        already in the current chunk; first step of the episode). The
        local paths ignore them; the remote path needs them to drive the
        server-resident carry protocol (reset on episode start, carry
        return at chunk-fill steps) without forking run_episode."""
        obs_b = jax.tree.map(lambda x: jnp.asarray(x)[None], obs)
        state, action, logp, value, self.rng = self.step_fn(self.params, state, obs_b, self.rng)
        return state, action, logp, value

    async def run_episode(self) -> float:
        cfg = self.cfg
        self.last_win = None
        # cfg.hero is one name or a comma-separated pool (config 3: shared
        # LSTM across a hero pool) — both sides draw independently
        pool = heroes.parse_pool(cfg.hero)
        config = ds.GameConfig(
            host_timescale=cfg.host_timescale,
            ticks_per_observation=cfg.ticks_per_observation,
            max_dota_time=cfg.max_dota_time,
            seed=self.np_rng.randint(1 << 30),
            hero_picks=[
                ds.HeroPick(team_id=2, hero_name=pool[self.np_rng.randint(len(pool))], control_mode=1),
                ds.HeroPick(
                    team_id=3,
                    hero_name=pool[self.np_rng.randint(len(pool))],
                    # 0 = passive scripted, 2 = hard scripted (farms/retreats)
                    control_mode={"scripted": 0, "scripted_hard": 2}.get(cfg.opponent, 1),
                ),
            ],
        )
        resp = await self.stub.reset(config)
        world = resp.world_state
        state, chunk = next_chunk(cfg.policy, P.initial_state(cfg.policy, (1,)))
        last_hero: Optional[ws.Unit] = None
        episode_return = 0.0
        done = False
        # each worldstate is featurized exactly once; the pair rolls forward
        obs, handles = self._featurize(world)

        episode_start = True
        while not done:
            state, action, logp, value = await self._policy_step(
                state, obs, chunk_len=len(chunk), episode_start=episode_start
            )
            episode_start = False

            hero = F.find_hero(world, self.player_id)
            if hero is not None:
                snap = ws.Unit()
                snap.CopyFrom(hero)
                last_hero = snap
            await self.stub.act(
                build_actions_proto(cfg, jax.device_get(action), handles, hero, self.team_id, self.player_id, world.dota_time)
            )
            resp = await self.stub.observe(ds.ObserveRequest(team_id=self.team_id))
            if resp.status == ds.Observation.RESOURCE_EXHAUSTED:
                # session lost (server restart/eviction): abandon the episode
                # and the partial chunk instead of publishing garbage steps
                _log.warning("actor %d: env session lost; abandoning episode", self.actor_id)
                self.episodes_done += 1
                return episode_return
            next_world = resp.world_state
            next_obs, next_handles = self._featurize(next_world)
            done = resp.status == ds.Observation.EPISODE_DONE
            r = R.reward(world, next_world, self.player_id, last_hero)
            episode_return += r

            chunk.obs.append(obs)
            chunk.actions.append(jax.device_get(action))
            chunk.logp.append(float(logp[0]))
            chunk.value.append(float(value[0]))
            chunk.rewards.append(r)
            chunk.dones.append(1.0 if done else 0.0)
            if cfg.policy.aux_heads:
                chunk.aux_lh.append(F.norm_last_hits(hero.last_hits) if hero else 0.0)
                chunk.aux_nw.append(F.norm_gold(hero.gold) if hero else 0.0)
            self.steps_done += 1

            if len(chunk) >= cfg.rollout_len or done:
                win = 0.0
                if done and next_world.winning_team:
                    win = 1.0 if next_world.winning_team == self.team_id else -1.0
                if done:
                    self.last_win = win
                rollout = chunk.to_rollout(
                    next_obs,
                    self.version,
                    self.actor_id,
                    episode_return if done else 0.0,
                    win,
                    cfg.policy.aux_heads,
                )
                if self.obs is not None:
                    rollout = self.obs.stamp(rollout, self.actor_id)
                # Cast-at-source wire quantization (identity under the
                # default f32), then shed/failed publishes drop the chunk
                # and pay a jittered backoff (ShedThrottle docstring);
                # the episode continues. Against a fabric broker the
                # publish carries the |TD-error| admission priority.
                if await self.publish_throttle.publish(
                    self.broker,
                    serialize_rollout(self._wire_cast(rollout)),
                    priority=(
                        self._priority_fn(rollout)
                        if self._priority_fn is not None
                        else None
                    ),
                ):
                    self.rollouts_published += 1
                state, chunk = next_chunk(cfg.policy, state)
                self.maybe_update_weights()

            world = next_world
            obs, handles = next_obs, next_handles

        self.episodes_done += 1
        return episode_return

    async def run(self, num_episodes: Optional[int] = None) -> None:
        """Episode loop with env-outage resilience: a gRPC failure (env
        server restarting, pod eviction) abandons the episode and retries
        with capped backoff instead of killing the actor — the k8s model
        is that actors outlive individual env instances."""
        backoff = 1.0
        while num_episodes is None or self.episodes_done < num_episodes:
            self.check_weight_freshness()
            try:
                ret = await self.run_episode()
                backoff = 1.0
            except self._RETRYABLE_EPISODE_ERRORS as e:
                _log.warning(
                    "actor %d: episode failed (%s: %s); retrying in %.1fs",
                    self.actor_id,
                    type(e).__name__,
                    e.code() if isinstance(e, grpc.aio.AioRpcError) else e,
                    backoff,
                )
                await reset_env_stub(self)  # drop the dead subchannel
                self.maybe_update_weights()  # stay fresh while waiting
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2.0, 30.0)
                continue
            _log.info(
                "actor %d: episode %d return %.2f (version %d, %d steps)",
                self.actor_id,
                self.episodes_done,
                ret,
                self.version,
                self.steps_done,
            )


class InferenceBatcher:
    """Per-process batched inference server for the vector fleet.

    Env coroutines submit one (state, obs, rng) step request each via
    `step()`; the `run()` driver coroutine gathers requests into a tick:
    it fires as soon as `capacity` requests are pending, and no later
    than `window_s` after the tick's FIRST request — a slow gRPC
    observe() stalls only its own env, never the batch. Partial ticks
    are padded to capacity (ONE jit signature, zero recompiles) with the
    pad rows masked out of the scatter; occupancy, gather wait, and jit
    latency are metered into the `actor_*` scalars (obs/registry.py).

    Everything here runs on one asyncio loop (requests, gather, the jit
    call itself), so there is no locking; `stats()` may be read from
    another thread and takes single-read snapshots of the counters.
    """

    # Queue sentinel: stop() pushes it so a driver blocked on get() wakes
    # even when its Task.cancel is swallowed by the Python 3.10 wait_for
    # race (inner future completing concurrently with the cancel leaves
    # the task "un-cancelled" — observed as a teardown deadlock here).
    _SENTINEL = object()

    def __init__(self, cfg: ActorConfig, params_fn, capacity: int, window_s: Optional[float] = None):
        if capacity < 1:
            raise ValueError(f"InferenceBatcher capacity must be >= 1, got {capacity}")
        self.capacity = capacity
        self.window_s = cfg.gather_window_s if window_s is None else window_s
        self._params_fn = params_fn
        self._step = make_batched_actor_step(cfg)
        self._queue: "asyncio.Queue" = asyncio.Queue()
        self._stopped = False
        # Fixed pad row: zero obs/state and a constant rng whose advanced
        # value is never written back anywhere — pad rows burn compute
        # (lax.map walks them too) but cannot perturb any real row.
        self._pad_state = jax.tree.map(np.asarray, P.initial_state(cfg.policy, (1,)))
        self._pad_obs = F.zeros_observation()
        self._pad_rng = np.asarray(jax.random.PRNGKey(0))
        # Meters (driver-coroutine-written; stats() snapshots).
        self._ticks = 0
        self._rows = 0
        # Rows-per-fired-tick occupancy HISTOGRAM (index k = ticks that
        # carried exactly k real rows; k=0 never fires — a tick starts
        # from its first request). The mean alone hid the distribution:
        # a 0.5 mean could be "every tick half full" (window too short)
        # or "alternating full/single" (bursty arrivals) — different
        # tuning moves. The serve tier exports the same family, so the
        # serve bench and the PR-5 fleet report comparable shapes.
        self._tick_rows = [0] * (capacity + 1)
        self._gather_wait_s = 0.0
        self._jit_s = 0.0
        self._first_tick_t: Optional[float] = None
        self._last_tick_t: Optional[float] = None

    async def step(self, state, obs: F.Observation, rng):
        """Submit one env's tick → (state', action, logp, value, rng'),
        shaped exactly like make_actor_step's return for that env alone
        (bit-identical to it, by the lax.map row contract)."""
        if self._stopped:
            # after stop() nothing will ever serve the queue — failing
            # loudly beats an await that can never resolve
            raise RuntimeError("InferenceBatcher is stopped")
        fut = asyncio.get_running_loop().create_future()
        self._queue.put_nowait((state, obs, rng, fut))
        return await fut

    def stop(self) -> None:
        """Flag the driver down and wake it if it's blocked on the queue.
        Cancellation alone is NOT sufficient: Python 3.10's wait_for can
        swallow a Task.cancel that races an arriving request, leaving the
        driver live forever and deadlocking the caller's teardown join."""
        self._stopped = True
        self._queue.put_nowait(self._SENTINEL)

    async def run(self) -> None:
        """Driver loop: gather → pad → ONE jit call → scatter. Stop via
        stop() (or task cancellation); in-flight futures are failed so no
        env worker can await a result that will never come."""
        reqs: list = []
        try:
            while not self._stopped:
                first = await self._queue.get()
                if first is self._SENTINEL:
                    break
                reqs = [first]
                t0 = time.monotonic()
                deadline = t0 + self.window_s
                while len(reqs) < self.capacity:
                    remaining = deadline - time.monotonic()
                    if remaining <= 0:
                        break
                    try:
                        item = await asyncio.wait_for(self._queue.get(), remaining)
                    except asyncio.TimeoutError:
                        break
                    if item is self._SENTINEL:
                        self._stopped = True
                        break
                    reqs.append(item)
                if self._stopped:
                    break
                t1 = time.monotonic()
                self._run_tick(reqs, gather_wait=t1 - t0)
                reqs = []
        finally:
            exc = RuntimeError("InferenceBatcher driver stopped")
            for _, _, _, fut in reqs:
                if not fut.done():
                    fut.set_exception(exc)
            self._fail_pending(exc)

    def _tick_bundle(self):
        """One ATOMIC read of everything a tick steps with. The base
        batcher only needs the param tree; the serve tier's subclass
        returns (params, version, tick_id) so every row of a tick is
        provably served by one tree — the no-mixed-batch-tick hot-swap
        invariant rides on this being a single read per tick."""
        return (self._params_fn(),)

    def _row_result(self, out, i: int, bundle):
        """Per-row future payload: the base contract is the bare row
        tree (state', action, logp, value, rng'); the serve subclass
        attaches the tick's (version, tick_id) from the bundle."""
        return jax.tree.map(lambda x: x[i], out)

    def _run_tick(self, reqs, gather_wait: float) -> None:
        K = len(reqs)
        M = self.capacity
        pad = M - K
        states = [r[0] for r in reqs] + [self._pad_state] * pad
        rngs = [r[2] for r in reqs] + [self._pad_rng] * pad
        obs_rows = [r[1] for r in reqs] + [self._pad_obs] * pad
        # Stack M unbatched rows leaf-wise, then restore the [1, ...]
        # inner batch axis the single-env path uses — row i of the
        # compiled program sees byte-identical shapes to a B=1 call.
        obs_b = jax.tree.map(lambda *xs: np.stack(xs)[:, None], *obs_rows)
        state_b = jax.tree.map(lambda *xs: np.stack(xs), *states)
        rng_b = np.stack([np.asarray(r) for r in rngs])
        bundle = self._tick_bundle()
        t1 = time.monotonic()
        out = self._step(bundle[0], state_b, obs_b, rng_b)
        # ONE transfer for the whole tick; per-env slices are then cheap
        # numpy views (the env loop re-device_gets them as no-ops).
        out = jax.device_get(out)
        t2 = time.monotonic()
        for i, (_, _, _, fut) in enumerate(reqs):
            if not fut.cancelled():
                fut.set_result(self._row_result(out, i, bundle))
        self._ticks += 1
        self._rows += K
        self._tick_rows[K] += 1
        self._gather_wait_s += gather_wait
        self._jit_s += t2 - t1
        if self._first_tick_t is None:
            self._first_tick_t = t1
        self._last_tick_t = t2

    def reset_meters(self) -> None:
        """Zero the meters (bench use: exclude the compile/warmup ticks
        from the measured window). Driver-loop-thread only."""
        self._ticks = 0
        self._rows = 0
        self._tick_rows = [0] * (self.capacity + 1)
        self._gather_wait_s = 0.0
        self._jit_s = 0.0
        self._first_tick_t = None
        self._last_tick_t = None

    def _fail_pending(self, exc: BaseException) -> None:
        while not self._queue.empty():
            item = self._queue.get_nowait()
            if item is self._SENTINEL:
                continue
            fut = item[3]
            if not fut.done():
                fut.set_exception(exc)

    def stats(self) -> dict:
        """The actor_* scalar family (obs/registry.py): offered rate,
        mean occupancy, mean gather wait, mean jit tick latency. Single
        reads of driver-written counters — a gauge that drifts by one
        in-flight tick is fine, a lock on the tick path is not."""
        ticks, rows = self._ticks, self._rows
        first, last = self._first_tick_t, self._last_tick_t
        elapsed = (last - first) if (first is not None and last is not None and last > first) else 0.0
        out = {
            "actor_offered_steps_per_sec": rows / elapsed if elapsed > 0 else 0.0,
            "actor_batch_occupancy": rows / float(max(ticks, 1) * self.capacity),
            "actor_gather_wait_s": self._gather_wait_s / max(ticks, 1),
            "actor_jit_step_s": self._jit_s / max(ticks, 1),
        }
        # Occupancy histogram (actor_tick_rows_<k> family, registry
        # PREFIXES): count of fired ticks that carried exactly k real
        # rows, k in 1..capacity. list(...) = one GIL-atomic snapshot of
        # the driver-written counters.
        for k, n in enumerate(list(self._tick_rows)):
            if k == 0:
                continue  # a tick fires from its first request; k=0 can't occur
            out[f"actor_tick_rows_{k}"] = float(n)
        return out


class _BatchedEnvActor(Actor):
    """One env slot of a VectorActor: the classic Actor episode loop with
    its per-tick inference routed through the owner's InferenceBatcher
    and its weight/freshness state delegated to the owner (ONE broker
    poll and ONE param tree per process, not M)."""

    def __init__(self, owner: "VectorActor", actor_id: int):
        self.owner = owner  # before super().__init__: _make_obs_runtime reads it
        super().__init__(owner.cfg, owner.broker, actor_id=actor_id, params=owner.params)

    def _make_obs_runtime(self):
        return self.owner.obs

    async def _policy_step(
        self, state, obs: F.Observation, chunk_len: int = 0, episode_start: bool = False
    ):
        state, action, logp, value, self.rng = await self.owner.batcher.step(state, obs, self.rng)
        return state, action, logp, value

    def maybe_update_weights(self) -> bool:
        """One poll for the whole fleet — but each env syncs its OWN
        stamped version here, i.e. only at its own chunk boundaries
        (run_episode calls this right after each publish). The shared
        params swap immediately for every env's next tick, so an env
        mid-chunk samples its tail under the new policy while still
        stamping the version its chunk STARTED under — staleness is
        over-estimated for those rows, never under-aged (the stamp feeds
        max_staleness drops and the ACER truncated importance weights)."""
        updated = self.owner.maybe_update_weights()
        self.version = self.owner.version
        return updated

    def check_weight_freshness(self) -> None:
        check_weight_freshness(self.owner)


class VectorActor:
    """M env sessions, one process, one batched jit inference per tick.

    Construction mirrors Actor (cfg, broker, actor_id); `envs` defaults
    to cfg.envs_per_process. Env slot j runs with actor_id
    `actor_id * M + j`, so its rng / env-seed streams (and therefore its
    episodes and published frames) are exactly those of a standalone
    Actor with that id — the property the fleet bit-equivalence test
    pins. Drive it with `run()` (actor binary) or `episode_stream()`
    (ActorPool envs-per-actor mode).
    """

    def __init__(
        self,
        cfg: ActorConfig,
        broker: Broker,
        actor_id: int = 0,
        envs: Optional[int] = None,
        params=None,
        obs_runtime=None,
    ):
        M = int(envs if envs is not None else getattr(cfg, "envs_per_process", 1))
        if M < 1:
            raise ValueError(f"envs_per_process must be >= 1, got {M}")
        self.cfg = cfg
        self.broker = broker
        self.actor_id = actor_id
        self.params = (
            params if params is not None else P.init_params(cfg.policy, jax.random.PRNGKey(cfg.seed))
        )
        self.version = 0
        self.last_weight_time = time.monotonic()
        self.last_win: Optional[float] = None
        if obs_runtime is not None:
            self.obs = obs_runtime
        else:
            from dotaclient_tpu.obs import ObsRuntime

            self.obs = ObsRuntime.create(cfg.obs, role=f"vector{actor_id}")
        self.batcher = InferenceBatcher(cfg, lambda: self.params, capacity=M)
        self.envs = [_BatchedEnvActor(self, actor_id * M + j) for j in range(M)]

    @classmethod
    def from_actor(cls, actor: Actor, envs: Optional[int] = None) -> "VectorActor":
        """Wrap a constructed classic Actor (ActorPool's envs-per-actor
        mode): same cfg/broker/actor_id/params, M env slots. The actor's
        ObsRuntime rides along too — it already installed the
        process-wide crash handlers when obs is enabled, and creating a
        second runtime would chain a duplicate recorder into them."""
        return cls(
            actor.cfg,
            actor.broker,
            actor_id=actor.actor_id,
            envs=envs,
            params=actor.params,
            obs_runtime=actor.obs,
        )

    # aggregate counters, so drivers' on_episode callbacks keep working
    @property
    def steps_done(self) -> int:
        return sum(e.steps_done for e in self.envs)

    @property
    def episodes_done(self) -> int:
        return sum(e.episodes_done for e in self.envs)

    @property
    def rollouts_published(self) -> int:
        return sum(e.rollouts_published for e in self.envs)

    @property
    def rollouts_shed(self) -> int:
        return sum(e.publish_throttle.shed for e in self.envs)

    @property
    def rollouts_failed(self) -> int:
        return sum(e.publish_throttle.failed for e in self.envs)

    def stats(self) -> dict:
        out = self.batcher.stats()
        # Fleet-wide publish-degradation meters (broker_shed_* family):
        # each env slot throttles itself, the gauges sum the fleet.
        shed = failed = published = 0
        throttle_s = 0.0
        for e in self.envs:
            t = e.publish_throttle
            shed += t.shed
            failed += t.failed
            published += e.rollouts_published
            throttle_s += t.throttle_s
        out["broker_shed_observed_total"] = float(shed)
        out["broker_shed_publish_failed_total"] = float(failed)
        out["broker_shed_throttle_s"] = throttle_s
        # Producer conservation ledger (obs/fleet.py "producer"):
        # attempted = published + shed + failed, derived from the SAME
        # per-slot reads so the identity holds exactly per scrape — the
        # fleet auditor's zero-unaccounted baseline for this tier.
        out["actor_rollouts_published_total"] = float(published)
        out["actor_publish_attempted_total"] = float(published + shed + failed)
        return out

    def maybe_update_weights(self) -> bool:
        """Apply a pending weight frame to the SHARED param tree (the
        batcher serves it to every env's next tick). Env slots pick the
        new version stamp up individually at their own chunk boundaries
        (_BatchedEnvActor.maybe_update_weights) — pushing it here would
        mis-stamp chunks whose early steps were sampled under the old
        params."""
        frame = self.broker.poll_weights()
        if frame is None:
            return False
        return apply_weight_frame(self, frame, f"vector actor {self.actor_id}")

    def check_weight_freshness(self) -> None:
        check_weight_freshness(self)

    async def _env_loop(self, env: _BatchedEnvActor, results: "asyncio.Queue") -> None:
        """Per-env worker: the same episode/retry/backoff shape as
        Actor.run, reporting completed episodes (or a fatal error) to
        the stream queue instead of logging-and-looping."""
        backoff = 1.0
        while True:
            try:
                self.check_weight_freshness()
                ret = await env.run_episode()
                backoff = 1.0
            except env._RETRYABLE_EPISODE_ERRORS as e:
                _log.warning(
                    "vector env %d: episode failed (%s: %s); retrying in %.1fs",
                    env.actor_id,
                    type(e).__name__,
                    e.code() if isinstance(e, grpc.aio.AioRpcError) else e,
                    backoff,
                )
                await reset_env_stub(env)  # drop the dead subchannel
                self.maybe_update_weights()  # stay fresh while waiting
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2.0, 30.0)
                continue
            except asyncio.CancelledError:
                raise
            except BaseException as e:  # incl. StaleWeightsError: surface it
                await results.put((env, e))
                return
            await results.put((env, float(ret)))

    async def episode_stream(self):
        """Async generator yielding each completed episode's return (any
        env). Starts the batcher driver + M env workers on the current
        loop; closing the generator tears them all down."""
        results: "asyncio.Queue" = asyncio.Queue()
        driver = asyncio.create_task(self.batcher.run())
        workers = [asyncio.create_task(self._env_loop(e, results)) for e in self.envs]
        try:
            while True:
                env, ret = await results.get()
                if isinstance(ret, BaseException):
                    raise ret
                self.last_win = env.last_win
                yield ret
        finally:
            # stop() BEFORE cancel: a cancel swallowed by the 3.10
            # wait_for race would otherwise leave the driver looping and
            # this gather waiting on it forever.
            self.batcher.stop()
            for t in workers:
                t.cancel()
            driver.cancel()
            await asyncio.gather(*workers, driver, return_exceptions=True)

    async def run(self, num_episodes: Optional[int] = None) -> None:
        """Run the fleet; `num_episodes` bounds TOTAL completed episodes
        across all envs (None = forever). With --obs.enabled and a
        metrics_port, the actor_* batcher gauges (offered rate,
        occupancy, gather wait, jit latency) export on /metrics."""
        if self.obs is not None:
            self.obs.serve_metrics([self.stats])
        try:
            done = 0
            async for _ in self.episode_stream():
                done += 1
                if num_episodes is not None and done >= num_episodes:
                    return
        finally:
            if self.obs is not None:
                self.obs.close()


def main(argv=None):
    from dotaclient_tpu.config import parse_config
    from dotaclient_tpu.transport.base import connect as broker_connect

    logging.basicConfig(level=logging.INFO)
    cfg = parse_config(ActorConfig(), argv)
    if cfg.platform:
        jax.config.update("jax_platforms", cfg.platform)
    broker = broker_connect(cfg.broker_url, retry=RetryPolicy.from_config(cfg.retry))
    if cfg.chaos.enabled:
        # Gated IMPORT, not just gated construction: with chaos off the
        # package never loads and the broker object is exactly the
        # production one (the inertness contract, tests/test_chaos.py).
        from dotaclient_tpu.chaos import wrap_broker

        broker = wrap_broker(broker, cfg.chaos)
    M = max(int(cfg.envs_per_process), 1)
    # League-through-serve mode: opponent sessions step the serve tier's
    # resident model slots (one --serve.models N server), matched by the
    # standing league service — the SelfPlayActor branch below handles it
    # (live side steps locally off the broker weight fan-out).
    remote_league = cfg.opponent == "league" and bool(cfg.serve.league)
    if cfg.serve.endpoint and not remote_league:
        # Centralized inference service mode (dotaclient_tpu/serve/):
        # featurized obs ship to the batching server, no local policy
        # step. Gated IMPORT (the chaos/ckpt precedent): with the
        # endpoint empty the serve package never loads and the actor hot
        # path is byte-identical to the local build.
        if cfg.opponent in ("self", "league"):
            raise ValueError(
                "--serve.endpoint does not serve mirror/league sessions "
                "directly: live self-play sides step the training params. "
                "League actors ARE supported through the multi-model serve "
                "tier — run the server with --serve.models N, point this "
                "actor at the league service with --serve.league "
                "<host:port> (opponents then step serve-resident slots "
                "via their matched --serve.model id); plain evaluation "
                "fleets pin one slot with --serve.model <id>"
            )
        from dotaclient_tpu.serve.client import RemoteFleet

        fleet = RemoteFleet(cfg, broker, actor_id=cfg.actor_id, envs=M)
        asyncio.run(fleet.run())
        return
    if cfg.opponent in ("self", "league"):
        from dotaclient_tpu.runtime.selfplay import SelfPlayActor

        if M > 1:
            # Self-play already batches all of a session's heroes into
            # one jit call per tick; envs_per_process here consolidates M
            # such sessions onto one loop (their env RPC waits overlap),
            # without cross-session batching — sessions step different
            # param sets (league snapshots), which can't share one call.
            actors = [SelfPlayActor(cfg, broker, actor_id=cfg.actor_id * M + j) for j in range(M)]

            async def run_all():
                await asyncio.gather(*(a.run() for a in actors))

            asyncio.run(run_all())
            return
        actor = SelfPlayActor(cfg, broker, actor_id=cfg.actor_id)
    elif M > 1:
        actor = VectorActor(cfg, broker, actor_id=cfg.actor_id)
    else:
        actor = Actor(cfg, broker, actor_id=cfg.actor_id)
    asyncio.run(actor.run())


if __name__ == "__main__":
    main()
