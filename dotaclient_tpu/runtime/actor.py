"""Asyncio actor loop — the re-design of the reference's agent.py
(SURVEY.md §2 "Actor loop", §3.1 call stack).

Per-step hot loop, exactly the reference's shape: observe() over gRPC →
featurize → policy step with carried LSTM state → mask/sample →
act() over gRPC → shaped reward from worldstate deltas → append to the
rollout chunk; every `rollout_len` steps (or at episode end) the chunk
ships to the broker with the chunk-start LSTM state and the model
version; fresh weights hot-swap in from the weight fanout at chunk
boundaries.

TPU-first differences from the reference:
- inference is ONE jit-compiled function (featurized obs + LSTM state +
  rng → action ints, log-prob, value, new state) — sampling happens
  inside jit so no logits ever cross the host boundary;
- the actor initializes params deterministically from the same seed as
  the learner, so it can act from step zero without waiting for the
  first weight broadcast (the reference downloads a pretrained
  state_dict or waits);
- rollouts go out in the pickle-free wire format (transport/serialize).
"""

from __future__ import annotations

import asyncio
import logging
import time
from typing import List, Optional, Tuple

import grpc
import jax
import jax.numpy as jnp
import numpy as np

from dotaclient_tpu.config import ActorConfig
from dotaclient_tpu.env import featurizer as F
from dotaclient_tpu.env import heroes
from dotaclient_tpu.env import rewards as R
from dotaclient_tpu.env.service import AsyncDotaServiceStub, connect_async
from dotaclient_tpu.models import policy as P
from dotaclient_tpu.ops import action_dist as ad
from dotaclient_tpu.protos import dotaservice_pb2 as ds
from dotaclient_tpu.protos import worldstate_pb2 as ws
from dotaclient_tpu.transport.base import Broker
from dotaclient_tpu.transport.serialize import (
    Rollout,
    RolloutAux,
    deserialize_weights,
    serialize_rollout,
    unflatten_params,
)

_log = logging.getLogger(__name__)


class StaleWeightsError(RuntimeError):
    """Raised by the actor kill switch: no weight broadcast arrived for
    longer than `max_weight_age_s`. The actor exits non-zero so its
    supervisor (k8s) replaces it with a fresh pod that re-subscribes —
    on-policy data from an ancient policy is worse than none
    (SURVEY.md §5 "stale-version kill switch")."""


def apply_weight_frame(agent, frame: bytes, log_name: str, on_applied=None) -> bool:
    """Shared weight hot-swap for Actor / SelfPlayActor / Evaluator.

    - malformed frames are logged and ignored (a bad broadcast must
      never kill a subscriber);
    - within one learner boot (same frame boot_epoch), frames OLDER than
      what the agent runs are rejected — a publish that sat blocked
      through a broker outage must not regress weights;
    - a boot_epoch CHANGE is the deterministic learner-restart signal
      (the epoch is drawn once at learner boot and stamped into every
      DTW2 frame): the agent resyncs to the new boot's version
      unconditionally, even if lower. This replaced the r3
      consecutive-older-frames counter, whose threshold a jittery broker
      at publish_every=1 could reach with merely-delayed frames
      (VERDICT r3 weak item 5). Worst case under the epoch scheme: ONE
      delayed frame from a dead previous boot swaps in once, and the
      next live broadcast (epoch differs again) swaps it right back;
    - `on_applied(named_params, version)` runs after a successful swap
      (league snapshotting hook).
    """
    try:
        named, version, boot_epoch = deserialize_weights(frame)
    except Exception as e:  # truncated frames raise struct.error etc.
        _log.warning("%s: bad weight frame: %s", log_name, e)
        return False
    last_epoch = getattr(agent, "weight_epoch", None)
    if last_epoch is not None and boot_epoch != last_epoch:
        _log.warning(
            "%s: weight boot_epoch %d -> %d — learner restarted, resyncing to v%d",
            log_name,
            last_epoch,
            boot_epoch,
            version,
        )
    elif version < agent.version:
        _log.warning(
            "%s: ignoring stale weight frame v%d (< v%d, same boot)",
            log_name,
            version,
            agent.version,
        )
        return False
    try:
        # a frame that deserializes but doesn't match the agent's param
        # template (learner restarted with a different PolicyConfig)
        # must ALSO never kill the subscriber
        agent.params = unflatten_params(named, agent.params)
    except Exception as e:
        _log.warning("%s: weight frame does not fit params (%s); ignoring", log_name, e)
        return False
    agent.version = version
    agent.weight_epoch = boot_epoch
    agent.last_weight_time = time.monotonic()
    if on_applied is not None:
        on_applied(named, version)
    return True


def check_weight_freshness(actor) -> None:
    """Shared kill-switch check for Actor and SelfPlayActor (both carry
    cfg.max_weight_age_s and last_weight_time)."""
    age = time.monotonic() - actor.last_weight_time
    if 0 < actor.cfg.max_weight_age_s < age:
        raise StaleWeightsError(
            f"actor {actor.actor_id}: no weight update for {age:.0f}s "
            f"(limit {actor.cfg.max_weight_age_s:.0f}s) — exiting for restart"
        )


def connect_env_async(cfg: ActorConfig) -> AsyncDotaServiceStub:
    """Dialect-aware env stub factory shared by Actor and SelfPlayActor:
    'valve' speaks a real dotaservice's wire schema through the adapter,
    anything else the internal protos."""
    if getattr(cfg, "env_dialect", "internal") == "valve":
        from dotaclient_tpu.env.valve_adapter import connect_valve_async

        return connect_valve_async(cfg.env_addr)
    return connect_async(cfg.env_addr)


async def reset_env_stub(actor) -> None:
    """Tear down the env channel after an RPC failure so the next episode
    reconnects from scratch (shared by Actor and SelfPlayActor; both keep
    the lazily-created stub in `_stub`).

    Required for convergent recovery: a kept channel reuses its dead
    subchannel, whose internal gRPC reconnect backoff grows to ~2 min —
    far past our own retry cadence — so a revived env server would sit
    unused while the actor's "retries" all fail against the stale
    subchannel."""
    stub = actor._stub
    actor._stub = None
    if stub is not None:
        try:
            await stub.channel.close()
        except Exception:  # a half-dead aio channel may throw on close
            pass


def make_actor_step(cfg: ActorConfig):
    """jit'd single-step inference: sampling stays on device.

    The rng split happens INSIDE the compiled program and the advanced
    rng is returned as a carry — a host-side jax.random.split per tick
    is a second compiled dispatch that costs ~35% of the whole actor
    step at B=1 (measured r3: 925 → 1,424 steps/s fused, 1 CPU core).
    """
    if cfg.policy.arch == "transformer" and cfg.policy.tf_context < cfg.rollout_len:
        # The cache is reset every chunk (next_chunk), so a capacity >=
        # rollout_len means it never wraps mid-chunk. A wrap would slide
        # the acting context window while the learner re-evaluates with
        # full chunk context — silently wrong PPO ratios, so refuse.
        raise ValueError(
            f"tf_context={cfg.policy.tf_context} < rollout_len={cfg.rollout_len}: "
            f"the KV cache would wrap mid-chunk and acting context would no "
            f"longer match the learner's chunk-local re-eval"
        )
    net = P.PolicyNet(cfg.policy)

    @jax.jit
    def step(params, state, obs, rng):
        rng, key = jax.random.split(rng)
        new_state, out = net.apply(params, state, obs)
        action = ad.sample(key, out.dist)
        logp = ad.log_prob(out.dist, action)
        return new_state, action, logp, out.value, rng

    return step


def build_action(
    cfg: ActorConfig,
    action: ad.Action,
    handles: np.ndarray,
    hero: Optional[ws.Unit],
    player_id: int,
    batch_index: int = 0,
) -> ds.Action:
    """Map one batch row of sampled head indices to an Action proto."""
    a = ds.Action(player_id=player_id)
    i = batch_index
    atype = int(action.type[i])
    if atype == F.ACT_MOVE and hero is not None:
        n = cfg.policy.n_move_bins
        grid = (np.arange(n) - n // 2) / max(n // 2, 1)
        a.type = ds.Action.MOVE
        a.move_x = hero.x + float(grid[int(action.move_x[i])]) * cfg.policy.move_step
        a.move_y = hero.y + float(grid[int(action.move_y[i])]) * cfg.policy.move_step
    elif atype == F.ACT_ATTACK:
        a.type = ds.Action.ATTACK
        a.target_handle = int(handles[int(action.target[i])])
    elif atype == F.ACT_CAST:
        a.type = ds.Action.CAST
        a.ability_slot = 0
        a.target_handle = int(handles[int(action.target[i])])
    else:
        a.type = ds.Action.NOOP
    return a


def build_actions_proto(
    cfg: ActorConfig,
    action: ad.Action,
    handles: np.ndarray,
    hero: Optional[ws.Unit],
    team_id: int,
    player_id: int,
    dota_time: float,
) -> ds.Actions:
    """Map sampled head indices back to a concrete Actions proto."""
    a = build_action(cfg, action, handles, hero, player_id)
    return ds.Actions(actions=[a], team_id=team_id, dota_time=dota_time)


def next_chunk(policy_cfg, state):
    """Chunk-boundary transition shared by Actor and SelfPlayActor:
    returns (state', fresh chunk). The LSTM carries state across chunks
    (shipped on the wire as the learner's initial carry); the
    transformer family resets its KV cache here so acting context is
    chunk-local, exactly like the learner's re-eval
    (models.policy.reset_between_chunks)."""
    state = P.reset_between_chunks(policy_cfg, state)
    return state, _Chunk(P.wire_state(policy_cfg, state))


class _Chunk:
    """Accumulates one rollout chunk between broker publishes. Takes the
    wire-format (c, h) [1, H] pair (models.policy.wire_state)."""

    def __init__(self, initial_state: Tuple[np.ndarray, np.ndarray]):
        self.initial_state = (np.asarray(initial_state[0][0]), np.asarray(initial_state[1][0]))
        self.obs: List[F.Observation] = []
        self.actions: List[ad.Action] = []
        self.logp: List[float] = []
        self.value: List[float] = []
        self.rewards: List[float] = []
        self.dones: List[float] = []
        self.aux_lh: List[float] = []
        self.aux_nw: List[float] = []

    def __len__(self) -> int:
        return len(self.actions)

    def to_rollout(
        self,
        bootstrap_obs: F.Observation,
        version: int,
        actor_id: int,
        episode_return: float,
        win: float,
        with_aux: bool,
    ) -> Rollout:
        L = len(self)
        obs = F.stack(self.obs + [bootstrap_obs])
        acts = ad.Action(
            type=np.asarray([int(a.type[0]) for a in self.actions], np.int32),
            move_x=np.asarray([int(a.move_x[0]) for a in self.actions], np.int32),
            move_y=np.asarray([int(a.move_y[0]) for a in self.actions], np.int32),
            target=np.asarray([int(a.target[0]) for a in self.actions], np.int32),
        )
        aux = None
        if with_aux:
            aux = RolloutAux(
                win=np.full(L, win, np.float32),
                last_hit=np.asarray(self.aux_lh, np.float32),
                net_worth=np.asarray(self.aux_nw, np.float32),
            )
        return Rollout(
            obs=obs,
            actions=acts,
            behavior_logp=np.asarray(self.logp, np.float32),
            behavior_value=np.asarray(self.value, np.float32),
            rewards=np.asarray(self.rewards, np.float32),
            dones=np.asarray(self.dones, np.float32),
            initial_state=self.initial_state,
            version=version,
            actor_id=actor_id,
            episode_return=episode_return,
            aux=aux,
        )


class Actor:
    """One self-play actor process (player_id 0 on team radiant)."""

    def __init__(
        self,
        cfg: ActorConfig,
        broker: Broker,
        actor_id: int = 0,
        stub: Optional[AsyncDotaServiceStub] = None,
    ):
        self.cfg = cfg
        self.broker = broker
        self.actor_id = actor_id
        # grpc.aio channels bind to the running event loop — create lazily
        # inside run_episode, not here (__init__ runs outside the loop).
        self._stub = stub
        self.params = P.init_params(cfg.policy, jax.random.PRNGKey(cfg.seed))
        self.version = 0
        self.step_fn = make_actor_step(cfg)
        self.rng = jax.random.PRNGKey(cfg.seed * 9973 + actor_id)
        # all host-side randomness (per-episode env seeds) flows from here,
        # so identical --seed/--actor_id replays identical episode sequences
        self.np_rng = np.random.RandomState(cfg.seed * 1000003 + actor_id)
        self.player_id = 0
        self.team_id = 2
        self.steps_done = 0
        self.episodes_done = 0
        self.rollouts_published = 0
        # Observability (--obs.*, dotaclient_tpu/obs/): when enabled the
        # actor trace-stamps each published chunk (DTR2 wire extension)
        # and keeps a flight-recorder ring; None = byte-identical legacy
        # DTR1 frames and zero extra work.
        from dotaclient_tpu.obs import ObsRuntime

        self.obs = ObsRuntime.create(cfg.obs, role=f"actor{actor_id}")
        # ±1 result of the last finished episode, 0.0 for a decided draw
        # (episode ended with no winning team), None while in flight or
        # after an abandoned episode — read by the evaluator and the
        # self-play league.
        self.last_win: Optional[float] = None
        # kill-switch clock: boot counts as "fresh" so a learner that is
        # still compiling doesn't kill its actors
        self.last_weight_time = time.monotonic()

    # ------------------------------------------------------------- weights

    def maybe_update_weights(self) -> bool:
        frame = self.broker.poll_weights()
        if frame is None:
            return False
        return apply_weight_frame(self, frame, f"actor {self.actor_id}")

    def check_weight_freshness(self) -> None:
        """Kill switch: raise if broadcasts stopped (cfg.max_weight_age_s
        > 0 enables it)."""
        check_weight_freshness(self)

    # ------------------------------------------------------------- episode

    @property
    def stub(self) -> AsyncDotaServiceStub:
        if self._stub is None:
            self._stub = connect_env_async(self.cfg)
        return self._stub

    def _featurize(self, world):
        """The ONE featurization choke point for this actor: worldstate →
        (Observation, handles), with per-actor observation policy (the
        disable_cast ablation mask) applied here so every consumer of an
        observation — step, chunk, bootstrap frame — sees the same view."""
        obs, handles = F.featurize_with_handles(world, self.player_id)
        if self.cfg.disable_cast:
            obs.action_mask[F.ACT_CAST] = False
        return obs, handles

    async def run_episode(self) -> float:
        cfg = self.cfg
        self.last_win = None
        # cfg.hero is one name or a comma-separated pool (config 3: shared
        # LSTM across a hero pool) — both sides draw independently
        pool = heroes.parse_pool(cfg.hero)
        config = ds.GameConfig(
            host_timescale=cfg.host_timescale,
            ticks_per_observation=cfg.ticks_per_observation,
            max_dota_time=cfg.max_dota_time,
            seed=self.np_rng.randint(1 << 30),
            hero_picks=[
                ds.HeroPick(team_id=2, hero_name=pool[self.np_rng.randint(len(pool))], control_mode=1),
                ds.HeroPick(
                    team_id=3,
                    hero_name=pool[self.np_rng.randint(len(pool))],
                    # 0 = passive scripted, 2 = hard scripted (farms/retreats)
                    control_mode={"scripted": 0, "scripted_hard": 2}.get(cfg.opponent, 1),
                ),
            ],
        )
        resp = await self.stub.reset(config)
        world = resp.world_state
        state, chunk = next_chunk(cfg.policy, P.initial_state(cfg.policy, (1,)))
        last_hero: Optional[ws.Unit] = None
        episode_return = 0.0
        done = False
        # each worldstate is featurized exactly once; the pair rolls forward
        obs, handles = self._featurize(world)

        while not done:
            obs_b = jax.tree.map(lambda x: jnp.asarray(x)[None], obs)
            state, action, logp, value, self.rng = self.step_fn(self.params, state, obs_b, self.rng)

            hero = F.find_hero(world, self.player_id)
            if hero is not None:
                snap = ws.Unit()
                snap.CopyFrom(hero)
                last_hero = snap
            await self.stub.act(
                build_actions_proto(cfg, jax.device_get(action), handles, hero, self.team_id, self.player_id, world.dota_time)
            )
            resp = await self.stub.observe(ds.ObserveRequest(team_id=self.team_id))
            if resp.status == ds.Observation.RESOURCE_EXHAUSTED:
                # session lost (server restart/eviction): abandon the episode
                # and the partial chunk instead of publishing garbage steps
                _log.warning("actor %d: env session lost; abandoning episode", self.actor_id)
                self.episodes_done += 1
                return episode_return
            next_world = resp.world_state
            next_obs, next_handles = self._featurize(next_world)
            done = resp.status == ds.Observation.EPISODE_DONE
            r = R.reward(world, next_world, self.player_id, last_hero)
            episode_return += r

            chunk.obs.append(obs)
            chunk.actions.append(jax.device_get(action))
            chunk.logp.append(float(logp[0]))
            chunk.value.append(float(value[0]))
            chunk.rewards.append(r)
            chunk.dones.append(1.0 if done else 0.0)
            if cfg.policy.aux_heads:
                chunk.aux_lh.append(F.norm_last_hits(hero.last_hits) if hero else 0.0)
                chunk.aux_nw.append(F.norm_gold(hero.gold) if hero else 0.0)
            self.steps_done += 1

            if len(chunk) >= cfg.rollout_len or done:
                win = 0.0
                if done and next_world.winning_team:
                    win = 1.0 if next_world.winning_team == self.team_id else -1.0
                if done:
                    self.last_win = win
                rollout = chunk.to_rollout(
                    next_obs,
                    self.version,
                    self.actor_id,
                    episode_return if done else 0.0,
                    win,
                    cfg.policy.aux_heads,
                )
                if self.obs is not None:
                    rollout = self.obs.stamp(rollout, self.actor_id)
                self.broker.publish_experience(serialize_rollout(rollout))
                self.rollouts_published += 1
                state, chunk = next_chunk(cfg.policy, state)
                self.maybe_update_weights()

            world = next_world
            obs, handles = next_obs, next_handles

        self.episodes_done += 1
        return episode_return

    async def run(self, num_episodes: Optional[int] = None) -> None:
        """Episode loop with env-outage resilience: a gRPC failure (env
        server restarting, pod eviction) abandons the episode and retries
        with capped backoff instead of killing the actor — the k8s model
        is that actors outlive individual env instances."""
        backoff = 1.0
        while num_episodes is None or self.episodes_done < num_episodes:
            self.check_weight_freshness()
            try:
                ret = await self.run_episode()
                backoff = 1.0
            except grpc.aio.AioRpcError as e:
                _log.warning(
                    "actor %d: env rpc failed (%s); retrying in %.1fs",
                    self.actor_id,
                    e.code(),
                    backoff,
                )
                await reset_env_stub(self)  # drop the dead subchannel
                self.maybe_update_weights()  # stay fresh while waiting
                await asyncio.sleep(backoff)
                backoff = min(backoff * 2.0, 30.0)
                continue
            _log.info(
                "actor %d: episode %d return %.2f (version %d, %d steps)",
                self.actor_id,
                self.episodes_done,
                ret,
                self.version,
                self.steps_done,
            )


def main(argv=None):
    from dotaclient_tpu.config import parse_config
    from dotaclient_tpu.transport.base import connect as broker_connect

    logging.basicConfig(level=logging.INFO)
    cfg = parse_config(ActorConfig(), argv)
    if cfg.platform:
        jax.config.update("jax_platforms", cfg.platform)
    broker = broker_connect(cfg.broker_url)
    if cfg.opponent in ("self", "league"):
        from dotaclient_tpu.runtime.selfplay import SelfPlayActor

        actor = SelfPlayActor(cfg, broker, actor_id=cfg.actor_id)
    else:
        actor = Actor(cfg, broker, actor_id=cfg.actor_id)
    asyncio.run(actor.run())


if __name__ == "__main__":
    main()
