"""Durable checkpoint/resume via orbax.

The reference uploads `model_%09d.pt` state_dicts to GCS and resumes via
a --pretrained flag (SURVEY.md §5 "Checkpoint / resume"). Here the full
TrainState (params + optimizer state + step/version counter) goes
through an orbax CheckpointManager, so a learner restart resumes
training exactly — including Adam moments — not just the policy. The
directory can be local or a gcs:// path (orbax handles both); actors
never read checkpoints, they get weights over the broker fanout.
"""

from __future__ import annotations

import json
import logging
from typing import Optional

from etils import epath
import orbax.checkpoint as ocp

from dotaclient_tpu.env.featurizer import FEATURE_SCHEMA_VERSION

_log = logging.getLogger(__name__)


class SchemaMismatchError(RuntimeError):
    """Checkpoint was written under a different feature schema."""


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 5):
        self._dir = epath.Path(directory)
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def _schema_path(self) -> epath.Path:
        return self._dir / "feature_schema.json"

    def save(self, state, step: int, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        # stamp the CURRENT build's schema unconditionally: the newest
        # checkpoints are always this version, and a stale stamp left in a
        # reused directory would false-positive the restore guard after
        # max_to_keep GC removes the old-era checkpoints
        self._schema_path().write_text(
            json.dumps({"feature_schema_version": FEATURE_SCHEMA_VERSION})
        )
        if wait:
            self._mngr.wait_until_finished()

    def restore_latest(self, template) -> Optional[object]:
        step = self._mngr.latest_step()
        if step is None:
            return None
        p = self._schema_path()
        if p.exists():
            saved = json.loads(p.read_text()).get("feature_schema_version")
            if saved != FEATURE_SCHEMA_VERSION:
                raise SchemaMismatchError(
                    f"checkpoint at {self._dir} was written with feature "
                    f"schema v{saved}, this build uses v{FEATURE_SCHEMA_VERSION} "
                    f"(env/featurizer.py history) — param shapes will not "
                    f"restore; retrain or convert the checkpoint"
                )
        return self._mngr.restore(step, args=ocp.args.StandardRestore(template))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
