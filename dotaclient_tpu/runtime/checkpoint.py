"""Durable checkpoint/resume via orbax.

The reference uploads `model_%09d.pt` state_dicts to GCS and resumes via
a --pretrained flag (SURVEY.md §5 "Checkpoint / resume"). Here the full
TrainState (params + optimizer state + step/version counter) goes
through an orbax CheckpointManager, so a learner restart resumes
training exactly — including Adam moments — not just the policy. The
directory can be local or a gcs:// path (orbax handles both); actors
never read checkpoints, they get weights over the broker fanout.
"""

from __future__ import annotations

import logging
from typing import Optional

from etils import epath
import orbax.checkpoint as ocp

_log = logging.getLogger(__name__)


class Checkpointer:
    def __init__(self, directory: str, max_to_keep: int = 5):
        self._mngr = ocp.CheckpointManager(
            epath.Path(directory),
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def save(self, state, step: int, wait: bool = False) -> None:
        self._mngr.save(step, args=ocp.args.StandardSave(state))
        if wait:
            self._mngr.wait_until_finished()

    def restore_latest(self, template) -> Optional[object]:
        step = self._mngr.latest_step()
        if step is None:
            return None
        return self._mngr.restore(step, args=ocp.args.StandardRestore(template))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        self._mngr.close()
