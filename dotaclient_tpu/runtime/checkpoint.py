"""Durable checkpoint/resume via orbax, with an optional remote mirror.

The reference uploads `model_%09d.pt` state_dicts to GCS and resumes via
a --pretrained flag (SURVEY.md §5 "Checkpoint / resume"). Here the full
TrainState (params + optimizer state + step/version counter) goes
through an orbax CheckpointManager, so a learner restart resumes
training exactly — including Adam moments — not just the policy. Actors
never read checkpoints; they get weights over the broker fanout.

Remote durability follows the reference's upload model, as an explicit
seam: orbax writes the local directory, then `remote_dir` (any epath
scheme — gs://, s3://, anything fsspec mounts) receives a file-level
mirror of the finished step, and restore pulls the newest remote step
down when the local directory is empty (fresh pod, ephemeral disk).
This is deliberately NOT orbax-writing-straight-to-gs://: the mirror
copies finished files through epath only, so the remote path is
testable in-process against fsspec's memory filesystem
(tests/test_checkpoint_remote.py) instead of being trusted on faith —
and a half-written step can never appear at the remote (copy starts
after wait_until_finished, and the step marker file lands last).
"""

from __future__ import annotations

import concurrent.futures
import io
import json
import logging
import os
import threading
from typing import Optional

from etils import epath
import orbax.checkpoint as ocp

from dotaclient_tpu.env.featurizer import FEATURE_SCHEMA_VERSION

_log = logging.getLogger(__name__)


class SchemaMismatchError(RuntimeError):
    """Checkpoint was written under a different feature schema."""


_STEP_DONE = "MIRROR_COMPLETE"  # marker file, written LAST per mirrored step
# Aux sidecar per step (full-state manifests: RNG streams, replay
# reservoir, pending frames, publisher high-water mark — the learner
# builds/consumes the payload, this module only stores it durably).
_AUX_FMT = "aux_{}.bin"
# Weight-publisher version high-water mark: a tiny file the publisher
# thread refreshes on every successful fanout, so a SIGKILL between
# periodic checkpoints cannot roll the restored version counter back
# below versions the fleet has already seen (staleness stamps must stay
# monotonic — never under-aged for max_staleness/ACER).
_HWM_FILE = "version_hwm"


def _atomic_write(dst: epath.Path, data: bytes) -> None:
    """tmp + fsync + replace: the destination either holds the previous
    complete contents or the new complete contents, never a torn write —
    the same pattern as the PR-1 native ISA fingerprint publish. The
    dot-prefixed tmp name keeps partials invisible to orbax's step scan
    and to the mirror's digit-named listing walks. fsync is best-effort:
    non-local epath backends (gs://, the in-memory test fs) expose no
    fd, and there the backend's replace/mv is the atomicity boundary."""
    tmp = dst.parent / f".{dst.name}.tmp"
    with tmp.open("wb") as f:
        f.write(data)
        try:
            f.flush()
            os.fsync(f.fileno())
        except (AttributeError, OSError, io.UnsupportedOperation):
            pass
    tmp.replace(dst)


class Checkpointer:
    def __init__(
        self,
        directory: str,
        max_to_keep: int = 5,
        remote_dir: str = "",
        remote_push: bool = True,
    ):
        """`remote_push=False` makes the remote READ-ONLY for this
        process: restores still pull the newest complete step, but saves
        never mirror up. Multihost learners set it on non-primary
        processes — every host must be able to pull the shared mirror on
        restart (or the resume-step consistency check trips), while only
        process 0 uploads."""
        self._dir = epath.Path(directory)
        self._remote = epath.Path(remote_dir) if remote_dir else None
        self._remote_push = remote_push
        self._max_to_keep = max_to_keep
        # Stream copies in bounded chunks (r4 known debt): a TrainState
        # shard can be GBs; whole-file read_bytes() would hold it all in
        # host RAM alongside the training arrays. Tests shrink the chunk
        # to force the multi-chunk path on small files.
        self._copy_chunk = 8 * 1024 * 1024
        # Mirroring happens on ONE worker thread: the upload (seconds to
        # minutes for a big TrainState) must never stall the train loop,
        # and a single worker keeps uploads ordered so remote GC sees
        # monotonic steps. wait_until_finished is safe off-thread (orbax's
        # async manager is thread-safe for waits).
        #
        # The queue is COALESCED to the newest pending step (ADVICE r4):
        # if uploads are persistently slower than the checkpoint cadence,
        # a FIFO of every step grows without bound while local
        # max_to_keep GC deletes step dirs before their queued mirror
        # runs. Superseded steps are dropped at submit time — the remote
        # only ever needs the newest durable state — and the drop is
        # counted in mirror_stats() so persistent lag is a metric, not a
        # buried log line.
        self._mirror_pool = (
            concurrent.futures.ThreadPoolExecutor(
                max_workers=1, thread_name_prefix="ckpt-mirror"
            )
            if self._remote is not None and remote_push
            else None
        )
        self._mirror_cond = threading.Condition()
        self._mirror_pending: Optional[int] = None
        self._mirror_inflight = False
        self._mirror_counts = {
            "mirrored": 0,
            "superseded": 0,
            "failures": 0,
        }
        self._last_saved_step: Optional[int] = None
        self._last_mirrored_step: Optional[int] = None
        # Aux finalize worker (full-state checkpoints only; None until the
        # first save(aux=...) so the plain params/opt/step path constructs
        # nothing new). Same single-worker latest-wins coalescing as the
        # mirror: the aux write must FOLLOW wait_until_finished (aux
        # present ⇒ the orbax step is complete — the transactional
        # contract), and that wait must never run on the train loop.
        self._aux_pool: Optional[concurrent.futures.ThreadPoolExecutor] = None
        self._aux_cond = threading.Condition()
        self._aux_pending: Optional[tuple] = None  # (step, payload bytes)
        self._aux_inflight = False
        self._aux_counts = {"aux_written": 0, "aux_superseded": 0, "aux_failures": 0}
        self._last_aux_step: Optional[int] = None
        self._last_aux_bytes = 0
        self._hwm_lock = threading.Lock()
        self._hwm: Optional[int] = None
        # ALL orbax save dispatch funnels through one dedicated thread:
        # CheckpointManager only clears its finalize-thread handle when
        # wait_until_finished runs on the SAME thread that called save()
        # — a save from any other thread then hits orbax's
        # `assert self._finalize_thread is None`. One owner thread makes
        # every (wait-for-previous → save) pair self-clearing, so saves
        # may originate from the loop thread (sync path), the
        # CheckpointWorker (async path), and a SIGTERM drain without
        # tripping it. The submit lock keeps step order = call order.
        self._save_lock = threading.Lock()
        self._orbax_pool = concurrent.futures.ThreadPoolExecutor(
            max_workers=1, thread_name_prefix="orbax-save"
        )
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=max_to_keep, create=True),
        )

    def _schema_path(self) -> epath.Path:
        return self._dir / "feature_schema.json"

    def save(self, state, step: int, wait: bool = False, aux: Optional[bytes] = None) -> None:
        """`aux` (full-state manifests) rides a per-step sidecar written
        by a finalize worker AFTER orbax commits the step, via tmp +
        fsync + os.replace — so a crash anywhere mid-save leaves the
        previous step (and ITS aux) fully restorable, and an aux file's
        existence certifies its step is complete. With a remote mirror,
        the aux path hands the mirror submit to the finalize worker so
        the upload always includes the sidecar; aux=None is the
        pre-existing params/opt/step path, byte-identical on disk."""
        with self._save_lock:
            # Blocks (like a direct save call would) until orbax has
            # staged the arrays; the commit itself stays async.
            self._orbax_pool.submit(self._orbax_save, state, step).result()
        # stamp the CURRENT build's schema unconditionally: the newest
        # checkpoints are always this version, and a stale stamp left in a
        # reused directory would false-positive the restore guard after
        # max_to_keep GC removes the old-era checkpoints
        _atomic_write(
            self._schema_path(),
            json.dumps({"feature_schema_version": FEATURE_SCHEMA_VERSION}).encode(),
        )
        if wait:
            self._mngr.wait_until_finished()
        self._last_saved_step = step
        if aux is not None:
            with self._aux_cond:
                if self._aux_pool is None:
                    self._aux_pool = concurrent.futures.ThreadPoolExecutor(
                        max_workers=1, thread_name_prefix="ckpt-aux"
                    )
                if self._aux_pending is not None:
                    self._aux_counts["aux_superseded"] += 1
                self._aux_pending = (step, aux)
                if not self._aux_inflight:
                    self._aux_inflight = True
                    self._aux_pool.submit(self._aux_worker)
            if wait:
                with self._aux_cond:
                    self._aux_cond.wait_for(
                        lambda: self._aux_pending is None and not self._aux_inflight
                    )
        elif self._mirror_pool is not None:
            with self._mirror_cond:
                if self._mirror_pending is not None:
                    # Slow-upload backpressure: the older pending step is
                    # superseded, never uploaded. Deliberate — see the
                    # coalescing note in __init__.
                    self._mirror_counts["superseded"] += 1
                self._mirror_pending = step
                if not self._mirror_inflight:
                    self._mirror_inflight = True
                    self._mirror_pool.submit(self._mirror_worker)
        if wait and self._mirror_pool is not None:
            with self._mirror_cond:
                self._mirror_cond.wait_for(
                    lambda: self._mirror_pending is None and not self._mirror_inflight
                )

    def _orbax_save(self, state, step: int) -> None:
        """Owner-thread half of save(): waiting here (same thread as the
        previous save) lets orbax clear its finalize handle before the
        next dispatch — see the _orbax_pool comment in __init__."""
        self._mngr.wait_until_finished()
        self._mngr.save(step, args=ocp.args.StandardSave(state))

    def _aux_worker(self) -> None:
        """Drain the coalesced aux queue on the single finalize thread:
        wait for orbax to commit the step, land the sidecar atomically,
        sweep sidecars orphaned by orbax's max_to_keep GC, then (mirror
        configured) hand the COMPLETE step to the mirror queue."""
        while True:
            with self._aux_cond:
                item = self._aux_pending
                self._aux_pending = None
                if item is None:
                    self._aux_inflight = False
                    self._aux_cond.notify_all()
                    return
            step, payload = item
            self._mngr.wait_until_finished()
            try:
                _atomic_write(self._dir / _AUX_FMT.format(step), payload)
                with self._aux_cond:
                    self._aux_counts["aux_written"] += 1
                    self._last_aux_step = step
                    self._last_aux_bytes = len(payload)
            except Exception:
                with self._aux_cond:
                    self._aux_counts["aux_failures"] += 1
                _log.exception("aux manifest write for step %d failed; continuing", step)
            self._gc_aux(keep=step)
            if self._mirror_pool is not None:
                with self._mirror_cond:
                    if self._mirror_pending is not None:
                        self._mirror_counts["superseded"] += 1
                    self._mirror_pending = step
                    if not self._mirror_inflight:
                        self._mirror_inflight = True
                        self._mirror_pool.submit(self._mirror_worker)

    def _gc_aux(self, keep: int) -> None:
        """Drop aux sidecars whose orbax step is gone (max_to_keep GC) —
        an aux file must never outlive (or predate) its step, or restore
        could pair one step's reservoir with another step's params."""
        try:
            live = set(self._mngr.all_steps())
        except Exception:
            return
        live.add(keep)
        for child in self._dir.iterdir():
            name = child.name
            if name.startswith("aux_") and name.endswith(".bin"):
                stem = name[4:-4]
                if stem.isdigit() and int(stem) not in live:
                    try:
                        child.unlink()
                    except OSError:
                        pass

    def load_aux(self, step: Optional[int]) -> Optional[bytes]:
        """The aux manifest for `step`, or None (no full-state save for
        that step, or a legacy checkpoint). Atomic-replace publishing
        guarantees complete-or-absent — never a torn read."""
        if step is None:
            return None
        p = self._dir / _AUX_FMT.format(step)
        if not p.exists():
            return None
        return p.read_bytes()

    # ------------------------------------------------- publish high-water

    def record_published_version(self, version: int) -> None:
        """Publisher-thread hook: persist the highest version ever fanned
        out to the fleet (monotonic; tmp + os.replace so the file is
        always a complete int). Off the train loop by construction — the
        WeightPublisher calls this after each successful send."""
        with self._hwm_lock:
            if self._hwm is not None and version <= self._hwm:
                return
            self._hwm = version
        try:
            _atomic_write(self._dir / _HWM_FILE, str(version).encode())
        except Exception:
            _log.exception("version high-water write failed; continuing")

    def published_hwm(self) -> Optional[int]:
        """Highest version the fleet has seen from this checkpoint dir
        (None before any full-state publish). Restore takes
        max(checkpoint step, aux hwm, this) as the resume version."""
        p = self._dir / _HWM_FILE
        if not p.exists():
            return None
        try:
            return int(p.read_text().strip())
        except (ValueError, OSError):
            return None

    def discard_pending(self) -> None:
        """SIGKILL emulation support (chaos controller): drop queued
        aux/mirror work as a real kill -9 would — the durable state is
        whatever already hit the disk, nothing in flight completes."""
        with self._aux_cond:
            self._aux_pending = None
        with self._mirror_cond:
            self._mirror_pending = None

    def save_stats(self) -> dict:
        """Full-state save-health snapshot for the learner's metrics
        stream (ckpt_* scalars). Empty until the first save(aux=...)."""
        with self._aux_cond:
            if self._aux_pool is None and self._aux_counts["aux_written"] == 0:
                return {}
            out = dict(self._aux_counts)
            out["last_aux_bytes"] = self._last_aux_bytes
            if self._last_aux_step is not None:
                out["last_aux_step"] = self._last_aux_step
            return out

    def _mirror_worker(self) -> None:
        """Drain the coalesced queue: mirror the newest pending step,
        repeat until nothing is pending, then retire. Runs on the single
        mirror thread."""
        while True:
            with self._mirror_cond:
                step = self._mirror_pending
                self._mirror_pending = None
                if step is None:
                    self._mirror_inflight = False
                    self._mirror_cond.notify_all()
                    return
            self._mngr.wait_until_finished()
            try:
                self._mirror_step(step)
                with self._mirror_cond:
                    self._mirror_counts["mirrored"] += 1
                    self._last_mirrored_step = step
            except Exception:
                with self._mirror_cond:
                    self._mirror_counts["failures"] += 1
                _log.exception("remote mirror of step %d failed; continuing", step)

    def mirror_stats(self) -> dict:
        """Mirror-health snapshot for the learner's metrics stream.
        `lag_steps` is newest-saved minus newest-mirrored, in STEP-LABEL
        units: healthy steady state oscillates between 0 and
        checkpoint_every while an upload is in flight; alert on growth
        across windows (with coalescing, growth shows up in `superseded`
        climbing too — ADVICE r4, a metric instead of a warning log).
        None until the first mirror completes: before that there is no
        mirrored step to measure against (a resumed learner at step 10k
        must not report lag=10k during its first healthy upload — r5
        review finding). Empty dict when no push mirror is configured."""
        if self._mirror_pool is None:
            return {}
        with self._mirror_cond:
            lag = None
            if self._last_saved_step is not None and self._last_mirrored_step is not None:
                lag = self._last_saved_step - self._last_mirrored_step
            return {
                "last_saved_step": self._last_saved_step,
                "last_mirrored_step": self._last_mirrored_step,
                "lag_steps": lag,
                **self._mirror_counts,
            }

    # ---------------------------------------------------------- mirroring

    def _copy_file(self, src: epath.Path, dst: epath.Path) -> None:
        # Bounded-memory streaming (r4 known debt): epath handles expose
        # file objects for every scheme fsspec mounts, so a multi-GB
        # tensorstore shard copies at `_copy_chunk` resident bytes, not
        # its full size.
        with src.open("rb") as fin, dst.open("wb") as fout:
            while True:
                buf = fin.read(self._copy_chunk)
                if not buf:
                    break
                fout.write(buf)

    def _copy_tree(self, src: epath.Path, dst: epath.Path) -> None:
        dst.mkdir(parents=True, exist_ok=True)
        for child in src.iterdir():
            if child.is_dir():
                self._copy_tree(child, dst / child.name)
            else:
                self._copy_file(child, dst / child.name)

    def _mirror_step(self, step: int) -> None:
        """File-level upload of the FINISHED local step dir + schema stamp
        to remote_dir; the _STEP_DONE marker lands last so a reader never
        trusts a partially-uploaded step. Mirrors the local max_to_keep GC."""
        local_step = self._dir / str(step)
        if not local_step.exists():  # orbax step layout is <dir>/<step>/
            _log.warning("mirror: local step dir %s missing; skipping", local_step)
            return
        remote_step = self._remote / str(step)
        self._copy_tree(local_step, remote_step)
        # Full-state aux sidecar rides the mirror BEFORE the marker, so a
        # marked remote step always has its complete manifest alongside.
        local_aux = self._dir / _AUX_FMT.format(step)
        if local_aux.exists():
            _atomic_write(self._remote / _AUX_FMT.format(step), local_aux.read_bytes())
        # Version high-water rides every mirror (as-of-mirror-time): a
        # fresh pod restoring from the mirror alone must not under-bump
        # its counter below versions the fleet has already seen.
        # Best-effort by construction — publishes between the last
        # mirror and a kill are only in the LOCAL hwm file — but the
        # boot-epoch resync bounds the residual window: actors re-stamp
        # against the reborn learner as soon as its first fanout lands.
        hwm = self.published_hwm()
        if hwm is not None:
            _atomic_write(self._remote / _HWM_FILE, str(hwm).encode())
        _atomic_write(
            self._remote / "feature_schema.json",
            json.dumps({"feature_schema_version": FEATURE_SCHEMA_VERSION}).encode(),
        )
        # Marker publish is atomic (tmp + replace): a reader listing the
        # remote can never see a half-written marker file and trust an
        # incomplete step.
        _atomic_write(remote_step / _STEP_DONE, b"ok")
        # GC: keep the newest max_to_keep COMPLETE steps; also sweep
        # UNMARKED step dirs other than the one just written — a crash
        # mid-upload leaves a markerless dir no future run completes
        # (steps are monotonic, single writer), and the marker filter in
        # _remote_steps would otherwise hide it from GC forever.
        complete = set(self._remote_steps())
        for child in self._remote.iterdir():
            name = child.name
            if name.isdigit() and int(name) != step and int(name) not in complete:
                child.rmtree()
            elif name.startswith("aux_") and name.endswith(".bin"):
                stem = name[4:-4]
                if stem.isdigit() and int(stem) != step and int(stem) not in complete:
                    child.unlink()
        for old in sorted(complete)[: -self._max_to_keep]:
            (self._remote / str(old)).rmtree()
            old_aux = self._remote / _AUX_FMT.format(old)
            if old_aux.exists():
                old_aux.unlink()

    def _remote_steps(self):
        if self._remote is None or not self._remote.exists():
            return []
        out = []
        for child in self._remote.iterdir():
            if child.name.isdigit() and (child / _STEP_DONE).exists():
                out.append(int(child.name))
        return out

    def pull_latest_remote(self, steps=None) -> Optional[int]:
        """Download the newest COMPLETE remote step into the local dir.
        Returns the step, or None. `steps` lets restore_latest pass the
        listing it already paid for (remote LIST + per-step marker checks
        are round trips on real object stores).

        The download lands in a dot-prefixed temp dir and RENAMES into
        place: an interrupted pull must never leave a partial dir under
        the final step name — orbax would list it as a finalized step,
        local latest would equal newest remote, the re-pull gate would
        never fire again, and restore would crash-loop with the good
        checkpoint one pull away (r4 review finding)."""
        if steps is None:
            steps = self._remote_steps()
        # The pull races the primary's remote GC (ADVICE r4): on a slow
        # download the chosen step can fall out of the newest-max_to_keep
        # window mid-copy and vanish under us. That is a retry-with-a-
        # newer-step situation, not a crash-loop: re-list and go again,
        # bounded.
        for attempt in range(4):
            if not steps:
                return None
            step = max(steps)
            src = self._remote / str(step)
            tmp = self._dir / f".pull_{step}"  # dot-prefixed: invisible to orbax's step scan
            if tmp.exists():
                tmp.rmtree()  # leftover from an interrupted pull
            try:
                self._copy_tree(src, tmp)
                (tmp / _STEP_DONE).unlink()  # marker is a mirror artifact, not orbax's
                break
            except FileNotFoundError:
                if tmp.exists():
                    tmp.rmtree()
                if attempt == 3:
                    raise
                _log.warning(
                    "remote step %d vanished mid-pull (primary GC); re-listing", step
                )
                steps = self._remote_steps()
        dst = self._dir / str(step)
        if dst.exists():
            dst.rmtree()  # stale/partial local copy loses to the verified pull
        tmp.rename(dst)
        # Pull the step's aux manifest too (full-state restores on a
        # fresh pod need the reservoir/RNG/hwm, not just the arrays);
        # absent remotely ⇒ a legacy step, restore proceeds state-only.
        remote_aux = self._remote / _AUX_FMT.format(step)
        if remote_aux.exists():
            _atomic_write(self._dir / _AUX_FMT.format(step), remote_aux.read_bytes())
        # Reconcile the version high-water DOWNWARD never: a stale local
        # file (in-place container restart) may be ahead of the mirror's
        # copy — max wins, monotonicity is the whole point.
        remote_hwm = self._remote / _HWM_FILE
        if remote_hwm.exists():
            try:
                rh: Optional[int] = int(remote_hwm.read_text().strip())
            except (ValueError, OSError):
                rh = None
            if rh is not None:
                lh = self.published_hwm()
                if lh is None or rh > lh:
                    _atomic_write(self._dir / _HWM_FILE, str(rh).encode())
        remote_schema = self._remote / "feature_schema.json"
        if remote_schema.exists():
            self._schema_path().write_text(remote_schema.read_text())
        # CheckpointManager scanned the directory at construction; rebuild
        # so it sees the pulled step.
        self._mngr.close()
        self._mngr = ocp.CheckpointManager(
            self._dir,
            options=ocp.CheckpointManagerOptions(max_to_keep=self._max_to_keep, create=True),
        )
        _log.info("pulled remote checkpoint step %d from %s", step, self._remote)
        return step

    def restore_latest(self, template) -> Optional[object]:
        step = self._mngr.latest_step()
        if self._remote is not None:
            # Pull when the remote holds a NEWER complete step, not only
            # when local is empty: after a mid-save crash a host whose
            # container restarted in place (emptyDir intact) can hold a
            # stale local step — resuming from it would trip the
            # multihost resume-consistency guard forever while the fix
            # sits one pull away in the mirror.
            remote_steps = self._remote_steps()
            newest_remote = max(remote_steps) if remote_steps else None
            if newest_remote is not None and (step is None or newest_remote > step):
                if self.pull_latest_remote(steps=remote_steps) is not None:
                    step = self._mngr.latest_step()
        if step is None:
            return None
        p = self._schema_path()
        if p.exists():
            saved = json.loads(p.read_text()).get("feature_schema_version")
            if saved != FEATURE_SCHEMA_VERSION:
                raise SchemaMismatchError(
                    f"checkpoint at {self._dir} was written with feature "
                    f"schema v{saved}, this build uses v{FEATURE_SCHEMA_VERSION} "
                    f"(env/featurizer.py history) — param shapes will not "
                    f"restore; retrain or convert the checkpoint"
                )
        return self._mngr.restore(step, args=ocp.args.StandardRestore(template))

    def latest_step(self) -> Optional[int]:
        return self._mngr.latest_step()

    def close(self) -> None:
        self._mngr.wait_until_finished()
        # Drain order matters: the aux finalize worker is what SUBMITS
        # mirror jobs on the full-state path, so it drains first — a
        # mirror shutdown that ran first could miss the final step's
        # upload that the aux worker was about to queue.
        with self._aux_cond:
            aux_pool = self._aux_pool
        if aux_pool is not None:
            aux_pool.shutdown(wait=True)  # drain pending aux manifests
        if self._mirror_pool is not None:
            self._mirror_pool.shutdown(wait=True)  # drain pending uploads
        self._orbax_pool.shutdown(wait=True)
        self._mngr.close()
