"""Metrics/observability — TensorBoard + JSONL.

The reference logs scalars through tensorboardX's SummaryWriter in
optimizer.py (SURVEY.md §5 "Metrics"): losses, entropy, grad norm,
reward components, steps/s, win rate. Scalar names are kept identical so
training curves are directly comparable. The TB dependency is soft
(torch's SummaryWriter if importable); a JSONL stream is always written
so headless runs and tests can assert on metrics without TB.
"""

from __future__ import annotations

import json
import os
import time
from typing import Dict, Optional


def histogram_scalars(prefix: str, edges, counts) -> Dict[str, float]:
    """Flatten a bucketed histogram into the TB/JSONL-friendly scalar
    names this logger speaks: `{prefix}_le_{edge}` per closed bucket plus
    `{prefix}_gt_{last_edge}` for the open tail. `counts` has
    len(edges)+1 entries. Used for the replay reservoir's replayed-frame
    age histogram (dotaclient_tpu/replay/reservoir.py) — scalars per
    bucket keep the stream greppable and TB-plottable without a
    histogram proto dependency. Empty `edges` means there is no
    bucketing to name — return {} rather than index edges[-1]."""
    if not len(edges):
        return {}
    out = {f"{prefix}_le_{edge}": float(counts[i]) for i, edge in enumerate(edges)}
    out[f"{prefix}_gt_{edges[-1]}"] = float(counts[len(edges)])
    return out


class MetricsLogger:
    def __init__(self, log_dir: str = "", flush_every: int = 20):
        self._tb = None
        self._jsonl = None
        self._flush_every = max(int(flush_every), 1)
        self._writes = 0
        self._closed = False
        # Latest logged record, served by the obs /metrics scrape surface
        # (obs/http.py): updated once per metrics window, never on the
        # per-row hot path. ONE tuple, replaced atomically, so readers on
        # other threads (scrape server, watchdog) can never pair one
        # window's step with another window's scalars.
        self._latest_rec: tuple = (-1, {})
        if log_dir:
            os.makedirs(log_dir, exist_ok=True)
            self._jsonl = open(os.path.join(log_dir, "metrics.jsonl"), "a", buffering=1)
            try:
                from torch.utils.tensorboard import SummaryWriter

                self._tb = SummaryWriter(log_dir)
            except Exception:
                self._tb = None

    def log(self, step: int, scalars: Dict[str, float]) -> None:
        # Post-close logging is a no-op, not an IO error: phased drivers
        # (and the learner's re-entrant run()) may race a final metrics
        # window against teardown, and a closed JSONL handle must not
        # turn a clean shutdown into a crash.
        if self._closed:
            return
        clean = {k: float(v) for k, v in scalars.items()}
        self._latest_rec = (step, clean)
        if self._jsonl is not None:
            rec = {"step": step, "time": time.time()}
            rec.update(clean)
            self._jsonl.write(json.dumps(rec) + "\n")
        if self._tb is not None:
            for k, v in clean.items():
                self._tb.add_scalar(k, v, step)
        # Flush pacing counts WRITES, uniformly: previously the counter
        # only advanced when TB was importable, so the documented pacing
        # was dead code on every headless host. JSONL is line-buffered,
        # but an explicit periodic flush also covers exotic buffering
        # (and keeps TB/JSONL on one cadence).
        self._writes += 1
        if self._writes % self._flush_every == 0:
            self.flush()

    def latest(self) -> Dict[str, float]:
        """Most recent scalars handed to log() (empty before the first
        window). Returns a copy — scrape threads must not alias the dict
        the logging thread will replace."""
        return dict(self._latest_rec[1])

    def latest_step(self) -> int:
        """Step of the most recent log() (-1 before the first window):
        the metrics-window identity. The watchdog keys once-per-window
        judging on this — latest() refreshes only once per window, and a
        detector polling faster than the log cadence must not re-judge
        (or re-sample) a window it has already seen. Steps are monotonic,
        so a caller reading step → latest() → step again and seeing the
        same value knows the middle read came from that exact window."""
        return self._latest_rec[0]

    def flush(self) -> None:
        if self._closed:
            return
        if self._tb is not None:
            self._tb.flush()
        if self._jsonl is not None:
            self._jsonl.flush()

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        if self._tb is not None:
            self._tb.flush()
            self._tb.close()
        if self._jsonl is not None:
            self._jsonl.close()
