"""Learner loop — the re-design of the reference's optimizer.py
(SURVEY.md §2 "Learner", §3.2 call stack).

Reference flow per iteration: consume N rollouts → pad/stack →
teacher-forced re-eval → GAE → PPO step → publish versioned weights →
checkpoint → TensorBoard. Here the device-side middle is ONE compiled
SPMD program over the mesh (parallel/train_step.py) and the host side
is the staging buffer (runtime/staging.py); this module owns the loop:

    staging.get_batch → device_put(dp-sharded) → train_step
    → every publish_every steps: device_get params → weight fanout
    → every checkpoint_every steps: orbax checkpoint
    → metrics (reference scalar names) + steps/s + staleness stats

The python-side `version` counter mirrors state.step without forcing a
device sync every iteration; it is the version actors stamp on their
rollouts and the learner's staleness filter reads.
"""

from __future__ import annotations

import logging
import time
from typing import Optional

import jax
import numpy as np

from dotaclient_tpu.config import LearnerConfig
from dotaclient_tpu.parallel import mesh as mesh_lib
from dotaclient_tpu.parallel.train_step import (
    TrainState,
    build_train_step,
    init_train_state,
)
from dotaclient_tpu.runtime.metrics import MetricsLogger
from dotaclient_tpu.runtime.staging import StagingBuffer
from dotaclient_tpu.transport.base import Broker
from dotaclient_tpu.transport.serialize import flatten_params, serialize_weights

_log = logging.getLogger(__name__)


class Learner:
    def __init__(self, cfg: LearnerConfig, broker: Broker, mesh=None):
        self.cfg = cfg
        self.broker = broker
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(cfg.mesh_shape)
        self.train_step, self.state_shardings, self.batch_sharding = build_train_step(cfg, self.mesh)
        self.version = 0
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
        self.state: TrainState = jax.device_put(state, self.state_shardings)
        self.staging = StagingBuffer(cfg, broker, version_fn=lambda: self.version)
        self.metrics = MetricsLogger(cfg.log_dir)
        if cfg.profile_port:
            # device-trace endpoint (SURVEY.md §5 tracing note): attach
            # TensorBoard's profiler or jax.profiler.trace to this port
            jax.profiler.start_server(cfg.profile_port)
        self.checkpointer = None
        if cfg.checkpoint_dir:
            from dotaclient_tpu.runtime.checkpoint import Checkpointer

            self.checkpointer = Checkpointer(cfg.checkpoint_dir)
            restored = self.checkpointer.restore_latest(self.state)
            if restored is not None:
                self.state = jax.device_put(restored, self.state_shardings)
                self.version = int(jax.device_get(restored.step))
                _log.info("restored checkpoint at step %d", self.version)

    # ---------------------------------------------------------------- ops

    def publish_weights(self) -> None:
        params = jax.device_get(self.state.params)
        frame = serialize_weights(flatten_params(params), version=self.version)
        self.broker.publish_weights(frame)

    def checkpoint(self) -> None:
        if self.checkpointer is not None:
            self.checkpointer.save(jax.device_get(self.state), step=self.version)

    # --------------------------------------------------------------- loop

    def run(self, num_steps: Optional[int] = None, batch_timeout: float = 60.0) -> int:
        """Train until num_steps (None = forever); returns steps done."""
        cfg = self.cfg
        self.staging.start()
        self.publish_weights()  # version 0 so actors align immediately
        env_steps_per_batch = None
        done_steps = 0
        t_last = time.perf_counter()
        try:
            while num_steps is None or done_steps < num_steps:
                t0 = time.perf_counter()
                batch = self.staging.get_batch(timeout=batch_timeout)
                if batch is None:
                    _log.warning("no batch within %.0fs; waiting", batch_timeout)
                    continue
                if env_steps_per_batch is None:
                    env_steps_per_batch = float(np.sum(batch.mask))
                t1 = time.perf_counter()
                batch_dev = jax.device_put(batch, self.batch_sharding)
                t2 = time.perf_counter()
                self.state, metrics = self.train_step(self.state, batch_dev)
                self.version += 1
                done_steps += 1

                if self.version % cfg.publish_every == 0:
                    self.publish_weights()
                if self.checkpointer is not None and self.version % cfg.checkpoint_every == 0:
                    self.checkpoint()

                # device_get below doubles as the per-step device sync, so
                # the step timer includes real device time, not dispatch
                scalars = {k: float(v) for k, v in jax.device_get(metrics).items()}
                now = time.perf_counter()
                stats = self.staging.stats()
                scalars["env_steps_per_sec"] = float(np.sum(batch.mask)) / max(now - t_last, 1e-9)
                # per-stage timing (SURVEY.md §5: consume / pack / put / step)
                scalars["time_wait_batch_s"] = t1 - t0
                scalars["time_device_put_s"] = t2 - t1
                scalars["time_step_s"] = now - t2
                scalars["active_actors"] = stats["active_actors"]
                scalars["staleness_dropped"] = stats["dropped_stale"]
                scalars["queue_ready"] = stats["ready_batches"]
                scalars["episodes"] = stats["episodes"]
                if stats["episodes"] > 0:
                    scalars["mean_episode_return"] = stats["episode_return_sum"] / stats["episodes"]
                self.metrics.log(self.version, scalars)
                t_last = now
        finally:
            self.staging.stop()
            self.metrics.close()
        return done_steps


def main(argv=None):
    from dotaclient_tpu.config import parse_config
    from dotaclient_tpu.transport.base import connect as broker_connect

    logging.basicConfig(level=logging.INFO)
    cfg = parse_config(LearnerConfig(), argv)
    if cfg.platform:
        jax.config.update("jax_platforms", cfg.platform)
    broker = broker_connect(cfg.broker_url)
    learner = Learner(cfg, broker)
    _log.info(
        "learner up: mesh=%s batch=%dx%d devices=%d",
        cfg.mesh_shape,
        cfg.batch_size,
        cfg.seq_len,
        len(jax.devices()),
    )
    learner.run()


if __name__ == "__main__":
    main()
