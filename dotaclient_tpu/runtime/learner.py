"""Learner loop — the re-design of the reference's optimizer.py
(SURVEY.md §2 "Learner", §3.2 call stack).

Reference flow per iteration: consume N rollouts → pad/stack →
teacher-forced re-eval → GAE → PPO step → publish versioned weights →
checkpoint → TensorBoard. Here the device-side middle is ONE compiled
SPMD program over the mesh (parallel/train_step.py) and the host side
is the staging buffer (runtime/staging.py); this module owns the loop:

    staging.get_batch → device_put(dp-sharded) → train_step
    → every publish_every steps: device_get params → weight fanout
    → every checkpoint_every steps: orbax checkpoint
    → metrics (reference scalar names) + steps/s + staleness stats

The python-side `version` counter mirrors state.step without forcing a
device sync every iteration; it is the version actors stamp on their
rollouts and the learner's staleness filter reads.

Pipelining (--learner.prefetch, default ON — the ISSUE-15 overlapped
loop): the loop never blocks on the device except where semantics
require it —
- a dedicated PREFETCH LANE thread runs the whole host side of batch
  N+1 — staging pop, pack wait, device_put dispatch, transfer retire,
  ring-lease release — WHILE the device executes train step N, so the
  loop thread's per-iteration host cost collapses to one queue pop plus
  the async train-step dispatch (double buffering with a real second
  lane, not just jax async dispatch; OVERLAP_AB.json commits the
  serial-vs-pipelined evidence and the bitwise-params parity proof);
- metrics are device_get only every `metrics_every` steps (each fetch is
  a full device sync);
- weight publishes dispatch ONE on-device flatten (ParamFlattener) and
  hand the device buffer to a dedicated publisher thread, which pays
  the blocking single-transfer host read + serialize + broker I/O with
  latest-wins coalescing. Stream ordering keeps this safe against the
  train step's state donation (flatten is dispatched first, on the loop
  thread — the lane never touches the state);
- `--learner.prefetch false` restores the serial fetch-after-step loop
  byte-for-byte (no lane thread, no pipeline_* scalars — the rollback
  path, MIGRATION item 15).
"""

from __future__ import annotations

import logging
import os
import queue
import threading
import time
from typing import NamedTuple, Optional

import jax
import numpy as np

from dotaclient_tpu.config import LearnerConfig
from dotaclient_tpu.parallel import mesh as mesh_lib
from dotaclient_tpu.parallel.train_step import (
    TrainState,
    build_train_step,
    init_train_state,
)
from dotaclient_tpu.runtime.metrics import MetricsLogger
from dotaclient_tpu.runtime.staging import StagingBuffer
from dotaclient_tpu.transport import serialize as serialize_mod
from dotaclient_tpu.transport.base import Broker
from dotaclient_tpu.transport.serialize import flatten_params, serialize_weights

_log = logging.getLogger(__name__)


class ParamFlattener:
    """ONE device→host transfer per weight publish instead of one per
    param leaf.

    The flagship params tree has ~30 leaves; over the tunneled chip each
    D2H read pays ~0.28 ms of RPC latency (the same per-transfer
    overhead parallel/fused_io.py fixed on the H2D side), so a per-leaf
    device_get costs ~8 ms — ON THE LOOP THREAD, every publish_every
    steps. Instead a tiny jit concatenates the raveled leaves into one
    f32 buffer ON DEVICE (async dispatch, ~1 copy of ~1 MB); the
    blocking host read of that single buffer happens on the publisher
    thread. Stream ordering makes this donation-safe: the flatten
    program is dispatched BEFORE the next (state-donating) train step,
    so it reads the params before donation can reuse them.
    """

    def __init__(self, params_template):
        self._slots = []  # (name, shape, start, size) in canonical order
        off = 0
        for name, leaf in serialize_mod.named_param_leaves(params_template):
            n = int(np.prod(leaf.shape, dtype=np.int64)) if leaf.ndim else 1
            self._slots.append((name, tuple(leaf.shape), off, n))
            off += n

        def flat_fn(params):
            import jax.numpy as jnp

            leaves = serialize_mod.named_param_leaves(params)
            return jnp.concatenate([jnp.ravel(l).astype(jnp.float32) for _, l in leaves])

        self._jit = jax.jit(flat_fn)

    def flatten_on_device(self, params):
        """Async-dispatched; returns the device buffer immediately."""
        return self._jit(params)

    def to_named(self, flat_dev) -> list:
        """Blocking host read + split — publisher-thread side. Output
        matches transport.serialize.flatten_params exactly."""
        flat = np.asarray(flat_dev, dtype=np.float32)
        return [
            (name, flat[start : start + size].reshape(shape))
            for name, shape, start, size in self._slots
        ]


class WeightPublisher:
    """Serialize + fanout weights off the train-loop thread.

    Latest-wins single slot: if the loop submits version v+1 while v is
    still serializing, v is superseded — actors only ever want the
    newest weights (transport/base.py fanout semantics), so coalescing
    is correct, not lossy. The expensive work (host read of the fused
    param buffer + wire framing + broker I/O) happens here; the loop
    thread only pays an async jit dispatch.

    `materialize(payload) -> named (name, f32 array) list` converts
    whatever the loop submitted on THIS thread; the default handles a
    host params pytree (tests, simple drivers), the Learner passes
    `ParamFlattener.to_named` with a device buffer payload.
    """

    def __init__(
        self,
        broker: Broker,
        materialize=None,
        boot_epoch: int = 0,
        legacy_dtw1: bool = False,
        on_published=None,
    ):
        self._materialize = materialize if materialize is not None else flatten_params
        self._broker = broker
        self._boot_epoch = boot_epoch
        self._legacy_dtw1 = legacy_dtw1
        # Post-send hook, called on THIS thread with the version just
        # fanned out. The full-state checkpointer persists its version
        # high-water mark here (runtime/checkpoint.py) — off the train
        # loop by construction. None = no extra work per publish.
        self._on_published = on_published
        self._cond = threading.Condition()
        self._slot = None  # (np_params, version) — latest pending
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.published = 0  # versions actually sent (telemetry/tests)
        self.coalesced = 0  # versions superseded before sending

    def start(self) -> "WeightPublisher":
        # restartable after stop(), same contract as StagingBuffer.start.
        # If a previous thread is still draining (stop()'s bounded join
        # timed out on a hung broker), it stays the active thread — it
        # will see _stop=False and keep serving; spawning a second one
        # would race two publishers and could deliver stale versions
        # after newer ones.
        with self._cond:
            self._stop = False
            if self._thread is not None and self._thread.is_alive():
                self._cond.notify()
                return self
            # Publish the new handle under the SAME lock hold that decided
            # a new thread is needed — an old thread's exit path nulls
            # _thread under this lock, so assigning outside it could let
            # that late null clobber the fresh handle.
            t = threading.Thread(target=self._run, daemon=True, name="weight-publisher")
            self._thread = t
            # start under the same hold: a stop() sneaking in after the
            # release would otherwise join an unstarted thread
            # (RuntimeError), and a second start() would see
            # is_alive()==False and spawn a duplicate publisher. The
            # worker's first act is acquiring this cond, so it simply
            # blocks until we release.
            t.start()
        return self

    def submit(self, np_params, version: int) -> None:
        with self._cond:
            if self._slot is not None:
                self.coalesced += 1
            self._slot = (np_params, version)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._slot is None and not self._stop:
                    self._cond.wait()
                if self._stop and self._slot is None:
                    # clear the handle under the SAME lock hold as the
                    # exit decision, so a concurrent start() never sees a
                    # thread that is alive but already committed to exit
                    self._thread = None
                    return
                np_params, version = self._slot
                self._slot = None
            try:
                frame = serialize_weights(
                    self._materialize(np_params),
                    version=version,
                    boot_epoch=self._boot_epoch,
                    legacy_dtw1=self._legacy_dtw1,
                )
                self._broker.publish_weights(frame)
                self.published += 1
                if self._on_published is not None:
                    self._on_published(version)
            except Exception:
                _log.exception("weight publish failed (version %d); continuing", version)

    def stop(self, flush: bool = True) -> None:
        """Stop the thread; by default drains a pending slot first."""
        with self._cond:
            if not flush:
                self._slot = None
            self._stop = True
            self._cond.notify()
            t = self._thread  # local ref: the thread nulls the handle on exit
        if t is not None:
            t.join(timeout=10)


class CheckpointWorker:
    """Off-critical-path full-state saver (--ckpt.async_save).

    The loop thread pays ONE async jit dispatch per checkpoint — an
    on-device copy of the TrainState, donation-safe for the same
    stream-ordering reason as ParamFlattener (the copy is dispatched
    before the next state-donating train step, so it reads the params
    before donation can reuse them). This thread then pays everything
    expensive: the blocking host read of the copy, the staging snapshot
    handshake, the manifest pickle, and the orbax/aux submit.

    Latest-wins single slot, the WeightPublisher coalescing argument:
    durability only ever needs the newest state, so if the loop submits
    step v+k while v is still saving, v is superseded — counted, never
    silently dropped.
    """

    def __init__(self, save_fn):
        self._save_fn = save_fn  # (host_state, version) -> None
        self._cond = threading.Condition()
        self._slot = None  # (state_copy_dev, version) — latest pending
        self._stop = False
        self._thread: Optional[threading.Thread] = None
        self.saved = 0  # checkpoints actually written (telemetry/tests)
        self.coalesced = 0  # checkpoints superseded before writing

    def start(self) -> "CheckpointWorker":
        with self._cond:
            self._stop = False
            if self._thread is not None and self._thread.is_alive():
                self._cond.notify()
                return self
            # Same handle-publish-under-the-lock discipline as
            # WeightPublisher.start (the late-null-clobber race).
            t = threading.Thread(target=self._run, daemon=True, name="ckpt-saver")
            self._thread = t
            t.start()
        return self

    def submit(self, state_copy_dev, version: int) -> None:
        with self._cond:
            if self._slot is not None:
                self.coalesced += 1
            self._slot = (state_copy_dev, version)
            self._cond.notify()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._slot is None and not self._stop:
                    self._cond.wait()
                if self._stop and self._slot is None:
                    self._thread = None
                    return
                state_dev, version = self._slot
                self._slot = None
            try:
                host_state = jax.device_get(state_dev)
                del state_dev  # release the device copy before the slow write
                self._save_fn(host_state, version)
                self.saved += 1
            except Exception:
                _log.exception("async checkpoint of step %d failed; continuing", version)

    def stop(self, flush: bool = True) -> None:
        """Stop the thread; by default drains a pending slot first."""
        with self._cond:
            if not flush:
                self._slot = None
            self._stop = True
            self._cond.notify()
            t = self._thread
        if t is not None:
            t.join(timeout=60)


class _LaneItem(NamedTuple):
    """One prefetch-lane handoff: kind ∈ {"batch", "idle", "exhausted",
    "error"}. `wait_s`/`put_s` are the lane's own fetch-wait and
    device-put attribution for the window accumulators (an "idle" item
    carries the empty wait so starvation stays visible)."""

    kind: str
    batch: object
    env_steps: int
    wait_s: float
    put_s: float
    trace: object
    error: Optional[BaseException]


class PrefetchLane:
    """The dedicated prefetch stage of the pipelined learner loop
    (--learner.prefetch): runs the WHOLE host side of batch N+1 —
    staging pop, pack wait, device_put dispatch, transfer retire, ring
    lease release — on its own thread while the loop thread keeps the
    device busy with step N, handing finished batches over a bounded
    queue (depth = --learner.prefetch_depth; 1 = classic double
    buffering).

    Ownership rules carried over from the serial loop, unchanged:
    - the lane is the ONE staging consumer, popping FIFO — batch order
      is identical to the serial loop, which is why the pipelined
      params are BITWISE equal to the serial params over the same
      frame schedule (OVERLAP_AB.json parity arm);
    - a ring lease is released only after ITS device_put retired
      (inside Learner._fetch_next — the PR-11 donation-safety rule;
      the lane moves the release off the loop thread, it never moves
      it before the retire);
    - `holding()` makes a popped-but-untrained batch visible to
      staging.drained() as the prefetch station, so the PR-7 SIGTERM
      zero-loss contract extends through the lane: a drain trains the
      in-flight prefetched batch out, never drops it.

    Budget (`limit` = the run's num_steps): the lane never fetches more
    batches than the loop will train, so a finite phased run
    (train → eval → train, scripts/train_north_star.py) cannot eat and
    discard a trailing batch — exactly the serial loop's
    no-trailing-prefetch rule. Empty waits ("idle" items) consume no
    budget. Fetch errors surface on the loop thread via "error" items
    (the staging _check_fatal fast-failure contract survives the lane).
    """

    def __init__(
        self,
        fetch_fn,
        depth: int = 1,
        limit: Optional[int] = None,
        drain: Optional[threading.Event] = None,
        abort: Optional[threading.Event] = None,
        upstream_drained=None,
        stop_event: Optional[threading.Event] = None,
    ):
        self._fetch = fetch_fn  # () -> (batch, env_steps, wait_s, put_s, trace)
        self._out: "queue.Queue[_LaneItem]" = queue.Queue(maxsize=max(int(depth), 1))
        self._limit = limit
        self._drain = drain
        self._abort = abort
        self._upstream_drained = upstream_drained
        # Doubles as the staging-getter cancel hook (the caller threads
        # it into _fetch_next): a stopping lane aborts its in-flight
        # wait within one 0.2s slice instead of sitting out a full
        # batch timeout (and overlapping a successor lane's pops on a
        # phased driver's next run()).
        self.stop_event = stop_event if stop_event is not None else threading.Event()
        # True from just before a fetch (which may pop a batch into this
        # thread's locals) until the item is in the handoff queue — the
        # drained() visibility contract (the _popping/_packing pattern,
        # one station further downstream). Atomically-rebound bool,
        # read once by holding().
        self._inflight = False
        self._thread: Optional[threading.Thread] = None
        self.fetched = 0  # successful batches delivered (telemetry/tests)

    def start(self) -> "PrefetchLane":
        t = threading.Thread(target=self._run, daemon=True, name="learner-prefetch")
        self._thread = t
        t.start()
        return self

    def holding(self) -> bool:
        """True while the lane holds popped-but-untrained frames — in
        its thread locals (mid-fetch) or the handoff queue. This is
        staging's prefetch drained() station; single reads of a
        rebound bool + one queue empty-check (gauge semantics: a
        False->True flicker only delays a drain verdict one poll)."""
        inflight = self._inflight
        return inflight or not self._out.empty()

    def get(self, timeout: float) -> _LaneItem:
        """Next handoff item (the loop thread's side). Raises
        queue.Empty on timeout — callers poll in short slices so
        abort/deadline stay responsive."""
        return self._out.get(timeout=timeout)

    def _put(self, item: _LaneItem) -> None:
        while not self.stop_event.is_set():
            try:
                self._out.put(item, timeout=0.2)
                return
            except queue.Full:
                continue

    def _run(self) -> None:
        while not self.stop_event.is_set():
            if self._limit is not None and self.fetched >= self._limit:
                # Budget consumed: every batch the loop will train is
                # fetched (or queued) — never eat a trailing batch.
                return
            self._inflight = True
            try:
                try:
                    batch, env_steps, wait_s, put_s, trace = self._fetch()
                except BaseException as e:  # surfaces on the loop thread
                    self._put(_LaneItem("error", None, 0, 0.0, 0.0, None, e))
                    return
                if batch is None:
                    if self._abort is not None and self._abort.is_set():
                        return
                    if (
                        self._drain is not None
                        and self._drain.is_set()
                        and (
                            self._upstream_drained is None
                            or self._upstream_drained()
                        )
                    ):
                        # SIGTERM drain: nothing upstream will ever
                        # arrive again. FIFO guarantees this lands
                        # AFTER any still-queued batch, so the loop
                        # trains everything out first.
                        self._put(_LaneItem("exhausted", None, 0, wait_s, 0.0, None, None))
                        return
                    self._put(_LaneItem("idle", None, 0, wait_s, 0.0, None, None))
                    continue
                self.fetched += 1
                self._put(_LaneItem("batch", batch, env_steps, wait_s, put_s, trace, None))
            finally:
                # Cleared AFTER the handoff put: the queue's own
                # non-emptiness covers the item from here, so holding()
                # never has a gap a drain could slip through.
                self._inflight = False

    def stop(self) -> None:
        self.stop_event.set()
        t = self._thread
        if t is not None:
            t.join(timeout=10)


class Learner:
    def __init__(self, cfg: LearnerConfig, broker: Broker, mesh=None):
        self.cfg = cfg
        self.broker = broker
        self.mesh = mesh if mesh is not None else mesh_lib.make_mesh(cfg.mesh_shape)
        # Overlapped step loop (--learner.prefetch, PrefetchLane): ON by
        # default; False restores the serial fetch-after-step loop
        # byte-for-byte (no lane thread, no pipeline_* scalars, no
        # staging probe — the flag-off inertness contract).
        pipeline_cfg = getattr(cfg, "learner", None)
        self._prefetch_enabled = bool(
            pipeline_cfg is not None and pipeline_cfg.prefetch
        )
        self._prefetch_depth = (
            max(int(pipeline_cfg.prefetch_depth), 1) if pipeline_cfg is not None else 1
        )
        # The live lane of the CURRENT run() (None between runs and in
        # serial mode); staging's prefetch drained() station reads it
        # through _prefetch_holding.
        self._prefetch_lane: Optional[PrefetchLane] = None
        # Fused 4-buffer H2D path when enabled and not sequence-parallel
        # (fused_io.py); per-leaf tree path otherwise. Same compiled math.
        # The replay reservoir also forces the tree path: the per-row
        # behavior_staleness stamp is not part of the fused transfer
        # layout, and replay targets data-starved regimes where the H2D
        # transfer-count overhead is not the bottleneck anyway.
        self.fused_io = None
        from dotaclient_tpu.parallel.train_step import is_sequence_parallel

        if cfg.fused_h2d and not is_sequence_parallel(cfg, self.mesh) and not cfg.replay.enabled:
            from dotaclient_tpu.parallel.train_step import (
                build_fused_train_step,
                build_single_train_step,
            )

            build = build_single_train_step if cfg.fused_single_h2d else build_fused_train_step
            self.train_step, self.state_shardings, self.fused_io = build(cfg, self.mesh)
            self.batch_sharding = None
        else:
            self.train_step, self.state_shardings, self.batch_sharding = build_train_step(
                cfg, self.mesh
            )
        self.version = 0
        # Drawn once per learner process and stamped into every weight
        # frame: subscribers detect a restart by the epoch CHANGING, not
        # by counting suspicious frames (runtime/actor.py
        # apply_weight_frame). Time ^ pid so two boots in the same second
        # still differ.
        self.boot_epoch = (int(time.time()) << 8 ^ os.getpid()) & 0xFFFFFFFF
        state = init_train_state(cfg, jax.random.PRNGKey(cfg.seed))
        self.state: TrainState = jax.device_put(state, self.state_shardings)
        # Multi-process (--multihost over DCN): batch_size stays GLOBAL;
        # each process's staging packs its share and _fetch_next stitches
        # the shares into one global array (standard multihost DP). The
        # broker is a SHARED cluster service (k8s: one broker every actor
        # and every learner host connects to): experience consumption
        # splits the shared queue across hosts, and weight publishing is
        # gated to process 0 so the fanout carries ONE frame per version
        # — a topology with per-host private brokers would starve
        # non-primary hosts' actors of weights and, once the version
        # outran max_staleness, deadlock the cluster in the collectives.
        self._n_proc = jax.process_count()
        self._primary = jax.process_index() == 0
        staging_cfg = cfg
        if self._n_proc > 1:
            import copy

            if cfg.batch_size % self._n_proc:
                raise ValueError(
                    f"batch_size={cfg.batch_size} must divide by the process "
                    f"count ({self._n_proc}) — each host stages an equal share"
                )
            # The dp axis must span the processes: each process's
            # addressable dp shards are where its local rows land. A
            # tp-only / replicated-batch mesh would make the per-process
            # shares incoherent under one 'replicated' global array.
            dp_size = dict(zip(self.mesh.axis_names, self.mesh.devices.shape)).get("dp", 1)
            if dp_size % self._n_proc:
                raise ValueError(
                    f"multihost needs the mesh dp axis to span the processes: "
                    f"dp={dp_size} not divisible by process count {self._n_proc} "
                    f"(mesh {cfg.mesh_shape!r})"
                )
            # dp must be the MAJOR mesh axis: jax.devices() orders
            # process-major, so a dp-major mesh gives each process a
            # contiguous block of dp shards (its local batch rows land on
            # its own devices) and any minor axis (tp/sp) stays WITHIN a
            # process — make_array_from_process_local_data is only
            # assembling along dp. A mesh like "sp=4,dp=2" would
            # interleave processes along sp and scatter each host's rows
            # across hosts. The invariant is "no axis of size > 1 ahead
            # of dp", not dp-literally-first: "tp=1,dp=8" is fine.
            names = list(self.mesh.axis_names)
            sizes = list(self.mesh.devices.shape)
            ahead = 1
            for n, s in zip(names, sizes):
                if n == "dp":
                    break
                ahead *= s
            if ahead != 1:
                raise ValueError(
                    f"multihost needs 'dp' as the MAJOR mesh axis (no axis of "
                    f"size > 1 ahead of it); got {dict(zip(names, sizes))} — "
                    f"write --mesh_shape dp=...,<rest>"
                )
            if cfg.broker_url.startswith("mem://"):
                _log.warning(
                    "multihost with mem:// broker: in-process queues cannot span "
                    "hosts — fine for tests, wrong for production (use tcp://"
                    "or amqp:// shared by all hosts)"
                )
            staging_cfg = copy.deepcopy(cfg)
            staging_cfg.batch_size = cfg.batch_size // self._n_proc
            if self.fused_io is not None:
                self.fused_io.local_rows = staging_cfg.batch_size
        # fused mode: staging packs straight into the dtype-grouped
        # transfer buffers (leaf views), so _fetch_next ships `groups`
        # without the io.pack regroup copy (~0.7 ms/batch of host memcpy
        # at flagship shapes — critical-path time on a 1-core host).
        # Observability (dotaclient_tpu/obs/, --obs.*): None when off —
        # every obs touchpoint below is a single `is not None` check, so
        # the disabled hot path is unchanged.
        from dotaclient_tpu.obs import ObsRuntime

        self.obs = ObsRuntime.create(cfg.obs, role="learner")
        self.staging = StagingBuffer(
            staging_cfg,
            broker,
            version_fn=lambda: self.version,
            fused_io=self.fused_io,
            tracer=self.obs.tracer if self.obs is not None else None,
            recorder=self.obs.recorder if self.obs is not None else None,
        )
        if self._prefetch_enabled:
            # The prefetch station of the zero-loss drain contract: a
            # batch the lane popped but the loop has not trained is
            # visible to staging.drained() (PR-7, one station further
            # downstream). Serial mode attaches nothing.
            self.staging.attach_prefetch_probe(self._prefetch_holding)
        self.flattener = ParamFlattener(state.params)
        # Full-state mode: every fanned-out version is persisted as a
        # high-water mark (tiny atomic file, publisher thread) so a
        # SIGKILL between periodic checkpoints can never roll the
        # restored version counter below versions actors have already
        # stamped on rollouts. Lazy closure: the checkpointer is
        # constructed further down.
        on_pub = None
        if cfg.ckpt.full_state and cfg.checkpoint_dir:

            def on_pub(version):
                ck = self.checkpointer
                if ck is not None:
                    ck.record_published_version(version)

        self.publisher = WeightPublisher(
            broker,
            materialize=self.flattener.to_named,
            boot_epoch=self.boot_epoch,
            legacy_dtw1=cfg.publish_legacy_dtw1,
            on_published=on_pub,
        )
        self.metrics = MetricsLogger(cfg.log_dir)
        self._boot_monotonic = time.monotonic()
        if self.obs is not None:
            # Compute observability (obs/compute.py): the train step gets
            # the recompile sentinel (aval-signature hash + compile wall
            # + shape-diff to the flight recorder), MFU accounting gets
            # the analytic FLOPs model against the platform peak table,
            # and — when cfg.obs.step_phases — the loop runs phase-fenced
            # (run() below). With obs off, self.train_step stays the raw
            # jit object: byte-identical hot path, asserted in test_obs.
            from dotaclient_tpu.ops.flops import aggregate_peak_flops, train_step_flops

            compute = self.obs.attach_compute(
                train_step_flops(cfg),
                aggregate_peak_flops(jax.devices()),
                # Pipelined loop: the phase timer runs in OVERLAP mode —
                # fetch/pack/h2d recorded on the prefetch lane (fenced
                # there, hidden behind the device step), loop lane
                # reports take-wait/residual/host, pipeline_* scalars
                # carry the overlap accounting. No per-step fence.
                overlap=self._prefetch_enabled,
            )
            self.train_step = compute.wrap_train_step(self.train_step)
            # (The liveness watchdog attaches at the END of __init__,
            # after checkpoint restore — the restore's version write must
            # not read as the first train-step heartbeat, or boot grace
            # ends before the first step. serve_metrics binds the
            # watchdog's gauges late, so the ordering is safe.)
            # Scrape surface (obs/http.py): the latest logged scalars plus
            # live gauges sampled per scrape — queue depth straight from
            # the broker, staging/replay occupancy from stats(). Runs for
            # the process lifetime (run() is re-entrant); close() stops it.
            # /healthz serves the structured health body (503 once the
            # watchdog trips — the k8s liveness-probe contract) and POST
            # /profile captures on-demand jax.profiler traces.
            self.obs.serve_metrics(
                [self.metrics.latest, self._obs_gauges], health_provider=self._health
            )
        self.env_steps_done = 0  # total real (unmasked) env steps trained on
        if cfg.profile_port:
            # DEPRECATED (MIGRATION.md): the always-on profiler server is
            # superseded by on-demand POST /profile?seconds=N on the obs
            # metrics port, which needs no TensorBoard round-trip to
            # start a capture. Kept functional for one deprecation cycle.
            _log.warning(
                "--profile_port is deprecated; use POST /profile?seconds=N on "
                "the obs metrics port (--obs.metrics_port) instead"
            )
            jax.profiler.start_server(cfg.profile_port)
        # SIGTERM drain / kill plumbing (--ckpt.*): `_drain` asks run()
        # to stop fetching, train out already-staged batches, and return
        # (the caller then drain_save()s); `_abort` asks run() to return
        # IMMEDIATELY, discarding staged work — the chaos controller's
        # SIGKILL emulation. Both default-unset: the steady-state loop
        # pays one Event.is_set() per iteration.
        self._drain = threading.Event()
        self._abort = threading.Event()
        # Budget timer armed by the SIGTERM handler, cancelled by
        # drain_save() once the final save is durable.
        self._drain_timer: Optional[threading.Timer] = None
        # resume_* scalars (obs/registry.py): merged into the FIRST
        # metrics window after a restore so the resume is visible on the
        # dashboard, then cleared.
        self._resume_scalars = {}
        self._ckpt_worker: Optional[CheckpointWorker] = None
        self._state_copy_jit = None
        if cfg.ckpt.async_save and cfg.checkpoint_dir:
            # Built ONLY in async mode: with the flag off no extra jit
            # object exists and checkpoint() is the pre-existing
            # synchronous path (the inertness proof's contract).
            import jax.numpy as jnp

            self._state_copy_jit = jax.jit(
                lambda s: jax.tree.map(jnp.copy, s)
            )
            self._ckpt_worker = CheckpointWorker(self._save_full)
        self.checkpointer = None
        if cfg.checkpoint_dir:
            from dotaclient_tpu.runtime.checkpoint import Checkpointer

            # Every process can PULL the shared mirror (a restarted
            # non-primary pod must restore the same step or the
            # consistency check below trips); only process 0 PUSHES —
            # per-host duplicate uploads would race on the remote paths.
            self.checkpointer = Checkpointer(
                cfg.checkpoint_dir,
                remote_dir=cfg.checkpoint_remote_dir,
                remote_push=self._primary,
            )
            t_restore = time.monotonic()
            restored = self.checkpointer.restore_latest(self.state)
            if restored is not None:
                self.state = jax.device_put(restored, self.state_shardings)
                self.version = int(jax.device_get(restored.step))
                _log.info("restored checkpoint at step %d", self.version)
                if cfg.ckpt.full_state:
                    self._restore_full_state(t_restore)
        if self._n_proc > 1:
            # Restore is per-process and a partial host restart (one pod
            # with a fresh disk) would leave processes at DIFFERENT
            # steps/params inside one SPMD program — divergent reuse-loop
            # permutations, garbage gradients, no error. Refuse to start
            # unless every process agrees on the resume step.
            from jax.experimental import multihost_utils

            steps = np.asarray(
                multihost_utils.process_allgather(np.int64(self.version))
            ).reshape(-1)
            if len(set(int(s) for s in steps)) != 1:
                raise RuntimeError(
                    f"multihost restore mismatch: per-process resume steps "
                    f"{steps.tolist()} — restore every host from the same "
                    f"checkpoint (shared checkpoint_dir or remote mirror) "
                    f"before starting"
                )
            if cfg.ckpt.full_state:
                # Published-high-water bump, global max: only process 0
                # writes the hwm file, but every process must resume the
                # SAME version counter (staleness filtering is
                # per-process host work inside one SPMD program).
                hwm = int(
                    np.asarray(
                        multihost_utils.process_allgather(
                            np.int64(getattr(self, "_pending_hwm", self.version))
                        )
                    ).max()
                )
                if hwm > self.version:
                    self._resume_scalars["resume_version_hwm_bump"] = float(
                        hwm - self.version
                    )
                    _log.info(
                        "resume: version counter %d -> %d (global published "
                        "high-water)", self.version, hwm,
                    )
                    self.version = hwm
        if self.obs is not None:
            # Liveness watchdog (obs/watchdog.py, --obs.watchdog.*): reads
            # the telemetry the loop already produces; trips /healthz.
            # Attached LAST — after checkpoint restore has written
            # self.version — so the restore is the watchdog's baseline,
            # not its first heartbeat: a heartbeat-counted restore would
            # drop the stall threshold from boot_grace_s to stall_s
            # before the first (minutes-long) compile+first-batch wait,
            # and the k8s liveness probe would crashloop every restored
            # learner. latest_step keys the per-check freshness/dedup of
            # the metrics-window detectors.
            self.obs.attach_watchdog(
                self.metrics.latest, lambda: self.version, self.metrics.latest_step
            )

    # ---------------------------------------------------------------- ops

    def _prefetch_holding(self) -> bool:
        """staging.drained()'s prefetch station: True while the current
        run's lane holds popped-but-untrained frames. Single read of a
        rebound attribute — safe from any thread."""
        lane = self._prefetch_lane
        return lane is not None and lane.holding()

    def _obs_gauges(self):
        """Live gauges for the /metrics scrape (obs_ prefix = the
        scrape-only family in obs/registry.py). Sampled per scrape, off
        the train loop."""
        out = {"obs_learner_version": float(self.version)}
        depth = self.broker.experience_depth()
        if depth >= 0:  # -1 = this transport can't know it cheaply
            out["obs_broker_experience_depth"] = float(depth)
        for k, v in self.staging.stats().items():
            out[f"obs_staging_{k}"] = float(v)
        return out

    def _health(self):
        """The /healthz body (obs/http.py contract: "ok" selects the
        status code). A learner without a watchdog is healthy by virtue
        of serving; with one, the watchdog verdict decides."""
        # Runs on scrape handler threads while close() may null
        # obs.watchdog — bind once so the None-check and the verdict()
        # call observe the same object.
        obs = self.obs
        watchdog = obs.watchdog if obs is not None else None
        wd = (
            watchdog.verdict()
            if watchdog is not None
            else {"enabled": False, "ok": True}
        )
        return {
            "ok": bool(wd.get("ok", True)),
            "role": "learner",
            "version": int(self.version),
            "uptime_s": round(time.monotonic() - self._boot_monotonic, 1),
            "watchdog": wd,
        }

    def publish_weights(self) -> None:
        if not self._primary:
            return  # one fanout per version — process 0 publishes
        params = jax.device_get(self.state.params)
        frame = serialize_weights(
            flatten_params(params),
            version=self.version,
            boot_epoch=self.boot_epoch,
            legacy_dtw1=self.cfg.publish_legacy_dtw1,
        )
        self.broker.publish_weights(frame)

    def checkpoint(self, wait: bool = False) -> None:
        if self.checkpointer is None:
            return
        cfg = self.cfg.ckpt
        if not cfg.full_state and not cfg.async_save:
            # Pre-existing path, byte-identical on disk (the resume
            # soak's inertness proof pins this).
            self.checkpointer.save(jax.device_get(self.state), step=self.version)
            return
        if self._ckpt_worker is not None and not wait:
            # Loop thread pays one async on-device copy dispatch; the
            # worker pays the host read + snapshot + write. Dispatched
            # BEFORE the next (state-donating) train step, so stream
            # ordering makes the copy donation-safe (CheckpointWorker
            # docstring).
            self._ckpt_worker.start()
            self._ckpt_worker.submit(self._state_copy_jit(self.state), self.version)
            return
        self._save_full(jax.device_get(self.state), self.version, wait=wait)

    def _save_full(self, host_state, version: int, wait: bool = False) -> None:
        """Write one transactional full-state checkpoint: orbax step +
        aux manifest (RNG streams, reservoir, pending frames, publisher
        high-water mark). Runs on the CheckpointWorker thread in async
        mode, on the caller otherwise."""
        aux = None
        if self.cfg.ckpt.full_state:
            aux = self._build_aux(version)
        self.checkpointer.save(host_state, step=version, wait=wait, aux=aux)

    def _build_aux(self, version: int) -> bytes:
        """The aux manifest payload. Versioned and pickled — everything
        in it is host-side state the orbax arrays cannot carry:

        - the staging snapshot: pending (popped-but-untrained) frames in
          arrival order + the replay reservoir's entries, priorities,
          ABSOLUTE staleness stamps, and its numpy Generator state (the
          only host RNG stream the learner owns — the device-side
          shuffle rng is a pure fold_in(seed, state.step) and needs no
          capture, and a restored state.step replays it exactly);
        - the weight-publisher version high-water AS OF this step (the
          authoritative per-publish watermark is the hwm side-file,
          which the mirror also carries — restore takes the max of all
          three sources);
        - metrics/env-step high-water marks so the restored learner's
          telemetry continues instead of rewinding."""
        import pickle

        staging_snap = self.staging.snapshot_state() or {}
        manifest = {
            "manifest_version": 1,
            "step": int(version),
            "version_hwm": int(version),
            "boot_epoch": int(self.boot_epoch),
            "staging": staging_snap,
            "metrics_last_step": int(self.metrics.latest_step()),
            "env_steps_done": int(self.env_steps_done),
        }
        return pickle.dumps(manifest, protocol=4)

    def _restore_full_state(self, t_restore: float) -> None:
        """Rehydrate the host-side state the aux manifest carries and
        bump the version counter to the published high-water mark —
        rollouts already in flight are stamped with every version the
        fleet has seen, and a counter that restarted BELOW those stamps
        would compute negative staleness: under-aged experience passing
        the max_staleness filter and entering ACER with staleness 0.
        Monotonic-never-under-aged is the contract; over-aging (frames
        from the dead incarnation's last steps looking older than the
        redone steps they interleave with) is the safe direction, same
        as the PR-5 chunk-boundary version stamping."""
        import pickle

        step = self.checkpointer.latest_step()
        aux_bytes = self.checkpointer.load_aux(step)
        aux = None
        if aux_bytes is not None:
            try:
                aux = pickle.loads(aux_bytes)
            except Exception:
                _log.exception("aux manifest for step %s unreadable; state-only restore", step)
        counts = {"pending": 0, "reservoir": 0}
        hwm = self.version
        if step is not None:
            hwm = max(hwm, int(step))  # save labels track the version counter
        if aux is not None:
            counts = self.staging.restore_state(aux.get("staging", {}))
            hwm = max(hwm, int(aux.get("version_hwm", 0)))
            self.env_steps_done = int(aux.get("env_steps_done", 0))
        file_hwm = self.checkpointer.published_hwm()
        if file_hwm is not None:
            hwm = max(hwm, file_hwm)
        if self._n_proc > 1:
            # Non-primary processes never publish, so only process 0
            # holds the hwm file. Defer the bump: the resume-step
            # equality check must compare the UN-bumped checkpoint
            # steps, and then every process applies the same global-max
            # bump (allgather in __init__).
            self._pending_hwm = hwm
            hwm = self.version
        bump = hwm - self.version
        if bump > 0:
            _log.info(
                "resume: version counter %d -> %d (published high-water; "
                "staleness stamps stay monotonic)", self.version, hwm,
            )
            self.version = hwm
        self._resume_scalars = {
            "resume_restored_step": float(step if step is not None else -1),
            "resume_version_hwm_bump": float(max(bump, 0)),
            "resume_reservoir_entries": float(counts["reservoir"]),
            "resume_pending_frames": float(counts["pending"]),
            "resume_restore_wall_s": round(time.monotonic() - t_restore, 3),
        }

    # ------------------------------------------------------ drain / abort

    @property
    def resume_info(self) -> dict:
        """The resume_* scalars of this boot's restore (empty for a
        fresh start, or after the first metrics window consumed them) —
        the chaos controller snapshots this at incarnation boot."""
        return dict(self._resume_scalars)

    def discard_unsaved(self) -> None:
        """SIGKILL-emulation teardown (chaos controller): drop queued
        async-checkpoint and aux/mirror work, exactly as a real kill -9
        would — durable state is whatever already hit the disk."""
        if self._ckpt_worker is not None:
            self._ckpt_worker.stop(flush=False)
        if self.checkpointer is not None:
            self.checkpointer.discard_pending()

    @property
    def drain_requested(self) -> bool:
        return self._drain.is_set()

    @property
    def aborted(self) -> bool:
        return self._abort.is_set()

    def request_drain(self) -> None:
        """SIGTERM semantics: run() stops fetching new broker frames,
        finishes the in-flight step, trains out already-staged batches,
        and returns; the caller then drain_save()s and exits 0."""
        self._drain.set()
        # Wake a fetch blocked on its full batch timeout: quiesce stops
        # intake and lets staging's getter raise Empty once drained.
        self.staging.quiesce()

    def abort(self) -> None:
        """SIGKILL emulation for the chaos controller: run() returns as
        soon as possible, staged work is DISCARDED, nothing is saved —
        recovery must come from the last periodic checkpoint, exactly as
        a real kill -9 would leave things."""
        self._abort.set()
        self.staging.quiesce()

    def drain_save(self) -> None:
        """Final act of the SIGTERM drain, called AFTER run() returned
        (staging/publisher threads already stopped): persist the full
        state — including the sub-batch leftover pending frames the
        quiesced staging could not pack — with wait=True, so a zero exit
        certifies durability."""
        if self.checkpointer is None:
            return
        if self._ckpt_worker is not None:
            self._ckpt_worker.stop(flush=False)  # superseded by this final save
        self._save_full(jax.device_get(self.state), self.version, wait=True)
        # The state is durable — disarm the budget timer. The budget
        # covers drain + save, not obs/metrics teardown: a timer left
        # running could os._exit(1) mid-close after a fully successful
        # drain and mis-signal a dirty shutdown to the supervisor.
        timer = self._drain_timer
        if timer is not None:
            timer.cancel()

    def install_drain_handler(self, budget_s: Optional[float] = None) -> None:
        """Learner-main wiring for --ckpt.drain_on_sigterm: SIGTERM →
        request_drain() + a budget timer that force-exits nonzero if the
        drain wedges — the pod must never coast past its k8s grace
        period into SIGKILL with a half-written step. Replaces any
        flight-recorder SIGTERM dump trigger: a drain is a CLEAN exit
        (the recorder's excepthook stays armed for dirty ones)."""
        import signal

        budget = self.cfg.ckpt.drain_budget_s if budget_s is None else budget_s

        def _on_term(signum, frame):
            _log.warning("SIGTERM: draining (budget %.0fs)", budget)
            self.request_drain()
            if self._drain_timer is None:  # repeated SIGTERMs arm ONE timer
                t = threading.Timer(budget, self._drain_budget_blown)
                t.daemon = True
                t.start()
                self._drain_timer = t

        signal.signal(signal.SIGTERM, _on_term)

    def _drain_budget_blown(self) -> None:
        _log.critical("SIGTERM drain exceeded its budget; forcing exit(1)")
        if self.obs is not None:
            try:
                self.obs.recorder.record("drain_budget_blown")
                self.obs.recorder.dump("drain_budget_blown")
            except Exception:
                pass
        os._exit(1)

    # --------------------------------------------------------------- loop

    def _fetch_next(self, batch_timeout: float, lane: bool = False, cancel=None):
        """Pull one batch off staging and device_put it (dp-sharded).

        Serial loop: called AFTER the current step has been dispatched,
        so the host wait and the transfer overlap the running device
        step. Pipelined loop (`lane=True`): called on the PrefetchLane
        thread — the same work, now FULLY off the loop thread, with
        phase attribution routed to the timer's overlap-lane sums
        (add_overlap) and the staging wait cancellable at lane teardown.
        Returns (batch_dev, env_steps, wait_s, put_s, trace) or
        (None, 0, w, 0.0, None); `trace` is the batch's obs trace refs
        (staging.last_batch_trace) with the h2d hop already recorded —
        at DISPATCH time, like every hop this loop records (the loop
        never syncs the device per step). In fused mode the pack
        happened on the STAGING thread (straight into the transfer
        buffers), so wait_s is queue wait; only the dense-staging
        fallback pays io.pack here (still charged to wait_s, never to
        put_s — that bucket is the pure H2D transfer).
        """
        timer = self.obs.compute.timer if self.obs is not None and self.obs.compute else None
        add = None
        if timer is not None:
            # Overlap mode attributes fetch/pack/h2d to the prefetch
            # lane (its own fenced wall, hidden behind the device step);
            # the serial timer keeps the loop-lane single-writer path.
            add = timer.add_overlap if lane else timer.add
        t0 = time.perf_counter()
        batch, groups = self.staging.get_batch_groups(timeout=batch_timeout, cancel=cancel)
        t1 = time.perf_counter()
        if add is not None:
            add("fetch", t1 - t0)
        if batch is None:
            return None, 0, t1 - t0, 0.0, None
        trace = self.staging.last_batch_trace
        # Ring lease (--staging.pack_workers > 1, fused mode): the batch
        # lives in a TransferRing slot that must go back to the packers
        # once — and only once — its device_put has retired. None on the
        # classic path.
        lease = self.staging.last_batch_lease
        env_steps = int(np.sum(batch.mask))
        if self.fused_io is not None:
            # Staging packed straight into the transfer buffers (groups
            # non-None); the io.pack fallback only runs if a caller wired
            # a dense staging buffer to a fused learner. Host memcpy is
            # charged to the WAIT bucket, not the put bucket:
            # time_device_put_s exists to attribute the H2D transfer
            # specifically (the on-silicon bottleneck).
            if groups is None:
                groups = self.fused_io.pack_transfer(batch)
            t2 = time.perf_counter()
            if add is not None:
                add("pack", t2 - t1)
            shardings = self.fused_io.transfer_shardings()
            if self._n_proc > 1:
                # Each process contributes its local rows; the result is
                # ONE global array per buffer whose dp shards live where
                # each host put them — no cross-host data movement.
                batch_dev = jax.tree.map(
                    lambda arr, sh: jax.make_array_from_process_local_data(sh, arr),
                    groups,
                    shardings,
                )
            else:
                batch_dev = jax.device_put(groups, shardings)
            if add is not None:
                # Fence: the phase is the real transfer, not its dispatch.
                # On the prefetch lane the fence blocks only the lane —
                # attribution costs no overlap there.
                jax.block_until_ready(batch_dev)
                add("h2d", time.perf_counter() - t2)
            if lease is not None:
                # Release the ring slot only after the device_put RETIRES:
                # jax may defer the host read of a put numpy buffer, and a
                # released slot is re-zeroed and repacked immediately —
                # an in-flight transfer would ship the next batch's bytes
                # (or zeros) to the device. The block waits on the H2D
                # stream only, and this fetch already overlaps the
                # in-flight device step, so the wait hides behind compute
                # (the ParamFlattener stream-ordering argument, applied
                # on the host side).
                jax.block_until_ready(batch_dev)
                lease.release()
            if self.obs is not None and trace is not None:
                self.obs.tracer.hop_batch("h2d", trace)
            return batch_dev, env_steps, t2 - t0, time.perf_counter() - t2, trace
        if self._n_proc > 1:
            batch_dev = jax.tree.map(
                lambda arr, sh: jax.make_array_from_process_local_data(sh, np.asarray(arr)),
                batch,
                self.batch_sharding,
            )
        else:
            batch_dev = jax.device_put(batch, self.batch_sharding)
        if add is not None:
            jax.block_until_ready(batch_dev)
            add("h2d", time.perf_counter() - t1)
        if self.obs is not None and trace is not None:
            self.obs.tracer.hop_batch("h2d", trace)
        return batch_dev, env_steps, t1 - t0, time.perf_counter() - t1, trace

    def run(
        self,
        num_steps: Optional[int] = None,
        batch_timeout: float = 60.0,
        max_idle: Optional[int] = None,
        max_seconds: Optional[float] = None,
    ) -> int:
        """Train until num_steps (None = forever); returns steps done.

        `max_idle`: raise TimeoutError after this many CONSECUTIVE empty
        batch waits (None = retry forever, the service default). Drivers
        with a finite budget set it so dead producers surface as an error
        instead of an infinite 'no batch; waiting' loop.

        `max_seconds`: stop cleanly once this much wall clock has elapsed
        (checked between steps) — for soak/bench drivers with a time
        budget rather than a step budget.

        Loop shape: --learner.prefetch (default ON) runs the pipelined
        loop — a PrefetchLane thread stages batch N+1 while the device
        executes step N (_run_pipelined); prefetch=False runs the
        serial fetch-after-step loop byte-for-byte (_run_serial).
        """
        self.staging.start()
        self.publisher.start()
        done_steps = 0
        # The latest dispatched metrics handle, shared with the finally
        # fence: an exception mid-loop must still drain the in-flight
        # device step before the staging/publisher teardown.
        metrics_box = [None]
        try:
            # Inside the try so a failed publish or first fetch still
            # stops the staging/publisher threads (a leaked consumer
            # would silently eat broker frames for the process lifetime).
            self.publish_weights()  # version 0, synchronous, so actors align immediately
            deadline = time.monotonic() + max_seconds if max_seconds is not None else None

            def _bt() -> float:
                # Fetch waits must respect the wall-clock budget, or the
                # final batch wait overshoots the deadline by up to
                # batch_timeout (observed: a 35s soak window returning
                # 120s late because producers had exited).
                if self._drain.is_set() or self._abort.is_set():
                    # Draining/aborting: never park against the full
                    # batch timeout — the drain budget is wall clock.
                    return 0.2
                if deadline is None:
                    return batch_timeout
                return max(0.05, min(batch_timeout, deadline - time.monotonic()))

            if self._prefetch_enabled:
                done_steps = self._run_pipelined(
                    num_steps, batch_timeout, max_idle, deadline, _bt, metrics_box
                )
            else:
                done_steps = self._run_serial(
                    num_steps, batch_timeout, max_idle, deadline, _bt, metrics_box
                )
        finally:
            if metrics_box[0] is not None:
                jax.block_until_ready(metrics_box[0])
            self.staging.stop()
            self.publisher.stop()
            # flush, don't close: run() is re-entrant (phased drivers call
            # it repeatedly); close() below releases the logger for good
            self.metrics.flush()
        return done_steps

    def _run_serial(
        self, num_steps, batch_timeout, max_idle, deadline, _bt, metrics_box
    ) -> int:
        """The serial fetch-after-step loop (--learner.prefetch false) —
        the pre-pipeline behavior, byte-for-byte (the rollback path;
        tests/test_pipeline.py pins the flag-off inertness)."""
        cfg = self.cfg
        # Step-phase decomposition (obs/compute.py): when the timer
        # exists the SERIAL loop FENCES the device once per step so each
        # phase is causally attributable — trading the round-3 prefetch
        # overlap for legibility. (The pipelined loop instead runs the
        # timer in overlap mode: attribution moves to the prefetch lane
        # and no fence is paid — _run_pipelined.) timer=None keeps the
        # async-dispatch shape untouched.
        compute = self.obs.compute if self.obs is not None else None
        timer = compute.timer if compute is not None else None
        done_steps = 0
        # per-window accumulators, reset at every metrics log
        win_wait = win_put = 0.0
        win_env_steps = 0
        win_steps = 0
        t_win = time.perf_counter()
        metrics = None
        idle = 0
        next_batch, next_env_steps, w, p, next_trace = self._fetch_next(_bt())
        win_wait += w
        win_put += p
        while num_steps is None or done_steps < num_steps:
            if self._abort.is_set():
                # SIGKILL emulation: return NOW, staged work dies
                # with the incarnation (chaos controller contract).
                break
            if deadline is not None and time.monotonic() >= deadline:
                break
            if next_batch is None:
                if self._drain.is_set():
                    # Drain: staging intake is quiesced; an empty
                    # fetch with nothing left to pack means the
                    # in-flight work is trained out — return so the
                    # caller can drain_save().
                    if self.staging.drained():
                        break
                    next_batch, next_env_steps, w, p, next_trace = self._fetch_next(_bt())
                    win_wait += w
                    win_put += p
                    continue
                idle += 1
                if max_idle is not None and idle >= max_idle:
                    raise TimeoutError(
                        f"no batch for {idle} consecutive {batch_timeout:.0f}s waits "
                        f"— producers dead or stalled"
                    )
                if deadline is not None and time.monotonic() >= deadline:
                    break
                _log.warning("no batch within %.0fs; waiting", batch_timeout)
                next_batch, next_env_steps, w, p, next_trace = self._fetch_next(_bt())
                win_wait += w
                win_put += p
                continue
            idle = 0
            batch_dev, env_steps, batch_trace = next_batch, next_env_steps, next_trace
            t_pass = time.perf_counter()
            # Async dispatch: returns immediately, device runs the step.
            self.state, metrics = self.train_step(self.state, batch_dev)
            metrics_box[0] = metrics
            if timer is not None:
                # Fence: device_step is dispatch + execution wall. The
                # prefetch below then runs AFTER the device finished —
                # the overlap cost the serial step_phases mode documents.
                jax.block_until_ready(metrics)
                timer.add("device_step", time.perf_counter() - t_pass)
            if self.obs is not None and batch_trace is not None:
                # Terminal hops at DISPATCH (the loop's only routine
                # sync is the metrics fetch): per-stage apply delta +
                # the e2e actor→apply scalar that decomposes staleness.
                self.obs.tracer.hop_batch("apply", batch_trace)
                self.obs.tracer.e2e(batch_trace)
            self.version += 1
            done_steps += 1
            self.env_steps_done += env_steps
            win_env_steps += env_steps
            win_steps += 1

            last = num_steps is not None and done_steps >= num_steps
            if not last:
                # Host work below overlaps the in-flight device step.
                # Skipped on the final step: a trailing prefetch would
                # eat (and discard) one packed batch per phased-run
                # call and could stall up to batch_timeout.
                next_batch, next_env_steps, w, p, next_trace = self._fetch_next(_bt())
                win_wait += w
                win_put += p
            else:
                next_batch, next_env_steps, next_trace = None, 0, None

            t_host = time.perf_counter()
            if self.version % cfg.publish_every == 0 and self._primary:
                # One async on-device flatten dispatch; the blocking
                # host read of the single buffer happens on the
                # publisher thread. Donation-safe because this
                # dispatch precedes the next (state-donating) train
                # step in stream order (ParamFlattener docstring).
                # Non-primary processes skip: weights are replicated
                # and one fanout per version is the contract.
                self.publisher.submit(
                    self.flattener.flatten_on_device(self.state.params), self.version
                )
            if self.checkpointer is not None and self.version % cfg.checkpoint_every == 0:
                self.checkpoint()

            if timer is not None:
                # Close the pass BEFORE a possible metrics window so
                # window_scalars only ever aggregates fully-closed
                # passes (a half-recorded pass would make the phase
                # sum drift from the wall). The metrics sync/log below
                # is the observer's own cost and stays outside the
                # decomposition by design.
                t_end = time.perf_counter()
                timer.add("host", t_end - t_host)
                timer.step(t_end - t_pass)

            if self.version % cfg.metrics_every == 0 or last:
                now = time.perf_counter()
                self._log_window(
                    metrics, now, t_win, win_steps, win_env_steps, win_wait, win_put
                )
                win_wait = win_put = 0.0
                win_env_steps = win_steps = 0
                t_win = now
        return done_steps

    def _run_pipelined(
        self, num_steps, batch_timeout, max_idle, deadline, _bt, metrics_box
    ) -> int:
        """The overlapped loop (--learner.prefetch, default): a
        PrefetchLane thread runs the whole host side of batch N+1 —
        staging pop, pack wait, device_put dispatch, retire, ring-lease
        release — while the device executes step N, so the loop thread's
        per-iteration host cost is one queue pop + the async train-step
        dispatch. Batch order is FIFO-identical to the serial loop (the
        lane is the same single staging consumer), so params are BITWISE
        equal to a serial run over the same frame schedule
        (OVERLAP_AB.json). The SIGTERM drain trains out every batch the
        lane holds (the "exhausted" sentinel lands FIFO-last), and the
        lane's fetch budget is capped at num_steps so a phased run never
        eats a trailing batch."""
        cfg = self.cfg
        compute = self.obs.compute if self.obs is not None else None
        timer = compute.timer if compute is not None else None
        # The lane's staging wait is cancellable at teardown via the
        # lane's stop event — a stopping lane must never sit out a full
        # batch timeout (nor overlap a successor lane's pops on a phased
        # driver's next run()).
        cancel = threading.Event()
        lane = PrefetchLane(
            lambda: self._fetch_next(_bt(), lane=True, cancel=cancel),
            depth=self._prefetch_depth,
            limit=num_steps,
            drain=self._drain,
            abort=self._abort,
            upstream_drained=lambda: self.staging.drained(include_prefetch=False),
            stop_event=cancel,
        )
        self._prefetch_lane = lane
        lane.start()
        done_steps = 0
        win_wait = win_put = win_take = 0.0
        win_env_steps = 0
        win_steps = 0
        t_win = time.perf_counter()
        metrics = None
        idle = 0
        try:
            while num_steps is None or done_steps < num_steps:
                # Take the next prefetched item, staying responsive to
                # abort/deadline in 0.2s slices (the lane's fetch waits
                # park against _bt() on its own thread).
                item = None
                t_take0 = time.perf_counter()
                while item is None:
                    if self._abort.is_set():
                        break
                    if deadline is not None and time.monotonic() >= deadline:
                        break
                    try:
                        item = lane.get(timeout=0.2)
                    except queue.Empty:
                        continue
                if item is None:
                    break  # abort / deadline
                take_s = time.perf_counter() - t_take0
                if item.kind == "error":
                    raise item.error
                if item.kind == "exhausted":
                    # Drain complete: the lane emits this sentinel ONLY
                    # under a set _drain (budget exhaustion ends the
                    # lane silently — the loop's own step bound ends
                    # us), it proved nothing more can arrive upstream,
                    # and FIFO put every remaining batch ahead of it —
                    # everything the drain owed is trained out.
                    break
                if item.kind == "idle":
                    # Starvation must read LOUD, exactly like the serial
                    # loop's empty fetches: the wall spent polling for
                    # this (empty) item is exposed loop wait — charge it
                    # to the take accumulator and the timer's fetch
                    # phase (compute_phase_fetch_frac is the watchdog's
                    # starvation signal), not the device residual. A
                    # starved window's fetch mean may exceed its wall
                    # mean — the documented, intended read.
                    win_take += take_s
                    win_wait += item.wait_s
                    if timer is not None:
                        timer.add("fetch", take_s)
                    if self._drain.is_set():
                        continue  # the lane signals "exhausted" when done
                    idle += 1
                    if max_idle is not None and idle >= max_idle:
                        raise TimeoutError(
                            f"no batch for {idle} consecutive {batch_timeout:.0f}s waits "
                            f"— producers dead or stalled"
                        )
                    _log.warning("no batch within %.0fs; waiting", batch_timeout)
                    continue
                idle = 0
                win_take += take_s
                win_wait += item.wait_s
                win_put += item.put_s
                if timer is not None:
                    # Loop-lane "fetch" = the EXPOSED wait for a
                    # prefetched batch: host time the lane failed to
                    # hide — the device-idle-per-step upper bound.
                    timer.add("fetch", take_s)
                batch_dev, env_steps, batch_trace = item.batch, item.env_steps, item.trace
                t_pass = time.perf_counter()
                # Async dispatch: returns immediately, device runs the
                # step; the lane is already staging batch N+1 beside it.
                self.state, metrics = self.train_step(self.state, batch_dev)
                metrics_box[0] = metrics
                if self.obs is not None and batch_trace is not None:
                    self.obs.tracer.hop_batch("apply", batch_trace)
                    self.obs.tracer.e2e(batch_trace)
                self.version += 1
                done_steps += 1
                self.env_steps_done += env_steps
                win_env_steps += env_steps
                win_steps += 1
                last = num_steps is not None and done_steps >= num_steps

                t_host = time.perf_counter()
                if self.version % cfg.publish_every == 0 and self._primary:
                    # Same donation-safety as the serial loop: the
                    # flatten dispatch precedes the next state-donating
                    # train step in THIS thread's stream order (the lane
                    # only ever touches batch buffers, never the state).
                    self.publisher.submit(
                        self.flattener.flatten_on_device(self.state.params), self.version
                    )
                if self.checkpointer is not None and self.version % cfg.checkpoint_every == 0:
                    self.checkpoint()

                if timer is not None:
                    # Overlap mode: no per-step fence. device_step is
                    # the UNFENCED residual — the in-flight device
                    # window from the loop's clock — so the loop-lane
                    # phases tile the wall by construction; the causal
                    # fetch/pack/h2d split lives in the lane's own
                    # pipeline_* sums (recorded fenced, on the lane).
                    t_end = time.perf_counter()
                    host_s = t_end - t_host
                    timer.add("host", host_s)
                    wall = t_end - t_take0
                    timer.add("device_step", max(wall - take_s - host_s, 0.0))
                    timer.step(wall)

                if self.version % cfg.metrics_every == 0 or last:
                    now = time.perf_counter()
                    self._log_window(
                        metrics, now, t_win, win_steps, win_env_steps,
                        win_wait, win_put, win_take=win_take,
                    )
                    win_wait = win_put = win_take = 0.0
                    win_env_steps = win_steps = 0
                    t_win = now
        finally:
            lane.stop()
            self._prefetch_lane = None
        return done_steps

    def _log_window(
        self,
        metrics,
        now: float,
        t_win: float,
        win_steps: int,
        win_env_steps: int,
        win_wait: float,
        win_put: float,
        win_take: Optional[float] = None,
    ) -> None:
        """One metrics window — the ONLY routine device sync in the loop
        (jax.device_get of the step metrics). Shared by both loop shapes;
        `win_take` is the pipelined loop's exposed take-wait accumulator
        (None = serial split)."""
        compute = self.obs.compute if self.obs is not None else None
        scalars = {k: float(v) for k, v in jax.device_get(metrics).items()}
        stats = self.staging.stats()
        dt = max(now - t_win, 1e-9)
        n = max(win_steps, 1)
        scalars["env_steps_per_sec"] = win_env_steps / dt
        # per-stage split (SURVEY.md §5): window averages. time_step_s is
        # the residual — device step + dispatch + publish-get — since the
        # loop never syncs per step.
        scalars["time_wait_batch_s"] = win_wait / n
        scalars["time_device_put_s"] = win_put / n
        if win_take is None:
            scalars["time_step_s"] = max(dt - win_wait - win_put, 0.0) / n
        else:
            # Pipelined loop: wait/put were paid on the prefetch lane,
            # overlapping the device step — only the take-wait is
            # exposed loop time, so the residual subtracts just that.
            # The pipeline_* family carries the overlap accounting
            # (obs overlap-mode timer refines these with fenced lane
            # sums when step_phases is on — same keys, logged after).
            lane_s = win_wait + win_put
            scalars["time_step_s"] = max(dt - win_take, 0.0) / n
            scalars["pipeline_prefetch_s"] = lane_s / n
            scalars["pipeline_device_idle_s"] = win_take / n
            scalars["pipeline_overlap_ratio"] = (
                max(0.0, min(1.0, 1.0 - win_take / lane_s)) if lane_s > 0 else 1.0
            )
        scalars["active_actors"] = stats["active_actors"]
        scalars["staleness_dropped"] = stats["dropped_stale"]
        scalars["staging_quarantined"] = stats["quarantined"]
        scalars["queue_ready"] = stats["ready_batches"]
        scalars["episodes"] = stats["episodes"]
        # Experience-wire meters (DTR3 quantized wire): bytes
        # entering the staging intake and the fleet's frame
        # split by obs wire dtype — the consumers-first
        # rolling upgrade's progress gauge.
        scalars["wire_bytes_consumed_total"] = stats["wire_bytes"]
        scalars["wire_frames_obs_bf16_total"] = stats["wire_frames_obs_bf16"]
        scalars["wire_frames_obs_f32_total"] = stats["wire_frames_obs_f32"]
        # Broker-fabric scoreboard (broker_shard_* / fanin_* registry
        # prefix families): per-shard pop/starve meters and the
        # fence/dedup ledgers. Pure local counters (no RPC); present
        # only when --broker_url is a shard list, so classic runs emit
        # nothing new.
        fabric_stats = getattr(self.broker, "fabric_stats", None)
        if fabric_stats is not None:
            for k, v in fabric_stats().items():
                scalars[k] = float(v)
        # Parallel host feed scoreboard (staging_pack_*, registry prefix
        # family): per-worker busy/stall seconds, ring occupancy/wait,
        # packer-proper rows/s. The pack_* keys exist only when
        # --staging.pack_workers > 1, so default runs emit nothing new.
        for k, v in stats.items():
            if k.startswith("pack_"):
                scalars[f"staging_{k}"] = float(v)
        # Replay reservoir health (replay.enabled only): occupancy, hit
        # ratio, replayed-frame age histogram buckets, bytes spilled —
        # all pre-flattened scalars.
        for k, v in stats.items():
            if k.startswith("replay_"):
                scalars[k] = v
        scalars["weights_published"] = self.publisher.published
        scalars["weights_coalesced"] = self.publisher.coalesced
        if self.checkpointer is not None:
            # Remote-mirror health (ADVICE r4): a growing lag means
            # uploads can't keep the checkpoint cadence and durability
            # is silently behind.
            for k, v in self.checkpointer.mirror_stats().items():
                if isinstance(v, (int, float)):
                    scalars[f"ckpt_mirror_{k}"] = v
            # Full-state save health (ckpt_* in obs/registry): empty
            # dict (no keys emitted) until the first aux save, so
            # default runs log nothing new.
            for k, v in self.checkpointer.save_stats().items():
                scalars[f"ckpt_{k}"] = float(v)
            if self._ckpt_worker is not None:
                scalars["ckpt_async_saves_total"] = float(self._ckpt_worker.saved)
                scalars["ckpt_async_coalesced_total"] = float(
                    self._ckpt_worker.coalesced
                )
        if self._resume_scalars:
            # One-shot: the restore's provenance rides the first logged
            # window, then clears.
            scalars.update(self._resume_scalars)
            self._resume_scalars = {}
        if stats["episodes"] > 0:
            scalars["mean_episode_return"] = stats["episode_return_sum"] / stats["episodes"]
        if self.obs is not None:
            # Per-stage pipeline latency histograms + the e2e
            # actor→apply decomposition (obs/trace.py). Empty until
            # traced frames flow (actors opted in).
            scalars.update(self.obs.tracer.scalars())
        if compute is not None:
            # compute_* families (obs/compute.py): phase means over this
            # window (every pass fully closed — the loops close the pass
            # before logging), cumulative recompile counters, cumulative
            # MFU; in overlap mode also the fenced pipeline_* lane sums.
            scalars.update(compute.window_scalars(win_steps, dt))
        self.metrics.log(self.version, scalars)

    def close(self) -> None:
        if self._ckpt_worker is not None:
            # Drain (not discard) a pending async save: close() after a
            # normal finish must leave the newest submitted step durable.
            self._ckpt_worker.stop(flush=True)
        if self.checkpointer is not None:
            self.checkpointer.close()  # drains the aux + mirror workers
        if self.obs is not None:
            self.obs.close()
        self.metrics.close()


def main(argv=None):
    from dotaclient_tpu.config import parse_config
    from dotaclient_tpu.transport.base import connect as broker_connect

    logging.basicConfig(level=logging.INFO)
    cfg = parse_config(LearnerConfig(), argv)
    if cfg.platform:
        jax.config.update("jax_platforms", cfg.platform)
    if cfg.multihost:
        # Must run before any backend touch: after this, jax.devices()
        # spans every process's chips and the existing mesh/shardings
        # scale across hosts with zero further changes. Each kwarg is
        # passed independently — an unset flag ("" / -1) defers to jax's
        # cluster-env/metadata auto-detection, a set one overrides it.
        kw = {}
        if cfg.coordinator:
            kw["coordinator_address"] = cfg.coordinator
        if cfg.num_processes >= 0:
            kw["num_processes"] = cfg.num_processes
        if cfg.process_id >= 0:
            kw["process_id"] = cfg.process_id
        jax.distributed.initialize(**kw)
    from dotaclient_tpu.transport.base import RetryPolicy

    broker = broker_connect(cfg.broker_url, retry=RetryPolicy.from_config(cfg.retry))
    if cfg.broker_shards:
        # Multi-learner fan-in (--broker_shards "0,1"): pin this learner
        # to a disjoint shard subset of the fabric. Only meaningful
        # against a shard-list broker_url — anything else is a deploy
        # mistake that must fail boot loudly, not silently consume the
        # whole queue.
        restrict = getattr(broker, "restrict_consume_shards", None)
        if restrict is None:
            raise ValueError(
                f"--broker_shards={cfg.broker_shards!r} needs a broker fabric "
                f"(comma-separated --broker_url shard list); got "
                f"{cfg.broker_url!r}"
            )
        restrict([int(s) for s in cfg.broker_shards.split(",") if s.strip()])
    if cfg.chaos.enabled:
        # Gated import — chaos off means the package never loads and the
        # broker is the production object (tests/test_chaos.py).
        from dotaclient_tpu.chaos import wrap_broker

        broker = wrap_broker(broker, cfg.chaos)
    learner = Learner(cfg, broker)
    if cfg.ckpt.drain_on_sigterm:
        # SIGTERM → drain: stop fetching, finish the in-flight step,
        # train out staged batches, save full state, exit 0 — inside
        # --ckpt.drain_budget_s (k8s pairs terminationGracePeriodSeconds
        # with it). Installed AFTER Learner.__init__ so it supersedes the
        # flight recorder's SIGTERM dump trigger (a drain is clean).
        learner.install_drain_handler()
    _log.info(
        "learner up: mesh=%s batch=%dx%d devices=%d",
        cfg.mesh_shape,
        cfg.batch_size,
        cfg.seq_len,
        len(jax.devices()),
    )
    try:
        learner.run(num_steps=cfg.train_steps or None)
        if learner.drain_requested and not learner.aborted:
            learner.drain_save()
            _log.info("SIGTERM drain complete at version %d; exiting 0", learner.version)
    finally:
        learner.close()


if __name__ == "__main__":
    main()
