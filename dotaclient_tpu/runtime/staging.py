"""Host-side staging buffer: broker frames → padded device batches.

This is the piece the north star adds to the reference design: the
consumer side of the RMQ pipe gains a TPU host-staging buffer that packs
variable-length trajectories into fixed [B, T] padded, masked,
version-filtered batches (BASELINE.json north_star; SURVEY.md §3.2
device-boundary note). Structure:

- a consumer thread drains the broker and deserializes frames;
- rollouts older than `max_staleness` learner versions are dropped here,
  on the host, before they cost any device time (SURVEY.md §7
  "Staleness/backpressure") — unless the replay reservoir is enabled
  (LearnerConfig.replay, dotaclient_tpu/replay/), in which case
  near-stale rollouts are RETAINED in a prioritized reservoir and mixed
  back into batches at a configurable ratio, each row stamped with its
  behavior-policy staleness for the ACER truncated importance weights
  in ops/ppo.py;
- a packer assembles ready batches into a bounded queue (depth 2) so
  packing the next batch overlaps the device step on the current one
  (double buffering);
- single-writer ownership: only the consumer thread touches the pending
  list AND the reservoir, only get_batch pops ready batches (SURVEY.md
  §5 race-detection note — structural avoidance, mirrored from the
  reference's single-threaded consumers).

Failure split (ADVICE r5 item 1): a malformed FRAME costs its own batch
at worst (dropped_bad, consumer continues) — and since the chaos era it
also leaves EVIDENCE: parse/layout failures are filed in a bounded
dead-letter quarantine ring (reason + size + header prefix, the
`staging_quarantined` scalar, dumped by the flight recorder as a
section) so a corrupt wire is distinguishable from a misbuilt actor
post-mortem. A batch/template LAYOUT or CONFIG mismatch
(ops.batch.BatchLayoutError from the native packer or the fused
transfer pack) is a persistent builder/staging disagreement that would
fail every batch forever — the consumer thread dies loudly and
get_batch/get_batch_groups re-raise instead of starving the learner
behind per-batch warnings.
"""

from __future__ import annotations

import collections
import logging
import queue
import threading
import time
from typing import Callable, Dict, List, Optional

import numpy as np

from dotaclient_tpu.config import LearnerConfig
from dotaclient_tpu.ops.batch import BatchLayoutError, TrainBatch, zeros_train_batch

_log = logging.getLogger(__name__)
from dotaclient_tpu.obs.trace import TraceRef
from dotaclient_tpu.transport.base import Broker
from dotaclient_tpu.transport.serialize import (
    Rollout,
    WireDtypeError,
    check_dtr3_dtype_map,
    deserialize_rollout,
    peek_rollout_trace,
    rollout_obs_bf16,
    strip_rollout_trace,
    wire_obs_is_bf16,
)


def fill_rollouts(
    batch: TrainBatch, rollouts: List[Rollout], seq_len: int, row_offset: int = 0
) -> None:
    """Fill a pre-zeroed TrainBatch (zeros_train_batch contract) with B
    variable-length rollouts, in place. The leaves may be strided views
    (the fused-H2D group buffers) or dense arrays; numpy assignment
    handles both, including the f32→bf16 cast when the obs leaves are
    staged in the compute dtype.

    `row_offset`: rollout i lands at batch row row_offset+i — the
    python-fallback half of the sharded pack (--staging.pack_workers):
    N workers fill disjoint contiguous row ranges of the SAME batch
    concurrently; rows never overlap and each row depends only on its
    own rollout, so any split is bitwise identical to one call."""
    T = seq_len
    obs, actions, aux = batch.obs, batch.actions, batch.aux
    # np.errstate: same untrusted-float story as cast_obs_to_compute_dtype
    # — on the fused path the obs destinations are bf16 views and this
    # assignment IS the f32→bf16 cast, so NaN/inf/out-of-range wire
    # values would emit per-batch RuntimeWarnings here.
    with np.errstate(invalid="ignore", over="ignore"):
        for i, r in enumerate(rollouts):
            b = row_offset + i
            L = r.length
            if L > T:
                raise ValueError(f"rollout length {L} exceeds learner seq_len {T}")
            for field in range(len(obs)):
                obs[field][b, : L + 1] = r.obs[field][: L + 1]
            for field in range(len(actions)):
                actions[field][b, :L] = r.actions[field][:L]
            batch.behavior_logp[b, :L] = r.behavior_logp
            batch.behavior_value[b, :L] = r.behavior_value
            batch.rewards[b, :L] = r.rewards
            batch.dones[b, :L] = r.dones
            batch.mask[b, :L] = 1.0
            batch.initial_state[0][b] = r.initial_state[0]
            batch.initial_state[1][b] = r.initial_state[1]
            if aux is not None and r.aux is not None:
                aux.win[b, :L] = r.aux.win
                aux.last_hit[b, :L] = r.aux.last_hit
                aux.net_worth[b, :L] = r.aux.net_worth


def shard_rows(total: int, workers: int) -> List[tuple]:
    """Contiguous (offset, count) row shards, as even as possible: the
    first total%workers shards get one extra row. Fewer rows than
    workers degenerates to one-row shards (never empty ones)."""
    n = max(1, min(workers, total))
    base, rem = divmod(total, n)
    shards = []
    off = 0
    for i in range(n):
        cnt = base + (1 if i < rem else 0)
        shards.append((off, cnt))
        off += cnt
    return shards


class _StagingStopped(Exception):
    """Internal: a sharded pack was abandoned because stop() landed
    mid-batch (ring acquire or pool join interrupted). Not a frame
    error — the pack loop exits without counting dropped_bad."""


class _ShardJob:
    """Countdown latch for one sharded batch: N tasks share one event,
    the last finisher sets it — the dispatcher pays ONE wait, not N."""

    __slots__ = ("event", "errors", "_remaining", "_lock")

    def __init__(self, n: int):
        self.event = threading.Event()
        self.errors: List[BaseException] = []
        self._remaining = n
        self._lock = threading.Lock()

    def done_one(self, error: Optional[BaseException]) -> None:
        with self._lock:
            if error is not None:
                self.errors.append(error)
            self._remaining -= 1
            last = self._remaining == 0
        if last:
            self.event.set()


class _PackPool:
    """--staging.pack_workers packer threads executing row-shard tasks.

    Each task packs a disjoint row range of ONE shared output buffer
    (native: dt_pack_batch with row_offset, GIL released → real
    parallelism; python fallback: fill_rollouts with row_offset). The
    meters feed the registry-pinned staging_pack_* scalars: per-worker
    busy seconds (executing a shard) and stall seconds (idle, waiting
    for work) — a pool whose stall dwarfs busy is oversized for the
    offered batch rate. All meters live under one lock; workers touch it
    twice per task, microseconds against a ~ms pack."""

    def __init__(self, workers: int, name: str = "staging-pack"):
        self.n = workers
        self._tasks: "queue.Queue" = queue.Queue()
        self._stop = threading.Event()
        self._meters_lock = threading.Lock()
        self._busy_s = [0.0] * workers
        self._stall_s = [0.0] * workers
        self._tasks_done = 0
        self._threads = [
            threading.Thread(target=self._run, args=(i,), daemon=True, name=f"{name}-{i}")
            for i in range(workers)
        ]
        for t in self._threads:
            t.start()

    def _run(self, i: int) -> None:
        while True:
            t0 = time.perf_counter()
            try:
                task = self._tasks.get(timeout=0.2)
            except queue.Empty:
                with self._meters_lock:
                    self._stall_s[i] += time.perf_counter() - t0
                if self._stop.is_set():
                    return
                continue
            with self._meters_lock:
                self._stall_s[i] += time.perf_counter() - t0
            fn, job = task
            t1 = time.perf_counter()
            # (workers never see a None task: dispatch is run_tasks only,
            # and shutdown rides the _stop event + empty-queue check)
            error = None
            try:
                fn()
            except BaseException as e:  # the dispatcher re-raises, typed
                error = e
            finally:
                with self._meters_lock:
                    self._busy_s[i] += time.perf_counter() - t1
                    self._tasks_done += 1
                job.done_one(error)

    def run_tasks(self, thunks, stop: threading.Event):
        """Dispatch the thunks (one per row shard) and wait for all.
        Returns None on success, the most severe error otherwise
        (BatchLayoutError outranks ValueError — fatal beats drop), or
        _StagingStopped when teardown interrupted the batch."""
        job = _ShardJob(len(thunks))
        for fn in thunks:
            self._tasks.put((fn, job))
        while not job.event.wait(timeout=0.2):
            # Workers only exit when stopped AND the task queue was
            # empty at their last check; a task enqueued after every
            # worker exited would wait forever — detect and abandon.
            if stop.is_set() and not any(t.is_alive() for t in self._threads):
                return _StagingStopped()
        layout = other = None
        for e in job.errors:
            if isinstance(e, BatchLayoutError):
                layout = layout or e
            else:
                other = other or e
        return layout or other

    def run_sharded(self, task_fn, shards, stop: threading.Event):
        """run_tasks over task_fn(offset, count) thunks — the
        convenience entry benches/tests use."""
        return self.run_tasks(
            [(lambda o=off, c=cnt: task_fn(o, c)) for off, cnt in shards], stop
        )

    def meters(self):
        """(busy_s list, stall_s list, tasks_done) — one locked snapshot."""
        with self._meters_lock:
            return list(self._busy_s), list(self._stall_s), self._tasks_done

    def stop(self) -> None:
        self._stop.set()
        for t in self._threads:
            t.join(timeout=5)


def pack_rollouts(rollouts: List[Rollout], seq_len: int, with_aux: bool) -> TrainBatch:
    """Pad B variable-length rollouts into one fixed [B, T] TrainBatch.

    Rollouts longer than `seq_len` are a config mismatch and rejected.
    Padding rows reuse zero observations; `mask` marks real steps. All
    leaves are numpy — `jax.device_put` with the dp sharding happens at
    the caller.
    """
    B = len(rollouts)
    H = rollouts[0].initial_state[0].shape[-1]
    batch = zeros_train_batch(B, seq_len, H, with_aux)
    fill_rollouts(batch, rollouts, seq_len)
    return batch


def cast_obs_to_compute_dtype(cfg: LearnerConfig, batch: TrainBatch) -> TrainBatch:
    """Cast float obs leaves to the policy compute dtype ON THE HOST
    (runs on the staging thread — off the train loop's critical path).

    The policy's first op on every obs float is `.astype(bf16)`, so
    pre-casting is numerically IDENTICAL (same round-to-nearest) and
    halves the bytes of the dominant host→device transfer — measured on
    silicon as the e2e bottleneck (BENCH_TPU_20260730T0510.json:
    device_put 12.0ms/iter vs 1.3ms of everything else; obs floats are
    5.1 of the batch's 5.65 MB). Casting selects by dtype, so every
    float32 obs leaf — present or future — is covered. GAE/loss scalars
    (rewards, logp, values, mask) stay f32 — their precision is
    load-bearing and their bytes are not. bench.py routes its synthetic
    batches through this same function so its device-only section times
    the executable production actually runs."""
    if not cfg.stage_obs_compute_dtype or cfg.policy.dtype == "float32":
        return batch
    import ml_dtypes

    dt = {"bfloat16": ml_dtypes.bfloat16}.get(cfg.policy.dtype)
    if dt is None:  # unknown compute dtype: ship f32, the policy casts
        return batch
    # Wire frames are untrusted: fuzzed/corrupt obs floats (NaN, inf,
    # beyond-bf16 magnitudes) reach this cast before any validation that
    # could reject them, and numpy's per-cast RuntimeWarning would spam
    # the gate output (VERDICT r5 item 9). The cast itself is total —
    # NaN/inf propagate, out-of-range saturates to inf — and the learner
    # masks or drops such rows downstream, so silence the warning here
    # rather than pay a pre-scan of every batch.
    with np.errstate(invalid="ignore", over="ignore"):
        obs = batch.obs._replace(
            **{
                f: v.astype(dt)
                for f, v in batch.obs._asdict().items()
                if getattr(v, "dtype", None) == np.float32
            }
        )
    return batch._replace(obs=obs)


class StagingBuffer:
    """Consume → filter → pack pipeline feeding the train loop.

    Two packing paths, identical output:
    - native (default): frames are header-validated in C and kept as raw
      bytes; a whole batch packs in one C call (one memcpy per field,
      GIL released — packing overlaps the device step);
    - python fallback: full deserialize + per-field numpy copies
      (DOTACLIENT_TPU_NO_NATIVE=1, no compiler, or native_packer=False).
    """

    def __init__(
        self,
        cfg: LearnerConfig,
        broker: Broker,
        version_fn: Callable[[], int] = lambda: 0,
        fused_io=None,
        tracer=None,
        recorder=None,
    ):
        self.cfg = cfg
        self.broker = broker
        self.version_fn = version_fn
        # Pipeline observability (dotaclient_tpu/obs/), both optional:
        # `tracer` records per-hop latency for trace-stamped frames,
        # `recorder` receives pipeline events and dumps its ring on the
        # fatal BatchLayoutError path. None (the default) keeps every
        # pre-obs code path byte-for-byte: no per-row hop work, no
        # parallel trace bookkeeping.
        self._tracer = tracer
        self._recorder = recorder
        # Parallel to _pending, ONLY maintained when tracer is set: the
        # TraceRef (or None) for each pending item, same single-writer
        # discipline.
        self._pending_traces: List = []
        # Trace refs of the batch most recently returned by
        # get_batch_groups (learner-thread-read; None when untraced).
        self.last_batch_trace = None
        # Fused-H2D mode (parallel/fused_io.FusedBatchIO): the packer
        # fills leaf VIEWS of the dtype-grouped transfer buffers, so the
        # learner ships `groups` without a regroup copy. The caller must
        # pass the SAME io the train step was built with (layouts must
        # agree) and read via get_batch_groups.
        self._fused_io = fused_io
        # python path: Rollout objects; native path: raw frame bytes
        self._pending: List = []
        # queue items: (TrainBatch, groups-dict-or-None, traces, lease)
        self._ready: "queue.Queue" = queue.Queue(maxsize=2)
        self._stop = threading.Event()
        # Parallel host feed (--staging.pack_workers > 1): a dedicated
        # pop thread drains the broker into a bounded intake queue, an
        # ASSEMBLER thread owns everything the consumer thread owned
        # (parse/filter/_pending/reservoir — the single-writer
        # discipline moves wholesale, it never splits), and a pool of
        # pack workers fills disjoint row shards of one output buffer
        # concurrently. In fused mode the outputs come from a
        # TransferRing of cfg.staging.transfer_depth preallocated
        # buffer sets (pack N+1 overlaps H2D of N); the learner's fetch
        # carries the slot as a lease (last_batch_lease) released after
        # its device_put retires. pack_workers=1 (default) builds NONE
        # of this — the classic one-consumer-thread path, byte-for-byte
        # (the inertness contract, proven in a subprocess in
        # tests/test_staging.py).
        from dotaclient_tpu.config import StagingConfig

        self._staging_cfg = getattr(cfg, "staging", None) or StagingConfig()
        if self._staging_cfg.pack_workers < 1:
            raise ValueError(
                f"staging.pack_workers must be >= 1, got "
                f"{self._staging_cfg.pack_workers}"
            )
        self._pool: Optional[_PackPool] = None
        self._ring = None
        # slot.index → per-shard native.PackPlan list (ring mode only)
        self._slot_plans: Dict[int, List] = {}
        self._intake: Optional["queue.Queue"] = None
        self._assembler: Optional[threading.Thread] = None
        # True while the pop thread holds a popped-but-not-yet-enqueued
        # drain in its locals (set under _mutate_lock, the _packing
        # pattern) — drained() must see those frames.
        self._popping = False
        # Lease of the batch most recently returned by a getter (None on
        # the classic path). Single-consumer contract, like
        # last_batch_trace: only the learner loop pops batches.
        self.last_batch_lease = None
        # Downstream prefetch-lane station (--learner.prefetch): the
        # pipelined learner's PrefetchLane pops batches off _ready and
        # holds them (locals or its handoff queue) until the loop trains
        # them. drained() must see those popped-but-untrained frames or
        # a SIGTERM drain could declare victory one batch early — the
        # PR-7 loss class, one station further downstream. None = no
        # lane (the serial loop, or a non-learner consumer).
        self._prefetch_probe = None
        # SIGTERM drain: once set, the consumer stops popping the broker
        # but keeps packing already-pending frames into full batches —
        # the learner trains those out, then checkpoints the (< B)
        # leftover pending frames in the full-state aux manifest so a
        # drain loses ZERO popped frames. Cleared by start() (the
        # restartable-buffer contract phased drivers rely on).
        self._quiesce = threading.Event()
        # True while the consumer holds a popped-but-not-yet-queued batch
        # in its locals (set under _mutate_lock in the pop, cleared after
        # the ready-queue put) — drained() must see that batch.
        self._packing = False
        # Full-state snapshot exclusion: the consumer thread holds this
        # around its two mutation sites (_ingest, _next_batch_items) —
        # two uncontended acquires per LOOP ITERATION, never per frame —
        # and snapshot_state() takes it from the checkpoint worker, so a
        # snapshot is always a consistent cut (never a half-formed
        # batch: take-pending and the reservoir sample live inside one
        # held region) regardless of whether the consumer is running,
        # stopped, or being restarted by a phased driver.
        self._mutate_lock = threading.Lock()
        self._thread: Optional[threading.Thread] = None
        # Set when the consumer thread dies on a BatchLayoutError; the
        # learner-side getters re-raise it so the mismatch surfaces as a
        # fast failure, not silent starvation.
        self._fatal: Optional[BaseException] = None
        self._lib = None
        if getattr(cfg, "native_packer", True):
            from dotaclient_tpu import native

            self._lib = native.load_packer()
        # Wire-bytes codec for pending items (full-state checkpoints):
        # the native path stages raw frame bytes (identity), the python
        # path stages Rollout objects (serialize/deserialize) — the same
        # split the replay reservoir uses, so snapshots re-enter the SAME
        # packer unchanged on restore.
        if self._lib is not None:
            self._item_encode = lambda it: it
            self._item_decode = lambda b: b
        else:
            from dotaclient_tpu.transport.serialize import serialize_rollout

            self._item_encode = serialize_rollout
            self._item_decode = deserialize_rollout
        # In-network batch assembly (--staging.assemble, transport/
        # assemble.py): the fabric shards pre-pack every admitted frame
        # into the native packer's exact row layout and this host
        # consumes DTB1 blocks of finished rows. _ingest_assembled
        # meters the per-row sidecars (version/trace/priority/episode)
        # and _pack_assembled lands payload bytes into a TransferRing
        # slot with memcpy only — the whole learner-host pack cost
        # collapses to the fan-in concat. The spec handed to the broker
        # is derived FROM the fused layout, so a shard whose template
        # disagrees fails the layout_crc handshake at connect, never
        # mid-batch.
        self._assemble_spec = None
        if self._staging_cfg.assemble:
            if fused_io is None:
                raise ValueError(
                    "staging.assemble requires the fused H2D path: the "
                    "assembled rows ARE the transfer layout (build the "
                    "learner with fused staging)"
                )
            if self._staging_cfg.pack_workers > 1:
                raise ValueError(
                    "staging.assemble replaces the host pack pool (the "
                    "learner-side pack is concat-only) — set "
                    "staging.pack_workers=1"
                )
            enable = getattr(broker, "enable_assembled_consume", None)
            if enable is None:
                raise ValueError(
                    "staging.assemble needs a broker that serves DTB1 "
                    "blocks (transport.fabric.FabricBroker over tcp:// "
                    "shards running --broker.assemble)"
                )
            from dotaclient_tpu.transport.serialize import (
                BlockSpec,
                deserialize_block,
                serialize_block,
            )

            spec = BlockSpec(
                seq_len=cfg.seq_len,
                lstm_hidden=cfg.policy.lstm_hidden,
                with_aux=cfg.policy.aux_heads,
                obs_bf16=(
                    cfg.stage_obs_compute_dtype
                    and cfg.policy.dtype == "bfloat16"
                ),
                row_bytes=fused_io.row_bytes,
                layout_crc=fused_io.layout.layout_crc,
            )
            enable(spec)
            self._assemble_spec = spec
            # Snapshot codec: a pending AssembledRow checkpoints as a
            # 1-row DTB1 block (payload + full sidecar), so restored
            # rows re-enter the same memcpy landing unchanged.
            self._item_encode = lambda row: serialize_block(spec, [row])
            self._item_decode = lambda b: deserialize_block(b)[1][0]
        # Replay reservoir (dotaclient_tpu/replay/): owned and touched by
        # the consumer thread only, same single-writer discipline as
        # _pending. Payloads match the pending-item type — raw frame
        # bytes on the native path, Rollout objects on the python path —
        # so sampled entries re-enter the SAME packer unchanged.
        self._reservoir = None
        self._replay_target = 0
        if cfg.replay.enabled:
            if fused_io is not None:
                raise ValueError(
                    "replay reservoir and fused H2D staging are mutually "
                    "exclusive: the behavior_staleness stamp is not part of "
                    "the dtype-grouped transfer layout (the Learner builds "
                    "the tree-path train step when replay.enabled)"
                )
            if cfg.replay.max_staleness <= cfg.ppo.max_staleness:
                raise ValueError(
                    f"replay.max_staleness={cfg.replay.max_staleness} must "
                    f"exceed ppo.max_staleness={cfg.ppo.max_staleness} — a "
                    f"smaller window can never retain a frame the fresh "
                    f"filter would drop"
                )
            from dotaclient_tpu.replay import ReplayReservoir

            if self._lib is not None:
                enc = dec = None  # native items ARE serialized frames
            else:
                from dotaclient_tpu.transport.serialize import serialize_rollout

                enc, dec = serialize_rollout, deserialize_rollout
            self._reservoir = ReplayReservoir(cfg.replay, encode=enc, decode=dec, seed=cfg.seed)
            # Cap at B-1: every batch keeps at least one fresh row, so
            # batch formation always drains the broker and the loop can
            # never spin on a reservoir-only diet.
            self._replay_target = min(
                int(round(cfg.batch_size * cfg.replay.ratio)), cfg.batch_size - 1
            )
        # actor heartbeats: actor_id → last time a frame from it arrived
        # (written only by the consumer thread; stats() reads a snapshot)
        self._actor_seen: Dict[int, float] = {}
        self.heartbeat_window_s = 60.0
        # Poison-frame quarantine: a bounded dead-letter ring of frames
        # that failed parse or per-frame layout validation. Before this
        # ring, a poison frame was a `dropped_bad` tick and GONE — no
        # way to tell a corrupt wire from a misbuilt actor from a fuzzer
        # after the fact. Entries keep the evidence (reason + length +
        # header-prefix hex) bounded; the flight recorder dumps the ring
        # as a section on any fatal. Written only by the consumer
        # thread, same single-writer discipline as _pending.
        self._quarantine: collections.deque = collections.deque(maxlen=64)
        if recorder is not None:
            recorder.add_section("staging_quarantine", self.quarantine)
        self._stats_lock = threading.Lock()
        self._stats = {
            "consumed": 0,
            "dropped_stale": 0,
            "dropped_bad": 0,
            "quarantined": 0,
            "batches": 0,
            "rows_packed": 0,
            "rows_replayed": 0,
            "episode_return_sum": 0.0,
            "episodes": 0,
            "consumer_errors": 0,
            # Experience-wire meters (the DTR3 quantized-wire rollout):
            # cumulative serialized bytes entering the intake, and frames
            # split by the wire dtype of their float obs leaves. The
            # learner re-emits these as the registry-pinned wire_*_total
            # scalars — the fleetwide "who has flipped to bf16" gauge a
            # consumers-first rolling upgrade is steered by.
            "wire_bytes": 0,
            "wire_frames_obs_bf16": 0,
            "wire_frames_obs_f32": 0,
        }
        if self._staging_cfg.pack_workers > 1 or self._assemble_spec is not None:
            # Parallel-feed meters, present ONLY in pool or assembled
            # mode so default runs emit no new scalars (stats() copies
            # this dict and the learner re-emits pack_* as the
            # registry-pinned staging_pack_* family). In assembled mode
            # pack_wall_s measures the concat-only landing — the
            # headline "host pack CPU collapsed" number.
            self._stats["pack_wall_s"] = 0.0
            self._stats["pack_ring_wait_s"] = 0.0

    @property
    def native(self) -> bool:
        return self._lib is not None

    # -- consumer thread -------------------------------------------------

    def start(self) -> "StagingBuffer":
        # restartable: a prior stop() leaves _stop set — clear it so
        # phased drivers (train N steps → eval → train again, e.g.
        # scripts/train_north_star.py) can reuse one buffer
        self._stop.clear()
        self._quiesce.clear()
        if self._staging_cfg.pack_workers > 1:
            # Pool mode: fresh intake/pool/ring per start — stop() joins
            # the old threads, and ring slots may still be leased by a
            # finished learner loop, so reuse would alias live buffers.
            self._intake = queue.Queue(maxsize=4)
            self._pool = _PackPool(self._staging_cfg.pack_workers)
            if self._fused_io is not None:
                self._ring = self._fused_io.make_ring(self._staging_cfg.transfer_depth)
                self._slot_plans = {}  # plans point into the OLD ring's buffers
            self._assembler = threading.Thread(
                target=self._run_assembler, daemon=True, name="staging-assembler"
            )
            self._assembler.start()
            self._thread = threading.Thread(
                target=self._run_pop, daemon=True, name="staging-consumer"
            )
            self._thread.start()
            return self
        if self._assemble_spec is not None:
            # Assembled intake lands rows into ring slots even on the
            # single-consumer path (memcpy of batch N+1 overlaps the H2D
            # of batch N; lease protocol identical to pool mode). Fresh
            # ring per start — a finished learner loop may still hold a
            # lease on an old slot, exactly the pool-mode hazard.
            self._ring = self._fused_io.make_ring(self._staging_cfg.transfer_depth)
        self._thread = threading.Thread(target=self._run, daemon=True, name="staging-consumer")
        self._thread.start()
        return self

    def _die_on_layout(self, e: BaseException) -> None:
        """Persistent builder/staging config disagreement: crash the
        consumer LOUDLY (ADVICE r5 item 1). The learner-side getters
        re-raise _fatal so the failure is fast, not a silent per-batch
        dropped_bad starvation."""
        _log.critical("staging layout/config mismatch; consumer dying: %s", e)
        if self._recorder is not None:
            # Soak/nightly BatchLayoutError deaths were unreproducible —
            # dump the recent pipeline events (incl. the offending
            # chunks' trace hops) before dying.
            self._recorder.record("batch_layout_error", error=str(e))
            self._recorder.dump("batch_layout_error")
        self._fatal = e
        self._stop.set()

    def _pack_pending_loop(self, B: int) -> None:
        """Pack as many full batches as _pending affords into the ready
        queue. Runs on the consumer thread (classic) or the assembler
        thread (pool mode) — the thread that owns _pending either way."""
        while not self._stop.is_set():
            with self._mutate_lock:
                items, staleness, traces = self._next_batch_items(B)
                # In-flight marker, set under the SAME lock hold that
                # popped the frames: between here and the ready-queue put
                # the batch lives only in this thread's locals, and a
                # quiesced drained() that ignored it would let a SIGTERM
                # drain stop one batch early — silently losing popped
                # frames.
                self._packing = items is not None
            if items is None:
                break
            t_pack = time.perf_counter()
            try:
                batch, groups, lease = self._pack(items)
            except BatchLayoutError:
                # layout/config mismatch: fails every batch, not this
                # batch — propagate to the fatal handler in the caller
                raise
            except _StagingStopped:
                # stop() landed mid-batch (ring acquire / pool join
                # interrupted): not a frame error, just exit
                self._packing = False
                break
            except ValueError:
                # a frame passed ingest validation but failed the
                # packer — drop the batch, never livelock on it
                _log.exception("packer rejected a batch; dropping %d frames", len(items))
                with self._stats_lock:
                    self._stats["dropped_bad"] += len(items)
                self._packing = False
                continue
            if staleness is not None:
                batch = batch._replace(
                    behavior_staleness=np.asarray(staleness, np.float32)
                )
            if self._tracer is not None and traces is not None:
                self._tracer.hop_batch("pack", traces)
            with self._stats_lock:
                self._stats["batches"] += 1
                self._stats["rows_packed"] += len(items)
                if "pack_wall_s" in self._stats:
                    self._stats["pack_wall_s"] += time.perf_counter() - t_pack
                if staleness is not None:
                    self._stats["rows_replayed"] += sum(1 for s in staleness if s > 0)
            while not self._stop.is_set():
                try:
                    self._ready.put((batch, groups, traces, lease), timeout=0.2)
                    break
                except queue.Full:
                    continue
            self._packing = False  # batch visible in _ready (or dead with _stop)

    def _drain_residual(self, max_items: int, sink) -> None:
        """Quiesced-mode residual drain shared by the classic consumer
        and the pool-mode pop thread: fetch the fabric fan-in residual
        (already-popped frames) and hand it to `sink`, pacing the loop
        in place of the consume timeout. _popping makes the locals-held
        residual visible to drained() — between the fabric queue and the
        sink the frames live only in this thread's locals. The flag
        region covers ONLY the non-blocking fetch+sink — the pacing
        sleep must run with it clear, or the drain's drained() polls
        livelock against a flag that is true for 99% of every loop
        iteration."""
        with self._mutate_lock:
            self._popping = True
        try:
            frames = self._residual_frames(max_items)
            if frames:
                sink(frames)
        finally:
            with self._mutate_lock:
                self._popping = False
        if frames is None:
            time.sleep(0.02)

    def _run(self) -> None:
        """Classic single consumer thread (pack_workers=1): pop → parse →
        pack, all here — byte-for-byte the pre-pool behavior."""
        B = self.cfg.batch_size

        def _ingest_sink(frames):
            with self._mutate_lock:
                self._ingest(frames)

        while not self._stop.is_set():
            try:
                if self._quiesce.is_set():
                    # Draining: no new broker pops; ingest any fabric
                    # fan-in residual (already-popped frames) and pack
                    # out what is pending (flag/pacing protocol in
                    # _drain_residual).
                    self._drain_residual(B, _ingest_sink)
                    frames = None
                else:
                    frames = self.broker.consume_experience(max_items=B, timeout=0.2)
                if frames:
                    with self._mutate_lock:
                        self._ingest(frames)
                self._pack_pending_loop(B)
            except BatchLayoutError as e:
                self._die_on_layout(e)
                raise
            except Exception:
                # The consumer thread must never die silently — a dead
                # consumer hangs the learner in get_batch forever.
                _log.exception("staging consumer error; continuing")
                with self._stats_lock:
                    self._stats["consumer_errors"] += 1

    def _run_pop(self) -> None:
        """Pool-mode pop thread: drain the broker into the intake queue
        and NOTHING else — broker pops never sit behind parse or pack
        (the single-consumer serialization the parallel feed removes).
        The intake bound (4 drains) is the backpressure that stops an
        outrun learner from buffering the broker into learner RAM."""
        B = self.cfg.batch_size

        def _intake_sink(frames):
            while not self._stop.is_set():
                try:
                    self._intake.put(frames, timeout=0.2)
                    break
                except queue.Full:
                    continue

        while not self._stop.is_set():
            try:
                if self._quiesce.is_set():
                    # Same residual drain as the classic consumer: the
                    # fabric's already-popped frames flow on to the
                    # intake queue; the assembler ingests them as usual
                    # (flag/pacing protocol in _drain_residual).
                    self._drain_residual(B, _intake_sink)
                    continue
                with self._mutate_lock:
                    # drained() must account a drain held in this
                    # thread's locals between pop and intake put — the
                    # same visibility contract as _packing.
                    self._popping = True
                try:
                    frames = self.broker.consume_experience(max_items=B, timeout=0.2)
                    if frames:
                        _intake_sink(frames)
                finally:
                    with self._mutate_lock:
                        self._popping = False
            except Exception:
                _log.exception("staging pop error; continuing")
                with self._stats_lock:
                    self._stats["consumer_errors"] += 1

    def _run_assembler(self) -> None:
        """Pool-mode assembler: the single-writer owner of _pending, the
        reservoir, heartbeats, and quarantine (the whole consumer role
        minus the broker pop). Parses each intake drain (the batched C
        header parse releases the GIL, so this genuinely overlaps the
        pop thread and the pack workers), forms batches, and dispatches
        row-sharded packs to the worker pool."""
        B = self.cfg.batch_size
        while not self._stop.is_set():
            try:
                try:
                    frames = self._intake.get(timeout=0.2)
                except queue.Empty:
                    frames = None
                if frames is not None:
                    try:
                        with self._mutate_lock:
                            self._ingest(frames)
                    finally:
                        # unfinished_tasks hits 0 only after the frames
                        # are visible in _pending — the drained() handoff
                        self._intake.task_done()
                self._pack_pending_loop(B)
            except BatchLayoutError as e:
                self._die_on_layout(e)
                raise
            except Exception:
                _log.exception("staging assembler error; continuing")
                with self._stats_lock:
                    self._stats["consumer_errors"] += 1

    def _take_pending(self, n: int):
        """Pop the first n pending items (+ their trace refs when the
        tracer maintains the parallel list)."""
        items = self._pending[:n]
        del self._pending[:n]
        traces = None
        if self._tracer is not None:
            traces = self._pending_traces[:n]
            del self._pending_traces[:n]
        return items, traces

    def _next_batch_items(self, B: int):
        """(items, staleness-list-or-None, trace-refs-or-None) for one
        batch, or (None, None, None) when not enough material is pending.
        Replay mode fills up to `replay.ratio` of the batch from the
        reservoir — never blocking on it (a short reservoir just means
        more fresh rows) — and stamps per-row behavior-policy staleness;
        fresh rows stamp 0."""
        if self._reservoir is None:
            if len(self._pending) < B:
                return None, None, None
            items, traces = self._take_pending(B)
            return items, None, traces
        now_v = self.version_fn()
        self._reservoir.expire(now_v)
        k = min(self._replay_target, self._reservoir.occupancy)
        if len(self._pending) < B - k:
            return None, None, None
        items, traces = self._take_pending(B - k)
        staleness = [0.0] * len(items)
        for payload, version, meta in self._reservoir.sample(k, now_v):
            items.append(payload)
            staleness.append(float(max(now_v - version, 0)))
            if self._tracer is not None:
                ref = None
                if meta is not None:
                    # Fresh per-re-emit TraceRef COPY: a resident entry can
                    # be sampled into several in-flight batches (classic
                    # PER reuse, max_replays), and the learner thread hops
                    # each batch's refs concurrently with this thread —
                    # sharing one mutable ref would race on last_t and
                    # corrupt the very histograms replay debugging needs.
                    # The resident meta keeps its admit-time last_t, so
                    # every re-emit measures time-in-reservoir.
                    ref = TraceRef(meta.trace_id, meta.birth, last_t=meta.last_t)
                    self._tracer.hop("replay_reemit", ref)
                traces.append(ref)
        return items, staleness, traces

    def _pack(self, items: List):
        """(TrainBatch, groups-or-None, lease-or-None). Fused mode packs
        straight into leaf views of the dtype-grouped transfer buffers
        (no regroup copy later); dense mode matches the original layout.
        Pool mode (pack_workers > 1) row-shards the same copy across the
        worker pool — bitwise identical output for any split — and in
        fused mode targets a TransferRing slot, returned as the lease."""
        if self._assemble_spec is not None:
            return self._pack_assembled(items)
        # Fuse the compute-dtype obs cast into the copy when staging
        # targets bf16 (bitwise equal to the separate numpy astype pass
        # it replaces; ~1.1ms/batch at flagship shapes).
        obs_bf16 = (
            self.cfg.stage_obs_compute_dtype and self.cfg.policy.dtype == "bfloat16"
        )
        if self._pool is not None:
            return self._pack_sharded(items, obs_bf16)
        if self._fused_io is not None:
            # payload: groups dict, or ONE u8 buffer in single mode —
            # opaque here; the learner ships it with io.transfer_shardings()
            groups, out = self._fused_io.alloc_transfer()
            if self._lib is not None:
                from dotaclient_tpu import native

                native.pack_frames(
                    self._lib,
                    items,
                    self.cfg.seq_len,
                    self.cfg.policy.lstm_hidden,
                    self.cfg.policy.aux_heads,
                    obs_bf16=obs_bf16,
                    out=out,
                )
            else:
                # numpy handles the strided views (and the f32→bf16
                # assignment cast) transparently; no post-cast — it
                # would detach the leaves from the transfer buffers.
                fill_rollouts(out, items, self.cfg.seq_len)
            return out, groups, None
        if self._lib is not None:
            from dotaclient_tpu import native

            batch = native.pack_frames(
                self._lib,
                items,
                self.cfg.seq_len,
                self.cfg.policy.lstm_hidden,
                self.cfg.policy.aux_heads,
                obs_bf16=obs_bf16,
            )
            if obs_bf16:
                return batch, None, None  # cast already applied in-copy
            return cast_obs_to_compute_dtype(self.cfg, batch), None, None
        batch = pack_rollouts(items, self.cfg.seq_len, self.cfg.policy.aux_heads)
        return cast_obs_to_compute_dtype(self.cfg, batch), None, None

    def _pack_sharded(self, items: List, obs_bf16: bool):
        """Pool-mode pack: N workers each fill a disjoint contiguous row
        range of ONE output buffer (native: dt_pack_batch row_offset,
        GIL released; python: fill_rollouts row_offset). Fused mode
        targets a re-zeroed TransferRing slot — returned as the lease
        the learner releases once the device_put retires; dense mode
        allocates fresh (exactly the classic layout/cast semantics)."""
        B = len(items)
        T = self.cfg.seq_len
        H = self.cfg.policy.lstm_hidden
        aux = self.cfg.policy.aux_heads
        lease = None
        if self._fused_io is not None:
            t0 = time.perf_counter()
            slot = None
            while slot is None:
                if self._stop.is_set():
                    raise _StagingStopped()
                # Ring backpressure: every slot packing/ready/in-transfer.
                slot = self._ring.acquire(timeout=0.2)
            with self._stats_lock:
                self._stats["pack_ring_wait_s"] += time.perf_counter() - t0
            out, payload, lease = slot.batch, slot.payload, slot
            if self._lib is not None:
                # Ring slots are long-lived: the per-shard ctypes glue
                # (20-leaf stride validation + 24 pointer marshals,
                # ~0.06 ms GIL-held per call) is identical every batch —
                # prebuild one PackPlan per (slot, shard) and pay only
                # the frame-pointer marshal per call (native.PackPlan).
                plans = self._slot_plans.get(slot.index)
                if plans is None:
                    from dotaclient_tpu import native

                    plans = [
                        native.PackPlan(
                            self._lib, out, cnt, T, H, aux, obs_bf16, off, B
                        )
                        for off, cnt in shard_rows(B, self._pool.n)
                    ]
                    self._slot_plans[slot.index] = plans
                err = self._pool.run_tasks(
                    [
                        (lambda p=p: p.pack(items[p.row_offset : p.row_offset + p.n]))
                        for p in plans
                    ],
                    self._stop,
                )
                if err is not None:
                    lease.release()
                    raise err
                return out, payload, lease
        else:
            payload = None
            obs_dtype = None
            if obs_bf16 and self._lib is not None:
                import ml_dtypes

                obs_dtype = ml_dtypes.bfloat16
            from dotaclient_tpu.ops.batch import zeros_train_batch

            out = zeros_train_batch(B, T, H, aux, obs_dtype=obs_dtype)
        if self._lib is not None:
            from dotaclient_tpu import native

            lib = self._lib

            def task(off, cnt):
                native.pack_frames(
                    lib, items[off : off + cnt], T, H, aux,
                    obs_bf16=obs_bf16, out=out, row_offset=off, total_rows=B,
                )
        else:

            def task(off, cnt):
                fill_rollouts(out, items[off : off + cnt], T, row_offset=off)

        err = self._pool.run_sharded(task, shard_rows(B, self._pool.n), self._stop)
        if err is not None:
            if lease is not None:
                # failed batch: the slot goes straight back to free —
                # nothing downstream will ever release it
                lease.release()
            raise err
        if self._fused_io is not None:
            return out, payload, lease
        if self._lib is not None and obs_bf16:
            return out, None, None  # cast applied in-copy
        return cast_obs_to_compute_dtype(self.cfg, out), None, None

    def _pack_assembled(self, items: List):
        """Assembled-intake landing: every pending item is an
        AssembledRow whose payload already holds the exact RowLayout
        bytes, so "packing" a batch is a ring-slot acquire plus one
        C-level row concat and one bulk copy per dtype group (single
        bulk copy in single-buffer mode) — no parse, no per-field
        scatter, no cast.
        Bitwise identical to the classic pack of the same wire
        frames: the shard ran the SAME row encoder over the SAME bytes
        (scripts/ab_inet_pack.py pins this, INET_PACK_AB.json)."""
        t0 = time.perf_counter()
        slot = None
        while slot is None:
            if self._stop.is_set():
                raise _StagingStopped()
            # Ring backpressure: every slot ready or in transfer.
            slot = self._ring.acquire(timeout=0.2)
        with self._stats_lock:
            self._stats["pack_ring_wait_s"] += time.perf_counter() - t0
        payload = slot.payload
        n_rows = len(items)
        # One C-level concat of the row payloads into a [rows, row_bytes]
        # matrix (b"".join is a single allocation+memcpy pass), then
        # bulk-land it — per-row python slicing costs more than the pack
        # it replaces at B=256 (the AB's landing-strategy measurement).
        raw = np.frombuffer(
            b"".join(row.payload for row in items), np.uint8
        ).reshape(n_rows, self._fused_io.row_bytes)
        if isinstance(payload, dict):
            # Grouped transfer layout: one vectorized strided copy per
            # dtype group — the row layout's segment order/offsets are
            # the grouped layout's columns, so each group is a column
            # slice of the stacked rows.
            seg_off = self._fused_io.seg_off
            for key, buf in payload.items():
                u8 = buf.view(np.uint8)
                off = seg_off[key]
                u8[:n_rows] = raw[:, off : off + u8.shape[1]]
        else:
            payload[:n_rows] = raw
        return slot.batch, payload, slot

    def _parse(self, frame: bytes):
        """PYTHON-fallback frame parse → ((Rollout, version, L, H,
        actor_id, ep_return, last_done), None) or (None, reason) if
        malformed — reason is the quarantine label ("dtype_map" for a
        DTR3 dtype-map failure, "parse" otherwise). The native path
        never comes through here — _ingest parses a whole drain in one
        `native.frame_headers` call and keeps raw frame bytes for the C
        packer."""
        try:
            r = deserialize_rollout(frame)
        except WireDtypeError:
            return None, "dtype_map"
        except (ValueError, KeyError):
            return None, "parse"
        last_done = float(r.dones[-1]) if r.length else 0.0
        return (
            r,
            r.version,
            r.length,
            r.initial_state[0].shape[-1],
            r.actor_id,
            r.episode_return,
            last_done,
        ), None

    def _offer_replay(
        self, item, frame: bytes, version: int, current_version: int, ref=None
    ) -> bool:
        """Consumer-thread-only: admit one would-be-stale item into the
        reservoir. Priority is the PER |TD-error| proxy computed from the
        actor-stamped behavior values — the native path pays a full
        deserialize here, but only for frames that were pure waste
        before, so any admitted frame is recovered value. `ref` (the
        chunk's TraceRef) rides the reservoir entry as opaque meta so a
        later re-emit can keep the hop chain going."""
        try:
            rollout = item if isinstance(item, Rollout) else deserialize_rollout(frame)
        except (ValueError, KeyError):
            return False
        from dotaclient_tpu.replay import td_error_priority

        priority = td_error_priority(
            rollout.rewards, rollout.behavior_value, rollout.dones, self.cfg.ppo.gamma
        )
        admitted = self._reservoir.offer(
            item, version, priority, len(frame), current_version, meta=ref
        )
        if admitted and ref is not None and self._tracer is not None:
            self._tracer.hop("replay_admit", ref)
        return admitted

    def _quarantine_put(self, frame: bytes, reason: str) -> None:
        """Consumer-thread-only: file one poison frame in the dead-letter
        ring. Bounded evidence, not storage: reason + size + the first
        64 bytes as hex (covers the header of every wire layout) — a
        whole corrupt frame can be megabytes and the ring must stay
        O(64) small."""
        self._quarantine.append(
            {
                "t": time.time(),
                "reason": reason,
                "bytes": len(frame),
                "head": bytes(frame[:64]).hex(),
            }
        )
        if self._recorder is not None:
            self._recorder.record(
                "staging_quarantine", reason=reason, size=len(frame)
            )

    def quarantine(self) -> List[dict]:
        """Snapshot of the dead-letter ring (newest last). One GIL-atomic
        deque copy; the flight recorder dumps this as a section."""
        return list(self._quarantine)  # graftlint: disable=THR001(one GIL-atomic deque-snapshot copy; appends live in _ingest on the sole writer thread)

    def _ingest_assembled(self, rows: List) -> None:
        """Assembled-intake twin of _ingest: items are AssembledRows the
        fabric fan-in already fence-checked, so admission here is pure
        sidecar bookkeeping — staleness filter on the shard-stamped
        version, episode accounting from the last_done row flag, trace
        hops from the sidecar ids, heartbeats from actor_id. No parse:
        a row that reached this host was already validated (and its
        layout_crc handshake pinned) by the shard; the one defensive
        check left is the payload length, which dead-letters under the
        classic "layout" reason rather than poisoning the memcpy."""
        version_now = self.version_fn()
        min_version = version_now - self.cfg.ppo.max_staleness
        spec = self._assemble_spec
        consumed = len(rows)
        dropped_stale = dropped_bad = quarantined = episodes = 0
        ep_ret = 0.0
        now = time.monotonic()
        tr = self._tracer
        wire_bytes = 0
        wire_bf16 = wire_f32 = 0
        for row in rows:
            wire_bytes += len(row.payload)
            if len(row.payload) != spec.row_bytes:
                dropped_bad += 1
                quarantined += 1
                self._quarantine_put(row.payload, "layout")
                continue
            # The wire dtype is a block-level fact in assembled mode
            # (every row of a block shares the negotiated layout), but
            # the fleetwide bf16-rollout gauges must keep counting.
            if spec.obs_bf16:
                wire_bf16 += 1
            else:
                wire_f32 += 1
            self._actor_seen[row.actor_id] = now
            if len(self._actor_seen) > 4096:
                cutoff = now - self.heartbeat_window_s
                self._actor_seen = {
                    a: t for a, t in self._actor_seen.items() if t >= cutoff
                }
            ref = None
            if tr is not None and (row.trace_id or row.birth_time):
                ref = TraceRef(row.trace_id, row.birth_time)
                # covers serialize + shard assembly + block wire
                tr.hop("consume", ref)
            if row.version < min_version:
                dropped_stale += 1
                continue
            if row.last_done:
                episodes += 1
                ep_ret += row.episode_return
            self._pending.append(row)
            if tr is not None:
                if ref is not None:
                    tr.hop("staging_admit", ref)
                self._pending_traces.append(ref)
        with self._stats_lock:
            self._stats["consumed"] += consumed
            self._stats["dropped_stale"] += dropped_stale
            self._stats["dropped_bad"] += dropped_bad
            self._stats["quarantined"] += quarantined
            self._stats["episodes"] += episodes
            self._stats["episode_return_sum"] += ep_ret
            self._stats["wire_bytes"] += wire_bytes
            self._stats["wire_frames_obs_bf16"] += wire_bf16
            self._stats["wire_frames_obs_f32"] += wire_f32

    def _ingest(self, frames: List[bytes]) -> None:
        if self._assemble_spec is not None:
            return self._ingest_assembled(frames)
        version_now = self.version_fn()
        min_version = version_now - self.cfg.ppo.max_staleness
        H = self.cfg.policy.lstm_hidden
        consumed = len(frames)
        dropped_stale = dropped_bad = quarantined = episodes = 0
        ep_ret = 0.0
        now = time.monotonic()
        tr = self._tracer
        wire_bytes = sum(len(f) for f in frames)
        wire_bf16 = wire_f32 = 0
        # Rolling-upgrade intake for the native path: trace-stamped DTR2
        # frames are normalized here to the byte-identical DTR1 layout
        # the C packer speaks (transport.serialize.strip_rollout_trace),
        # independent of whether THIS process traces — a consumer must
        # parse every producer's frames mid-roll. Quantized DTR3 frames
        # pass through WHOLE (the C packer parses the dtype-map itself —
        # stripping would change the array encoding); only their
        # dtype-map is pre-checked here, in constant time per frame, so
        # a truncated/corrupt map dead-letters under its own "dtype_map"
        # reason instead of the generic native parse failure. An
        # all-DTR1 drain (the default-off fleet) pays one 4-byte prefix
        # check per frame and keeps the exact frame objects (no copies —
        # asserted in tests/test_obs.py). The python fallback needs
        # none of this: deserialize_rollout speaks all three magics.
        frame_traces: Optional[List] = None
        bad_maps: Dict[int, bytes] = {}
        if self._lib is not None:
            for i, f in enumerate(frames):
                pfx = f[:4]
                if pfx == b"DTR2":
                    if tr is not None:
                        if frame_traces is None:
                            frame_traces = [None] * consumed
                        tid, birth = peek_rollout_trace(f)
                        frame_traces[i] = TraceRef(tid, birth)
                    frames[i] = strip_rollout_trace(f)
                elif pfx == b"DTR3":
                    if check_dtr3_dtype_map(f) is not None:
                        # Keep the original bytes as quarantine evidence;
                        # the emptied slot fails the native header parse
                        # below, which routes it to the poison branch.
                        bad_maps[i] = f
                        frames[i] = b""
                    elif tr is not None:
                        tid, birth = peek_rollout_trace(f)
                        if tid or birth:
                            if frame_traces is None:
                                frame_traces = [None] * consumed
                            frame_traces[i] = TraceRef(tid, birth)
            # ONE ctypes call parses/validates every frame of the drain
            # (the per-frame FFI loop cost 1.3ms/batch at 256 frames —
            # r5 profile); the python loop below then touches only plain
            # ints/floats.
            from dotaclient_tpu import native

            ok, versions, Ls, Hs, _flags, actor_ids, ep_rets, last_dones = (
                native.frame_headers(self._lib, frames)
            )
            parsed_iter = (
                (
                    (frames[i], versions[i], Ls[i], Hs[i], actor_ids[i], ep_rets[i], last_dones[i])
                    if ok[i]
                    else None,
                    "dtype_map" if i in bad_maps else "parse",
                )
                for i in range(consumed)
            )
        else:
            parsed_iter = (self._parse(f) for f in frames)
        for i, (parsed, bad_reason) in enumerate(parsed_iter):
            if parsed is None:
                # Poison frame (bad magic, truncated arrays, corrupt
                # header, unsupported dtype-map): dead-letter it WITH
                # evidence instead of only ticking a counter.
                dropped_bad += 1
                quarantined += 1
                self._quarantine_put(bad_maps.get(i, frames[i]), bad_reason)
                continue
            item, version, L, frame_h, actor_id, frame_ret, last_done = parsed
            # Wire-dtype meter: native items are raw frame bytes (magic +
            # map byte check), python items are Rollouts (leaf dtype).
            if (
                wire_obs_is_bf16(item)
                if not isinstance(item, Rollout)
                else rollout_obs_bf16(item)
            ):
                wire_bf16 += 1
            else:
                wire_f32 += 1
            self._actor_seen[actor_id] = now  # heartbeat (consumer thread only)
            # Prune long-gone ids here, on the sole writer thread, so the
            # dict stays bounded without stats() ever mutating shared state.
            if len(self._actor_seen) > 4096:
                cutoff = now - self.heartbeat_window_s
                self._actor_seen = {
                    a: t for a, t in self._actor_seen.items() if t >= cutoff
                }
            # Per-frame config validation happens HERE so one misconfigured
            # actor can only ever cost its own frames, never the pack step.
            if L > self.cfg.seq_len or frame_h != H:
                dropped_bad += 1
                quarantined += 1
                self._quarantine_put(frames[i], "layout")
                continue
            ref = None
            if tr is not None:
                if frame_traces is not None:
                    ref = frame_traces[i]
                elif isinstance(item, Rollout) and item.traced:
                    # python fallback: the trace rode through deserialize
                    ref = TraceRef(item.trace_id, item.birth_time)
                if ref is not None:
                    # covers serialize + broker queueing + the wire
                    tr.hop("consume", ref)
            if version < min_version:
                # Pre-replay behavior: pure waste (dropped_stale). With
                # the reservoir on, near-stale frames are retained for
                # off-policy reuse instead; the reservoir itself rejects
                # anything past replay.max_staleness (still a stale drop).
                if self._reservoir is not None and self._offer_replay(
                    item, frames[i], version, version_now, ref=ref
                ):
                    continue
                dropped_stale += 1
                continue
            if L and last_done > 0:
                episodes += 1
                ep_ret += frame_ret
            self._pending.append(item)
            if tr is not None:
                if ref is not None:
                    tr.hop("staging_admit", ref)
                self._pending_traces.append(ref)
        with self._stats_lock:
            self._stats["consumed"] += consumed
            self._stats["dropped_stale"] += dropped_stale
            self._stats["dropped_bad"] += dropped_bad
            self._stats["quarantined"] += quarantined
            self._stats["episodes"] += episodes
            self._stats["episode_return_sum"] += ep_ret
            self._stats["wire_bytes"] += wire_bytes
            self._stats["wire_frames_obs_bf16"] += wire_bf16
            self._stats["wire_frames_obs_f32"] += wire_f32

    # -- learner side ----------------------------------------------------

    def _check_fatal(self) -> None:
        # Single atomic read: _fatal is rebound once by the dying consumer
        # thread; binding it to a local means the check and the raise can
        # never observe two different values of the attribute.
        fatal = self._fatal
        if fatal is not None:
            raise RuntimeError(
                "staging consumer died on a layout/config mismatch — every "
                "batch would fail; fix the builder/staging config disagreement"
            ) from fatal

    def _get_ready(self, timeout: Optional[float], cancel=None):
        """queue.get that stays responsive to a consumer death: waits in
        short slices and re-checks _fatal between them, so a learner
        already blocked when the consumer dies on a BatchLayoutError
        fails within ~0.2s instead of sitting out its full batch timeout
        against a queue nothing will ever fill again. `cancel` (an
        Event) aborts the wait within one slice — the prefetch lane's
        teardown hook, so a stopping lane never sits out a full batch
        timeout (and never overlaps a successor lane's pops)."""
        deadline = None if timeout is None else time.monotonic() + timeout
        while True:
            self._check_fatal()
            if cancel is not None and cancel.is_set():
                raise queue.Empty
            if self._quiesce.is_set() and self.drained(include_prefetch=False):
                # SIGTERM drain: nothing left to pack and nothing queued —
                # waiting out the full batch timeout would only burn the
                # drain budget against a queue nothing will ever fill.
                # UPSTREAM stations only: the caller here IS the consumer
                # (the prefetch lane in pipelined mode), and its own
                # mid-fetch _inflight flag covers this very wait — the
                # full-station drained() would read False forever and the
                # fast-exit would never fire, burning the whole
                # batch_timeout of the k8s drain budget (review catch;
                # regression-pinned in test_pipeline). Anything already
                # past this pop (handoff queue) is trained out by the
                # loop regardless — the "exhausted" sentinel lands
                # FIFO-last.
                raise queue.Empty
            if deadline is None:
                step = 0.2
            else:
                step = min(0.2, deadline - time.monotonic())
                if step <= 0:
                    raise queue.Empty
            try:
                return self._ready.get(timeout=step)
            except queue.Empty:
                continue

    def get_batch(
        self, timeout: Optional[float] = None, cancel=None
    ) -> Optional[TrainBatch]:
        """One packed batch (or None on timeout). On the ring path
        (pack_workers > 1 with fused_io) the batch's leaves are views
        into a leased ring slot — the caller must release
        `last_batch_lease` once done, exactly like get_batch_groups, or
        the ring stalls after transfer_depth batches."""
        try:
            item = self._get_ready(timeout, cancel=cancel)
        except queue.Empty:
            self.last_batch_lease = None
            return None
        self.last_batch_lease = item[3]
        return item[0]

    def get_batch_groups(self, timeout: Optional[float] = None, cancel=None):
        """(TrainBatch, groups) — `groups` is the ready-to-ship fused-H2D
        buffer dict when the buffer was built with fused_io, else None
        (caller falls back to io.pack). The batch's leaves are views into
        `groups`.

        Classic path (pack_workers=1): every batch allocates fresh
        buffers, so no aliasing hazard. Ring path (pack_workers>1):
        `groups` is a leased TransferRing slot — the caller must release
        `self.last_batch_lease` AFTER the device_put of `groups` has
        retired (jax.block_until_ready), at which point the slot may be
        re-zeroed and repacked; holding leases is the ring's
        backpressure.

        Side channels: `self.last_batch_trace` is set to the returned
        batch's trace refs (or None) — the learner records the h2d/apply
        hops from it — and `self.last_batch_lease` to the ring lease (or
        None). Single-consumer by contract (only the learner loop pops
        batches), so the attribute reads are race-free."""
        try:
            batch, groups, traces, lease = self._get_ready(timeout, cancel=cancel)
        except queue.Empty:
            self.last_batch_trace = None
            self.last_batch_lease = None
            return None, None
        self.last_batch_trace = traces
        self.last_batch_lease = lease
        return batch, groups

    # -- checkpoint / drain support --------------------------------------

    def _take_snapshot(self) -> dict:
        """Build the serializable staging image: pending (popped but not
        yet packed) frames as wire bytes, in order, plus the reservoir's
        own snapshot. Caller holds _mutate_lock."""
        snap: dict = {"pending": [bytes(self._item_encode(it)) for it in self._pending]}  # graftlint: disable=THR001(caller holds _mutate_lock, the same lock the consumer's two mutation sites hold)
        if self._reservoir is not None:
            snap["reservoir"] = self._reservoir.snapshot()
        return snap

    def snapshot_state(self, timeout: float = 10.0) -> Optional[dict]:
        """Checkpoint-worker side: a consistent image of the staging host
        state for the full-state aux manifest. The mutate lock excludes
        the consumer's two mutation sites, so the cut never contains a
        half-formed batch — whether the consumer is live, stopped, or
        mid-restart (phased drivers stop/start the buffer around every
        run() call). `timeout` bounds the wait against a consumer
        wedged inside a mutation (e.g. a ready-queue put stuck behind a
        stalled learner): the checkpoint degrades to state-only rather
        than stalling durability.

        Pool mode: the cut covers _pending + the reservoir (the
        assembler holds this same lock at both its mutation sites).
        Frames mid-flight in the intake queue are NOT snapshotted —
        bounded by the intake depth (4 drains), the same exposure class
        as the classic path's pop-to-ingest window; the SIGTERM drain is
        unaffected (drained() accounts every upstream station, so a
        drain trains those frames out before the final save)."""
        if not self._mutate_lock.acquire(timeout=timeout):
            return None
        try:
            return self._take_snapshot()
        finally:
            self._mutate_lock.release()

    def restore_state(self, snap: dict) -> Dict[str, int]:
        """PRE-START only (the learner restores in __init__, before any
        consumer thread exists): re-inject checkpointed pending frames —
        ahead of anything the broker will deliver, preserving the exact
        pre-kill batch-formation order — and rebuild the reservoir.
        Returns counts for the resume_* scalars."""
        restored = [self._item_decode(b) for b in snap.get("pending", [])]
        self._pending = restored  # graftlint: disable=THR001(pre-start contract: runs in Learner.__init__ before the consumer thread exists)
        if self._tracer is not None:
            # Restored frames re-enter untraced (TraceRefs are
            # process-local); the parallel list must stay aligned.
            self._pending_traces = [None] * len(restored)
        restored_reservoir = 0
        if self._reservoir is not None and "reservoir" in snap:
            restored_reservoir = self._reservoir.restore(snap["reservoir"])
        return {"pending": len(restored), "reservoir": restored_reservoir}

    def _residual_frames(self, max_items: int):
        """Quiesced-intake residual: frames a fabric broker's fan-in pop
        threads already took OFF the shards before quiesce landed
        (transport/fabric.py consume_residual). They are POPPED frames —
        the PR-7 zero-loss drain contract owns them — so the quiesced
        consumer keeps ingesting them instead of new broker pops. None
        on classic brokers (no such station exists)."""
        residual = getattr(self.broker, "consume_residual", None)
        if residual is None:
            return None
        frames = residual(max_items)
        return frames or None

    def quiesce(self) -> None:
        """Stop popping the broker; keep packing already-pending frames.
        The SIGTERM drain's first act — see _quiesce in __init__. A
        fabric broker quiesces WITH us (its shard pop threads stop
        pulling new frames), and its already-popped residual is drained
        through _residual_frames so no popped frame strands between the
        shards and staging."""
        broker_quiesce = getattr(self.broker, "quiesce", None)
        if broker_quiesce is not None:
            broker_quiesce()
        self._quiesce.set()

    def attach_prefetch_probe(self, probe: Callable[[], bool]) -> None:
        """Register the pipelined learner's prefetch-lane station
        (runtime/learner.py PrefetchLane.holding): a callable that is
        True while the lane holds popped-but-untrained frames — in its
        thread locals mid-fetch or in its handoff queue. drained()
        checks it LAST (the lane sits downstream of the ready queue;
        frames only move downstream, the upstream-first rule)."""
        self._prefetch_probe = probe

    def drained(self, include_prefetch: bool = True) -> bool:
        """True once a quiesced buffer can produce no further batch: the
        ready queue is empty and pending holds fewer frames than the
        next batch's fresh-row requirement. Learner-thread gauge reads
        of consumer-owned counters (len/occupancy) are single GIL-atomic
        calls; a one-frame drift only delays the verdict by one poll.

        `include_prefetch=False` is the prefetch lane's OWN exhaustion
        check ("will anything more ever arrive upstream?") — the lane
        must not count its already-delivered holdings against itself, or
        a drain would livelock on the batch the loop is about to train.
        Every external caller keeps the default: the full zero-loss
        verdict includes the lane station."""
        if not self._quiesce.is_set():
            return False
        # Pool mode adds two upstream stations frames can occupy: the
        # pop thread's locals (_popping, the _packing pattern) and the
        # intake queue (unfinished_tasks stays nonzero until the
        # assembler's ingest has made the frames visible in _pending).
        # Check stations UPSTREAM-first — frames only move downstream
        # (pop → intake → pending → in-flight pack → ready), so a frame
        # crossing a boundary mid-check is seen at the later station.
        # A fabric broker adds the MOST upstream station: frames its
        # fan-in threads popped off the shards before quiesce (they are
        # popped — the zero-loss contract owns them; the quiesced
        # consumer drains them via _residual_frames).
        fanin_residual = getattr(self.broker, "fanin_residual", None)
        if fanin_residual is not None and fanin_residual():
            return False
        with self._mutate_lock:
            if self._popping:
                return False
        if self._intake is not None and self._intake.unfinished_tasks:
            return False
        # (packing, pending) must be observed atomically with the
        # consumer's pop — it sets _packing under this same lock hold
        # that empties _pending, so a batch is ALWAYS visible as one of:
        # pending frames, the in-flight flag, or a ready-queue entry.
        # Check _ready LAST (that is the direction batches move).
        with self._mutate_lock:
            if self._packing:
                return False
            need = self.cfg.batch_size
            if self._reservoir is not None:
                need -= min(self._replay_target, self._reservoir.occupancy)
            if len(self._pending) >= need:  # graftlint: disable=THR001(read is under _mutate_lock; the consumer's mutation call sites (_ingest/_next_batch_items in _run) hold the same lock — lexically outside the mutating functions, so the rule cannot see it)
                return False
        if not self._ready.empty():
            return False
        # The most DOWNSTREAM station: a batch the prefetch lane popped
        # off _ready but the loop has not trained yet (--learner.prefetch).
        if include_prefetch:
            probe = self._prefetch_probe
            if probe is not None and probe():
                return False
        return True

    def stats(self) -> Dict[str, float]:
        with self._stats_lock:
            out = dict(self._stats)
        out["ready_batches"] = self._ready.qsize()
        # len() of a list the consumer thread appends/deletes is one
        # GIL-atomic C call; a gauge that drifts by one in-flight frame
        # is acceptable and a lock here would serialize every scrape
        # against the packer.
        out["pending_rollouts"] = len(self._pending)  # graftlint: disable=THR001(one GIL-atomic len read; gauge may drift by one in-flight frame)
        # heartbeat gauge: actors heard from within the window (dict reads
        # are atomic enough; values drift by at most one frame)
        cutoff = time.monotonic() - self.heartbeat_window_s
        # dict() of the consumer-written heartbeat map is a single
        # GIL-atomic snapshot copy; item writes land entirely before or
        # entirely after it.
        seen = dict(self._actor_seen)  # graftlint: disable=THR001(one GIL-atomic dict-copy snapshot; pruning lives in _ingest on the sole writer thread)
        out["active_actors"] = sum(1 for t in seen.values() if t >= cutoff)
        if self._reservoir is not None:
            for k, v in self._reservoir.stats().items():
                out[f"replay_{k}"] = v
            # Fraction of packed rows served from the reservoir — the
            # headline "how much previously-wasted work is being reused".
            out["replay_hit_ratio"] = out["rows_replayed"] / max(out["rows_packed"], 1)
        if self._pool is not None:
            # Parallel-feed scoreboard (staging_pack_* once the learner
            # re-emits them): per-worker busy/stall seconds, ring
            # occupancy, and packer-proper rows/s (rows over the summed
            # per-batch pack walls — the sharded-pack rate itself, not
            # the e2e rate).
            busy, stall, _done = self._pool.meters()
            out["pack_workers"] = float(self._pool.n)
            for i in range(self._pool.n):
                out[f"pack_worker_busy_s_{i}"] = round(busy[i], 4)
                out[f"pack_worker_stall_s_{i}"] = round(stall[i], 4)
            if self._ring is not None:
                out["pack_ring_depth"] = float(self._ring.depth)
                out["pack_ring_occupancy"] = float(self._ring.occupancy)
            out["pack_rows_per_s"] = out["rows_packed"] / max(
                out.get("pack_wall_s", 0.0), 1e-9
            )
        elif self._ring is not None:
            # Assembled intake: ring gauges without a pool (the concat
            # landing runs on the one consumer thread).
            out["pack_ring_depth"] = float(self._ring.depth)
            out["pack_ring_occupancy"] = float(self._ring.occupancy)
        return out

    def stop(self) -> None:
        self._stop.set()
        if self._thread:
            self._thread.join(timeout=5)
        if self._assembler is not None:
            self._assembler.join(timeout=5)
            self._assembler = None
        if self._pool is not None:
            self._pool.stop()
