"""ChaosBroker: wrap any transport.base.Broker in a seeded fault
schedule.

Sits ABOVE the transport client, so its faults model what the wire and
a misbehaving peer can do to the pipeline: corrupted/truncated frames
(→ the staging quarantine must catch them), duplicate delivery (→ the
conservation ledger must account them), connection resets (→ producer
retry/degradation paths), admission sheds (→ the actor throttle), added
latency and scheduled stalls (→ staleness filtering and the watchdog).
Broker KILLS are the one fault a client-side wrapper cannot execute;
chaos/controller.py owns those against the real server.

Fault decisions are a pure function of (seed, spec, op-index) —
chaos/schedule.py — so a failing soak replays bit-identically. The
wrapper is never constructed in production: config gating in the
binaries means `dotaclient_tpu.chaos` is not even IMPORTED unless
--chaos.enabled (asserted in tests/test_chaos.py).
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from dotaclient_tpu.chaos.schedule import FaultSchedule, corrupt_bytes, truncate_bytes
from dotaclient_tpu.transport.base import Broker, BrokerShedError


class ChaosBroker(Broker):
    """Fault-injecting Broker decorator.

    Experience ops get the full fault set; weight ops get latency/stall
    only — weight-path outages are exercised by the kill events (a
    poll_weights reset would kill an actor outright rather than degrade
    it, which is a different experiment than graceful degradation).

    `t0` anchors the timed events; pass one shared epoch when several
    wrapped brokers must see the same schedule (the soak's actor fleet).
    Thread-safe: the op counter is lock-guarded (actors publish from
    many threads in ActorPool drivers).
    """

    def __init__(
        self,
        inner: Broker,
        schedule: FaultSchedule,
        t0: Optional[float] = None,
        clock=time.monotonic,
        sleep=time.sleep,
    ):
        self.inner = inner
        self.schedule = schedule
        self._clock = clock
        self._sleep = sleep
        self.t0 = clock() if t0 is None else t0
        self._lock = threading.Lock()
        self._ops = 0
        # chaos_* meters (obs/registry.py family): what the layer DID —
        # the soak artifact's injected-fault inventory.
        self.meters = {
            "chaos_ops": 0,
            "chaos_corrupted": 0,
            "chaos_truncated": 0,
            "chaos_duplicated": 0,
            "chaos_resets": 0,
            "chaos_sheds": 0,
            "chaos_stall_s": 0.0,
            "chaos_latency_s": 0.0,
        }

    # ------------------------------------------------------------ common

    def _next_op(self):
        with self._lock:
            i = self._ops
            self._ops += 1
            self.meters["chaos_ops"] += 1
        return self.schedule.decide(i)

    def _pay_delays(self, faults) -> None:
        stall = self.schedule.stall_remaining(self._clock() - self.t0)
        if stall > 0:
            with self._lock:
                self.meters["chaos_stall_s"] += stall
            self._sleep(stall)
        if faults.latency_s > 0:
            with self._lock:
                self.meters["chaos_latency_s"] += faults.latency_s
            self._sleep(faults.latency_s)

    def _count(self, key: str) -> None:
        with self._lock:
            self.meters[key] += 1

    # -------------------------------------------------------- experience

    def publish_experience(self, data: bytes) -> None:
        f = self._next_op()
        self._pay_delays(f)
        if f.reset:
            self._count("chaos_resets")
            raise ConnectionResetError("chaos: injected connection reset on publish")
        if f.shed:
            self._count("chaos_sheds")
            raise BrokerShedError("chaos: injected shed on publish")
        poison_meter = None
        if f.truncate:
            data = truncate_bytes(data, f.rng)
            poison_meter = "chaos_truncated"
        elif f.corrupt:
            data = corrupt_bytes(data, f.rng)
            poison_meter = "chaos_corrupted"
        self.inner.publish_experience(data)
        # Poison is counted only when the inner publish SUCCEEDED: the
        # meters feed conservation cross-checks (quarantined vs injected
        # poison), so a corrupted frame the dead broker never accepted
        # must not be claimed as delivered.
        if poison_meter is not None:
            self._count(poison_meter)
        if f.dup:
            # Best-effort duplicate, counted ONLY on success: the meter
            # is the conservation ledger's dup-extras term, so a shed or
            # failed duplicate must not claim a frame it never delivered.
            try:
                self.inner.publish_experience(data)
            except Exception:
                pass
            else:
                self._count("chaos_duplicated")

    def consume_experience(self, max_items: int, timeout: Optional[float] = None) -> List[bytes]:
        f = self._next_op()
        self._pay_delays(f)
        if f.reset:
            self._count("chaos_resets")
            raise ConnectionResetError("chaos: injected connection reset on consume")
        return self.inner.consume_experience(max_items, timeout=timeout)

    # ----------------------------------------------------------- weights

    def publish_weights(self, data: bytes) -> None:
        f = self._next_op()
        self._pay_delays(f)
        self.inner.publish_weights(data)

    def poll_weights(self) -> Optional[bytes]:
        f = self._next_op()
        self._pay_delays(f)
        return self.inner.poll_weights()

    # ------------------------------------------------------------- misc

    def experience_depth(self) -> int:
        return self.inner.experience_depth()

    def stats(self) -> dict:
        with self._lock:
            out = dict(self.meters)
        inner_stats = getattr(self.inner, "stats", None)
        if callable(inner_stats):
            try:
                out.update(inner_stats())
            except Exception:
                pass  # a dead inner broker must not kill a meters read
        return out

    def close(self) -> None:
        self.inner.close()
