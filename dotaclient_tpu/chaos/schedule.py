"""Seeded, deterministic fault schedules — the grammar behind
`--chaos.spec` / `--chaos.seed`.

A spec is a comma-separated list of clauses:

Rate faults (fire probabilistically, decided PER OPERATION INDEX from
the seed — same seed + spec replays the same faults at the same
operation indices, which is what makes a chaos failure a bug report
instead of an anecdote):

    corrupt:P       flip bytes of a published frame with prob P
    truncate:P      cut a published frame's tail with prob P
    dup:P           deliver a published frame twice with prob P
    reset:P         fail the op with ConnectionResetError with prob P
    shed:P          refuse a publish with BrokerShedError with prob P
    latency:M~J     add M±J seconds of sleep to every op (J optional)

Timed one-shots (wall-clock offsets from the schedule epoch `t0`):

    stall@T:D       every broker op blocks for the window [T, T+D)
    rolling@T:P@server
    rolling@T:P@broker
                    staggered sequential restarts across a replicated
                    tier, starting at T: kill replica 0, keep it down
                    P seconds, restart it, wait for its recovery probe,
                    then replica 1, and so on — at most ONE replica is
                    ever down, the rolling-deploy shape. `server` rolls
                    the serve tier (PR 13); `broker` rolls the broker
                    fabric's shard fleet (transport/fabric.py — the
                    shard-kill soak's at-most-one-shard-down arm).
                    Executed by a ScheduleRunner whose matching
                    controller fans kills across replicas (a
                    replica_count()-bearing router, or a bare
                    ServeIncarnations/BrokerIncarnations = 1 replica).
                    The selector rides the ARG side like the kill
                    targets, so existing specs parse byte-identically
                    and no rate draw ever moves (the golden
                    decision-sequence pin covers it).
    scale@T:N@TIER  set TIER's replica count to N at T. TIER is
                    `broker` (fabric shards), `server` (serve
                    replicas), or `actor` (the actor fleet). Executed
                    by the control tier (dotaclient_tpu/control/) or a
                    soak harness against a driver that owns the tier's
                    replica routers — a client-side wrapper cannot add
                    or remove processes, the kill@ argument again. The
                    replica count rides the duration slot and the tier
                    selector rides the ARG side, so existing specs
                    parse byte-identically and scale clauses consume
                    ZERO per-op rate draws (the golden
                    decision-sequence pin covers it).
    kill@T:D        kill the broker at T, restart it at T+D — executed
                    by a ScheduleRunner against a controller that owns
                    the broker process (chaos/controller.py), because a
                    client-side wrapper cannot kill a server
    kill@T:D@TGT    kill-target selector: TGT is `broker` (the default,
                    identical to the bare form), `learner[:SIG]`
                    where SIG is `kill` (SIGKILL semantics: nothing
                    saved, recovery from the last periodic checkpoint)
                    or `term` (SIGTERM drain: train out staged batches,
                    full-state save, clean exit) — executed against a
                    LearnerIncarnations controller — or `server` (the
                    inference service, dotaclient_tpu/serve/), executed
                    against a ServeIncarnations controller (sequential
                    in-process InferenceServer lives on one port,
                    per-life ledgers, first-served-step recovery probe;
                    scripts/soak_serve_chaos.py is the closed-loop
                    proof). Timed events never consume per-op rate
                    draws, so the selector leaves the canonical draw
                    order of every existing spec untouched (pinned by
                    the golden decision-sequence test in
                    tests/test_chaos.py — including the server target).

Determinism contract: the decision for operation index i draws from
`random.Random(seed * 1_000_003 + i)` in a FIXED canonical order, for
every fault type whether configured or not — so decisions at index i
are identical across runs AND stable when unrelated clauses are added
to the spec.
"""

from __future__ import annotations

import random
from dataclasses import dataclass, field
from typing import List, Optional, Tuple

# Canonical per-op draw order (schedule determinism contract above).
_RATE_FAULTS = ("corrupt", "truncate", "dup", "reset", "shed")


@dataclass
class TimedEvent:
    kind: str  # "stall" | "kill" | "rolling" | "scale"
    at_s: float  # offset from the schedule epoch
    duration_s: float  # down window (per replica, for rolling); replica count for scale
    target: str = "broker"  # "broker" | "learner" | "server" | "actor" (scale only)
    signal: str = "kill"  # "kill" (SIGKILL) | "term" (SIGTERM drain); learner only


@dataclass
class OpFaults:
    """The decided faults for ONE operation index."""

    corrupt: bool = False
    truncate: bool = False
    dup: bool = False
    reset: bool = False
    shed: bool = False
    latency_s: float = 0.0
    # seeded sub-rng for data-dependent choices (which bytes to flip,
    # where to cut) so those are reproducible too
    rng: Optional[random.Random] = None


@dataclass
class FaultSchedule:
    seed: int = 0
    rates: dict = field(default_factory=dict)  # fault name -> probability
    latency_mean_s: float = 0.0
    latency_jitter_s: float = 0.0
    events: List[TimedEvent] = field(default_factory=list)

    @classmethod
    def parse(cls, spec: str, seed: int = 0) -> "FaultSchedule":
        sched = cls(seed=seed)
        for clause in filter(None, (c.strip() for c in spec.split(","))):
            name, _, arg = clause.partition(":")
            if "@" in name:
                kind, _, at = name.partition("@")
                if kind not in ("stall", "kill", "rolling", "scale"):
                    raise ValueError(f"unknown timed fault {kind!r} in {clause!r}")
                if kind == "scale":
                    # scale@T:N@TIER — a topology set-point, not a
                    # fault: the tier selector is MANDATORY (there is
                    # no default tier to scale) and N must be a whole
                    # replica count >= 1 (scale-to-zero is a kill, and
                    # kills already exist).
                    n_s, _, tier = arg.partition("@")
                    if not tier or ":" in tier:
                        raise ValueError(
                            f"scale needs scale@T:N@broker|server|actor, got {clause!r}"
                        )
                    if tier not in ("broker", "server", "actor"):
                        raise ValueError(f"unknown scale tier {tier!r} in {clause!r}")
                    n = float(n_s)
                    if n != int(n) or int(n) < 1:
                        raise ValueError(
                            f"scale replica count must be an integer >= 1 in {clause!r}"
                        )
                    sched.events.append(TimedEvent("scale", float(at), n, target=tier))
                    continue
                # kill@T:D@TGT[:SIG] / rolling@T:P@server — the target
                # selector rides the ARG side of the clause, so existing
                # bare specs parse byte-identically (target defaults to
                # broker) and the canonical rate-draw order never moves.
                dur, _, tail = arg.partition("@")
                target, sig = ("server" if kind == "rolling" else "broker"), "kill"
                if tail:
                    if kind == "stall":
                        raise ValueError(
                            f"target selector only applies to kill/rolling, not "
                            f"{kind!r} in {clause!r}"
                        )
                    target, _, sig_s = tail.partition(":")
                    if kind == "rolling":
                        # rolling targets the two N-replica tiers: the
                        # serve tier (PR 13) and the broker fabric's
                        # shard fleet (transport/fabric.py — a shard
                        # router with replica_count() fans the kills).
                        # The learner stays a singleton where rolling
                        # degenerates to kill.
                        if target not in ("server", "broker") or sig_s:
                            raise ValueError(
                                f"rolling restarts target the serve tier or the "
                                f"broker fabric (rolling@T:P@server|broker) in "
                                f"{clause!r}"
                            )
                    elif target not in ("broker", "learner", "server"):
                        raise ValueError(f"unknown kill target {target!r} in {clause!r}")
                    if sig_s:
                        if target != "learner":
                            raise ValueError(
                                f"signal selector needs target learner in {clause!r}"
                            )
                        if sig_s not in ("kill", "term"):
                            raise ValueError(f"unknown kill signal {sig_s!r} in {clause!r}")
                        sig = sig_s
                sched.events.append(
                    TimedEvent(kind, float(at), float(dur), target=target, signal=sig)
                )
            elif name == "latency":
                mean, _, jit = arg.partition("~")
                sched.latency_mean_s = float(mean)
                sched.latency_jitter_s = float(jit) if jit else 0.0
            elif name in _RATE_FAULTS:
                p = float(arg)
                if not 0.0 <= p <= 1.0:
                    raise ValueError(f"fault probability out of range in {clause!r}")
                sched.rates[name] = p
            else:
                raise ValueError(f"unknown fault {name!r} in {clause!r}")
        sched.events.sort(key=lambda e: e.at_s)
        return sched

    # ----------------------------------------------------- per-op decide

    def decide(self, op_index: int) -> OpFaults:
        """The faults for operation `op_index` — pure function of
        (seed, spec, op_index)."""
        rng = random.Random(self.seed * 1_000_003 + op_index)
        out = OpFaults()
        # fixed canonical draw order, configured or not (determinism
        # contract: adding a clause must not shift other draws)
        for name in _RATE_FAULTS:
            draw = rng.random()
            if draw < self.rates.get(name, 0.0):
                setattr(out, name, True)
        jitter_draw = rng.random()
        if self.latency_mean_s > 0.0:
            out.latency_s = max(
                0.0,
                self.latency_mean_s + (2.0 * jitter_draw - 1.0) * self.latency_jitter_s,
            )
        out.rng = rng
        return out

    # ------------------------------------------------------ timed events

    def stalls(self) -> List[TimedEvent]:
        return [e for e in self.events if e.kind == "stall"]

    def kills(self) -> List[TimedEvent]:
        """Kill-class timed events a ScheduleRunner executes — bare
        kills AND rolling restarts (a rolling event is a kill sequence
        fanned across replicas)."""
        return [e for e in self.events if e.kind in ("kill", "rolling")]

    def scales(self) -> List[TimedEvent]:
        """Scale set-points (scale@T:N@tier) in schedule order — the
        control tier's deterministic topology script. `duration_s`
        carries the target replica count; kept OUT of kills() so every
        existing ScheduleRunner routes exactly what it did before."""
        return [e for e in self.events if e.kind == "scale"]

    def stall_remaining(self, elapsed_s: float) -> float:
        """Seconds an op starting at `elapsed_s` (since epoch) must block
        to honor any active stall window."""
        for e in self.stalls():
            if e.at_s <= elapsed_s < e.at_s + e.duration_s:
                return e.at_s + e.duration_s - elapsed_s
        return 0.0


def corrupt_bytes(data: bytes, rng: random.Random, n_flips: int = 4) -> bytes:
    """Flip up to `n_flips` bytes at seeded positions, never changing the
    length (truncation is its own fault). The FIRST flip always lands in
    the magic (bytes 0..3): payload-only corruption is undetectable
    without wire checksums (a known limitation — the frame parses and
    the garbage trains), and this fault exists to exercise the DETECTED
    path: parse rejection → staging quarantine, with the conservation
    ledger able to cross-check quarantined ≈ corrupted + truncated."""
    if not data:
        return data
    buf = bytearray(data)
    buf[rng.randrange(min(4, len(buf)))] ^= 0xFF
    for _ in range(min(n_flips - 1, len(buf))):
        i = rng.randrange(len(buf))
        buf[i] ^= 0xFF
    return bytes(buf)


def truncate_bytes(data: bytes, rng: random.Random) -> bytes:
    """Cut the frame at a seeded point in its back half (an empty or
    header-only stub is the corrupt fault's job)."""
    if len(data) < 2:
        return data
    cut = rng.randrange(len(data) // 2, len(data))
    return data[:cut]
