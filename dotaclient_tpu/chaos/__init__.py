"""Seeded fault injection for the actors→broker→staging→learner pipe.

The ROADMAP's broker-sharding item needs load-shed and backpressure
that have actually been PROVEN against faults, and the only way to
trust recovery code is to run it — on purpose, reproducibly. This
package wraps the production plugin boundaries (the Broker interface,
the env stub) in deterministic scheduled faults:

- chaos/schedule.py   the `--chaos.spec` grammar + per-op-index seeded
                      decisions (same seed+spec ⇒ same faults at the
                      same op indices);
- chaos/broker.py     ChaosBroker: corrupt/truncate/dup/reset/shed/
                      latency/stall around any Broker;
- chaos/env.py        ChaosEnvStub: env latency + session-loss faults
                      inside the protocol the actor already handles;
- chaos/controller.py broker, learner AND inference-server kill/restart
                      execution (kill@T:D@broker|learner[:term|kill]|
                      server routing) + exact per-incarnation
                      conservation ledgers.

Production inertness is a hard contract: binaries import this package
ONLY under `--chaos.enabled` (k8s manifests pin it false), so the off
path has zero new imports and byte-identical wire behavior — asserted
by tests/test_chaos.py::test_chaos_off_is_import_free_and_wire_identical.

    from dotaclient_tpu.chaos import wrap_broker
    broker = wrap_broker(broker, cfg.chaos)   # cfg.chaos.enabled is True

scripts/chaos_soak.py composes all of it into the closed-loop
degradation proof (CHAOS_SOAK.json).
"""

from __future__ import annotations

from dotaclient_tpu.chaos.broker import ChaosBroker
from dotaclient_tpu.chaos.controller import (
    BrokerIncarnations,
    LearnerIncarnations,
    ScheduleRunner,
    ServeIncarnations,
)
from dotaclient_tpu.chaos.env import ChaosEnvStub
from dotaclient_tpu.chaos.schedule import FaultSchedule, OpFaults, TimedEvent

__all__ = [
    "BrokerIncarnations",
    "ChaosBroker",
    "ChaosEnvStub",
    "FaultSchedule",
    "LearnerIncarnations",
    "OpFaults",
    "ScheduleRunner",
    "ServeIncarnations",
    "TimedEvent",
    "wrap_broker",
    "wrap_env_stub",
]


def wrap_broker(broker, chaos_cfg, t0=None):
    """Broker decorator factory for the binaries: parse the spec once,
    wrap. Callers gate on cfg.chaos.enabled BEFORE importing this
    package (the inertness contract)."""
    if hasattr(broker, "fanin_residual"):
        # A broker FABRIC (transport/fabric.py) cannot be chaos-wrapped:
        # ChaosBroker forwards only the base Broker surface, so the
        # wrapper would silently strip quiesce/consume_residual/
        # fanin_residual (the SIGTERM drain would declare victory over
        # frames stranded in the fan-in queue — a zero-loss-contract
        # violation, not a fault injection), fabric_stats, and the
        # per-endpoint routing the actor throttle keys on. Inject
        # faults into individual SHARDS instead (chaos-wrapped shard
        # clients, or the fabric soak's BrokerIncarnations kills).
        raise ValueError(
            "chaos cannot wrap a broker fabric (comma --broker_url): the "
            "wrapper would strip the fabric's drain/routing surface — "
            "point chaos at individual shards or use the fabric soak's "
            "shard-kill schedules instead"
        )
    schedule = FaultSchedule.parse(chaos_cfg.spec, seed=chaos_cfg.seed)
    return ChaosBroker(broker, schedule, t0=t0)


def wrap_env_stub(stub, chaos_cfg):
    schedule = FaultSchedule.parse(chaos_cfg.spec, seed=chaos_cfg.seed)
    return ChaosEnvStub(stub, schedule)
