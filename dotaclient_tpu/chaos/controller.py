"""Broker kill/restart execution + post-mortem ledger harvesting.

The one fault a client-side wrapper cannot inject is the broker DYING:
that belongs to whoever owns the server process. `BrokerIncarnations`
owns a sequence of in-process tcp BrokerServer incarnations on ONE port
and harvests each incarnation's conservation ledger at kill time —
exact, because the counters are read AFTER stop() joined the server
loop. `ScheduleRunner` executes a FaultSchedule's kill events against
it on a side thread.

Recovery-time probe: each incarnation records the monotonic time of its
first post-boot enqueue (transport/tcp.py `first_enqueue_t`); recovery
after a kill = that minus the restart completion time — i.e. how long
the fleet's jittered reconnect/backoff took to actually land a frame in
the reborn broker.
"""

from __future__ import annotations

import threading
import time
from typing import List, Optional

from dotaclient_tpu.chaos.schedule import FaultSchedule
from dotaclient_tpu.transport.tcp import BrokerServer


class BrokerIncarnations:
    """N sequential BrokerServer lives on one port, ledgers kept."""

    def __init__(self, port: int = 0, maxlen: int = 4096, shed_high: int = 0, shed_low: int = 0):
        self.maxlen, self.shed_high, self.shed_low = maxlen, shed_high, shed_low
        self.server = BrokerServer(
            port=port, maxlen=maxlen, shed_high=shed_high, shed_low=shed_low
        ).start()
        self.port = self.server.port
        self.ledgers: List[dict] = []  # one per DEAD incarnation
        self.kill_times: List[float] = []
        self.restart_times: List[float] = []
        self._lock = threading.Lock()

    def kill(self) -> dict:
        """Stop the live server and harvest its exact ledger. The dead
        incarnation is unbound immediately so a final_ledger() landing
        before any restart (runner stopped mid-down-window, restart
        raised) can never harvest — and double-count — the same life."""
        with self._lock:
            if self.server is None:
                raise RuntimeError("kill() with no live incarnation")
            self.server.stop()
            led = self.server.ledger()
            self.server = None
            led["killed_at"] = time.monotonic()
            self.ledgers.append(led)
            self.kill_times.append(led["killed_at"])
            return led

    def restart(self) -> None:
        """Bring a fresh incarnation up on the SAME port. Bounded retry:
        the dead server's socket can linger briefly."""
        with self._lock:
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    self.server = BrokerServer(
                        port=self.port,
                        maxlen=self.maxlen,
                        shed_high=self.shed_high,
                        shed_low=self.shed_low,
                    ).start()
                    break
                except (RuntimeError, OSError):
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.1)
            self.restart_times.append(time.monotonic())

    def final_ledger(self) -> dict:
        """Stop the last incarnation (if live) and sum every life's
        counters into one run ledger."""
        with self._lock:
            if self.server is not None:
                self.server.stop()
                led = self.server.ledger()
                led["killed_at"] = None  # run end, not a chaos kill
                self.ledgers.append(led)
                self.server = None
            total = {
                k: sum(l[k] for l in self.ledgers)
                for k in (
                    "enqueued", "popped", "dropped_oldest", "shed",
                    "shed_closes", "reply_lost", "resident",
                )
            }
            total["incarnations"] = len(self.ledgers)
            return total

class ScheduleRunner:
    """Execute a schedule's kill events against BrokerIncarnations on a
    daemon thread, relative to a shared epoch `t0`."""

    def __init__(self, schedule: FaultSchedule, broker: BrokerIncarnations, t0: float):
        self.schedule = schedule
        self.broker = broker
        self.t0 = t0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (kill_index, restart_monotonic, first_enqueue_monotonic | None)
        self.recovery: List[dict] = []

    def start(self) -> "ScheduleRunner":
        self._thread = threading.Thread(target=self._run, daemon=True, name="chaos-kills")
        self._thread.start()
        return self

    def _sleep_until(self, at_s: float) -> bool:
        """Sleep to schedule-offset at_s; False if stopped first."""
        while not self._stop.is_set():
            remaining = (self.t0 + at_s) - time.monotonic()
            if remaining <= 0:
                return True
            self._stop.wait(min(remaining, 0.2))
        return False

    def _run(self) -> None:
        for k, ev in enumerate(self.schedule.kills()):
            if not self._sleep_until(ev.at_s):
                return
            self.broker.kill()
            if not self._sleep_until(ev.at_s + ev.duration_s):
                return
            self.broker.restart()
            restarted = time.monotonic()
            # recovery probe: poll the reborn incarnation's first-enqueue
            # stamp for up to 30s (clients are backing off with jitter)
            first = None
            deadline = time.monotonic() + 30.0
            while time.monotonic() < deadline and not self._stop.is_set():
                t = self.broker.server.first_enqueue_t
                if t is not None:
                    first = t
                    break
                time.sleep(0.05)
            self.recovery.append(
                {
                    "kill_index": k,
                    "at_s": ev.at_s,
                    "down_s": round(ev.duration_s, 3),
                    "recovery_s": None if first is None else round(first - restarted, 3),
                }
            )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
