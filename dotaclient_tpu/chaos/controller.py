"""Kill/restart execution + post-mortem ledger harvesting, for the
stateful processes a client-side fault wrapper cannot kill.

`BrokerIncarnations` owns a sequence of in-process tcp BrokerServer
incarnations on ONE port and harvests each incarnation's conservation
ledger at kill time — exact, because the counters are read AFTER stop()
joined the server loop. `LearnerIncarnations` is its learner-side
sibling: sequential in-process Learner lives against one broker and one
checkpoint directory, with SIGTERM (drain: the same request_drain →
train-out → drain_save path the real signal handler invokes) and
SIGKILL (abort mid-flight, discard queued saves, nothing persisted
beyond what already hit disk) variants — in-process for the same reason
the broker is: a real kill -9 vaporizes the very counters the
conservation proof needs, while the abandoned object still holds them.
`ServeIncarnations` is the serving-tier third: sequential in-process
InferenceServer lives on one port, with per-life ledgers (requests
served, carries stranded at kill = episodes the kill abandoned,
evictions, weight swaps). `ScheduleRunner` executes a FaultSchedule's
kill events against any of the three on a side thread, routed by the
spec's kill-target selector
(`kill@T:D@broker|learner[:sig]|server`, chaos/schedule.py).

Recovery-time probes: a broker incarnation records the monotonic time
of its first post-boot enqueue (transport/tcp.py `first_enqueue_t`);
recovery after a broker kill = that minus the restart completion time —
how long the fleet's jittered reconnect/backoff took to actually land a
frame in the reborn broker. A learner incarnation's recovery = restart
completion to its first post-restore trained step (the version counter
advancing past the resumed high-water mark). A serve incarnation's
recovery = restart completion to its first post-restart SERVED step
(`first_request_t`, serve/server.py).
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, List, Optional

from dotaclient_tpu.chaos.schedule import FaultSchedule
from dotaclient_tpu.transport.tcp import BrokerServer

_log = logging.getLogger(__name__)


class BrokerIncarnations:
    """N sequential BrokerServer lives on one port, ledgers kept."""

    def __init__(
        self,
        port: int = 0,
        maxlen: int = 4096,
        shed_high: int = 0,
        shed_low: int = 0,
        priority_shed: bool = False,
    ):
        self.maxlen, self.shed_high, self.shed_low = maxlen, shed_high, shed_low
        self.priority_shed = priority_shed
        self.server = BrokerServer(
            port=port, maxlen=maxlen, shed_high=shed_high, shed_low=shed_low,
            priority_shed=priority_shed,
        ).start()
        self.port = self.server.port
        self.ledgers: List[dict] = []  # one per DEAD incarnation
        self.kill_times: List[float] = []
        self.restart_times: List[float] = []
        self._lock = threading.Lock()

    def kill(self) -> dict:
        """Stop the live server and harvest its exact ledger. The dead
        incarnation is unbound immediately so a final_ledger() landing
        before any restart (runner stopped mid-down-window, restart
        raised) can never harvest — and double-count — the same life."""
        with self._lock:
            if self.server is None:
                raise RuntimeError("kill() with no live incarnation")
            self.server.stop()
            led = self.server.ledger()
            self.server = None
            led["killed_at"] = time.monotonic()
            self.ledgers.append(led)
            self.kill_times.append(led["killed_at"])
            return led

    def restart(self) -> None:
        """Bring a fresh incarnation up on the SAME port. Bounded retry:
        the dead server's socket can linger briefly."""
        with self._lock:
            deadline = time.monotonic() + 30.0
            while True:
                try:
                    self.server = BrokerServer(
                        port=self.port,
                        maxlen=self.maxlen,
                        shed_high=self.shed_high,
                        shed_low=self.shed_low,
                        priority_shed=self.priority_shed,
                    ).start()
                    break
                except (RuntimeError, OSError):
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.1)
            self.restart_times.append(time.monotonic())

    def wait_first_enqueue(self, timeout: float = 30.0, stop: Optional[threading.Event] = None):
        """Monotonic time of the reborn incarnation's first post-boot
        enqueue (None if none landed in time) — the broker recovery
        probe: how long the fleet's jittered reconnect/backoff took to
        actually land a frame in the reborn broker. Shared by the bare
        kill path and the rolling executor."""
        with self._lock:
            server = self.server
        if server is None:
            return None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and (stop is None or not stop.is_set()):
            t = server.first_enqueue_t
            if t is not None:
                return t
            time.sleep(0.05)
        return None

    def replica_count(self) -> int:
        """One controller = one broker replica; a shard ROUTER (e.g. the
        fabric soak's round-robin over N BrokerIncarnations) reports N —
        the rolling@T:P@broker execution contract, same shape as the
        serve tier's."""
        return 1

    def final_ledger(self) -> dict:
        """Stop the last incarnation (if live) and sum every life's
        counters into one run ledger."""
        with self._lock:
            if self.server is not None:
                self.server.stop()
                led = self.server.ledger()
                led["killed_at"] = None  # run end, not a chaos kill
                self.ledgers.append(led)
                self.server = None
            total = {
                k: sum(l[k] for l in self.ledgers)
                for k in (
                    "enqueued", "popped", "dropped_oldest", "shed",
                    "shed_closes", "reply_lost", "evicted_low", "resident",
                )
            }
            total["incarnations"] = len(self.ledgers)
            return total

class ServeIncarnations:
    """Sequential in-process InferenceServer lives on ONE port — the
    serving-tier sibling of BrokerIncarnations, and the controller the
    PR-9 `kill@T:D@server` routing stub existed for.

    `make_server(port)` builds AND starts a fresh InferenceServer bound
    to `port` (0 on the first boot picks a free one; every restart
    reuses that port, so client endpoint lists stay valid across
    lives). In-process for the same reason the broker/learner
    controllers are: a real kill -9 vaporizes the counters the
    conservation proof needs, while the abandoned object still holds
    them — and stop() joins the serve loop, so each harvested ledger is
    exact. A kill abandons every in-flight episode on that replica:
    their resident carries die with the life. `carries_resident_at_kill`
    is the server-side UPPER BOUND on those abandons (a carry also
    stays resident between a client's episodes until reset/disconnect),
    which the soak reconciles against the clients' exact
    episodes_abandoned counters.

    Recovery probe: `wait_first_request()` polls the reborn server's
    `first_request_t` (the first SERVED post-restart step — the
    first_enqueue_t analog); ScheduleRunner reports it as recovery_s.
    """

    def __init__(self, make_server: Callable[[int], object], port: int = 0):
        self.make_server = make_server
        self.server = make_server(port)
        self.port = self.server.port
        self.ledgers: List[dict] = []  # one per DEAD incarnation
        self.kill_times: List[float] = []
        self.restart_times: List[float] = []
        self._lock = threading.Lock()

    @staticmethod
    def _harvest(server, chaos_kill: bool) -> dict:
        """Stop `server` and read its exact counters. The resident-carry
        count is snapshotted BEFORE stop(): the shutdown path runs the
        handlers' eviction code, which would fold the carries this kill
        stranded into the ordinary eviction counter."""
        resident = sum(len(c.carries) for c in list(server._conns))
        server.stop()
        # The controller owns the life end-to-end: make_server built a
        # fresh weights-broker client for it, so the kill closes it
        # (stop() only joins the poll thread).
        broker = getattr(server, "broker", None)
        if broker is not None:
            try:
                broker.close()
            except Exception:
                pass
        return {
            "requests": int(server.requests_total),
            "bad_requests": int(server.bad_requests_total),
            "episode_resets": int(server.episode_resets_total),
            "unknown_client": int(server.unknown_client_total),
            "evictions": int(server.evictions_total),
            "weight_swaps": int(server.weight_swaps_total),
            "version": int(server.version),
            "carries_resident_at_kill": int(resident),
            # Session-continuity counters (serve/handoff.py; zero when
            # the life ran without a carry store): the handoff soak
            # reconciles resumes against kills the same way the abandon
            # ledger reconciles them in the PR-10 soak.
            "handoff_writes": int(getattr(server, "handoff_writes_total", 0)),
            "handoff_write_errors": int(getattr(server, "handoff_write_errors_total", 0)),
            "resumes": int(getattr(server, "resumes_total", 0)),
            "resume_misses": int(getattr(server, "resume_misses_total", 0)),
            "replayed_steps": int(getattr(server, "replayed_steps_total", 0)),
            "killed_at": time.monotonic() if chaos_kill else None,
            # Per-model-slot ledgers (multi-model tier; {} on a
            # single-model server): FLAT int keys "model<m>_<what>" —
            # final_ledger's sum() folds them like any other counter.
            **ServeIncarnations._model_ledgers(server),
        }

    @staticmethod
    def _model_ledgers(server) -> dict:
        models = int(getattr(server, "models", 1))
        if models <= 1:
            return {}
        out = {}
        for m in range(models):
            out[f"model{m}_requests"] = int(server.model_requests[m])
            out[f"model{m}_evictions"] = int(server.model_evictions[m])
            out[f"model{m}_swaps"] = int(server.model_swaps[m])
        return out

    def kill(self) -> dict:
        """Stop the live incarnation and harvest its exact ledger."""
        with self._lock:
            if self.server is None:
                raise RuntimeError("kill() with no live incarnation")
            led = self._harvest(self.server, chaos_kill=True)
            self.server = None
            self.ledgers.append(led)
            self.kill_times.append(led["killed_at"])
            return led

    def restart(self) -> None:
        """Bring a fresh incarnation up on the SAME port. Bounded retry:
        the dead server's socket can linger briefly, and start() raises
        through the boot-error path when the bind fails."""
        with self._lock:
            deadline = time.monotonic() + 60.0
            while True:
                try:
                    self.server = self.make_server(self.port)
                    break
                except (RuntimeError, OSError):
                    if time.monotonic() >= deadline:
                        raise
                    time.sleep(0.2)
            self.restart_times.append(time.monotonic())

    def wait_first_request(self, timeout: float = 30.0, stop: Optional[threading.Event] = None):
        """Monotonic time of the reborn incarnation's first served step
        (None if none arrived in time) — the serve recovery probe."""
        server = self.server
        if server is None:
            return None
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and (stop is None or not stop.is_set()):
            t = server.first_request_t
            if t is not None:
                return t
            time.sleep(0.02)
        return None

    def final_ledger(self) -> dict:
        """Stop the last incarnation (if live) and sum every life."""
        with self._lock:
            if self.server is not None:
                self.ledgers.append(self._harvest(self.server, chaos_kill=False))
                self.server = None
            keys = [
                "requests", "bad_requests", "episode_resets", "unknown_client",
                "evictions", "weight_swaps", "carries_resident_at_kill",
                "handoff_writes", "handoff_write_errors", "resumes",
                "resume_misses", "replayed_steps",
            ]
            # per-model keys appear only on multi-model lives; sum each
            # across the lives that carried it (a rolling schedule can
            # mix single- and multi-model incarnations mid-migration).
            keys += sorted(
                {k for l in self.ledgers for k in l if k.startswith("model")}
            )
            total = {k: sum(l.get(k, 0) for l in self.ledgers) for k in keys}
            total["incarnations"] = len(self.ledgers)
            return total

    def replica_count(self) -> int:
        """One controller = one replica; multi-replica topologies route
        through a replica router (e.g. the soaks' round-robin router)
        that fans kill()/restart() across N of these and reports N here
        — the rolling@T:P@server execution contract."""
        return 1


class LearnerIncarnations:
    """Sequential in-process Learner lives sharing one checkpoint dir.

    `make_learner` builds (and thereby restores) a fresh Learner; the
    controller runs each life's `run()` on a daemon thread and executes
    the two death variants against it:

    - kill(sig="term"): the SIGTERM drain — request_drain(), join the
      loop (which trains out already-staged batches), drain_save() with
      wait=True. A clean exit is part of the harvested ledger.
    - kill(sig="kill"): SIGKILL emulation — abort() the loop mid-flight
      and DISCARD queued async-checkpoint/aux/mirror work; the next
      incarnation restores from whatever the periodic cadence already
      made durable (plus the publisher's version high-water file).
      Known emulation gap: a save already INSIDE its orbax commit at
      kill time completes (an in-process emulation cannot abort a
      mid-write commit, and half-killing it would corrupt the very
      directory under test) — so the restored step can be at most one
      save newer than a true kill -9 would allow. The resume soak's
      SIGKILL claims (bounded divergence, conservation, monotonic hwm)
      are restore-point-agnostic, and its part-A kill offsets are
      chosen off the checkpoint cadence so the worker is provably idle.

    Every death harvests the dead life's staging/replay counters EXACTLY
    (the in-process advantage — see module docstring), so the resume
    soak's conservation ledger can account each popped frame even for a
    life that "lost" its in-flight work.
    """

    def __init__(self, make_learner: Callable[[], object], run_kwargs: Optional[dict] = None):
        self.make_learner = make_learner
        self.run_kwargs = dict(run_kwargs or {})
        self.learner = None
        self._thread: Optional[threading.Thread] = None
        self._run_error: Optional[str] = None
        self.lives: List[dict] = []  # one ledger per DEAD incarnation
        self.boots: List[dict] = []  # one record per boot (construct/restore)
        self.kill_times: List[float] = []
        self.restart_times: List[float] = []
        self._lock = threading.Lock()

    # ---------------------------------------------------------- lifecycle

    def start(self) -> "LearnerIncarnations":
        with self._lock:
            if self.learner is not None:
                raise RuntimeError("start() with a live incarnation")
            t0 = time.monotonic()
            learner = self.make_learner()
            boot = {
                "construct_s": round(time.monotonic() - t0, 3),
                "resume_version": int(learner.version),
                "resume": learner.resume_info,
            }
            self.boots.append(boot)
            self.learner = learner
            self._run_error = None

            def _loop():
                try:
                    learner.run(**self.run_kwargs)
                except Exception as e:  # harvested into the life ledger
                    self._run_error = f"{type(e).__name__}: {e}"
                    _log.exception("learner incarnation loop died")

            self._thread = threading.Thread(target=_loop, daemon=True, name="learner-life")
            self._thread.start()
            self.restart_times.append(time.monotonic())
        return self

    def kill(self, sig: str = "kill") -> dict:
        """Execute one death; returns the harvested life ledger."""
        if sig not in ("kill", "term"):
            raise ValueError(f"unknown learner kill signal {sig!r}")
        with self._lock:
            learner = self.learner
            if learner is None:
                raise RuntimeError("kill() with no live incarnation")
            t0 = time.monotonic()
            if sig == "term":
                learner.request_drain()
            else:
                learner.abort()
            self._thread.join(timeout=120)
            joined = not self._thread.is_alive()
            if sig == "term" and joined:
                learner.drain_save()
            else:
                learner.discard_unsaved()
            s = learner.staging.stats()
            # Single atomic read: the loop thread rebinds _run_error once
            # on death; a local keeps exit_clean and loop_error coherent.
            run_error = self._run_error
            led = {
                "sig": sig,
                "exit_clean": bool(joined and run_error is None and sig == "term"),
                "loop_error": run_error,
                "death_wall_s": round(time.monotonic() - t0, 3),
                "version": int(learner.version),
                "consumed": int(s["consumed"]),
                "dropped_stale": int(s["dropped_stale"]),
                "dropped_bad": int(s["dropped_bad"]),
                "quarantined": int(s["quarantined"]),
                "rows_packed": int(s["rows_packed"]),
                "rows_replayed": int(s.get("rows_replayed", 0)),
                "replay_admitted": int(s.get("replay_admitted", 0)),
                "pending_at_death": int(s["pending_rollouts"]),
                "ready_batches_at_death": int(s["ready_batches"]),
                "reservoir_at_death": int(s.get("replay_occupancy", 0)),
                "resume_version": self.boots[-1]["resume_version"],
                "resume_pending": int(self.boots[-1]["resume"].get("resume_pending_frames", 0)),
                "resume_reservoir": int(
                    self.boots[-1]["resume"].get("resume_reservoir_entries", 0)
                ),
                "killed_at": time.monotonic(),
            }
            obs = getattr(learner, "obs", None)
            if obs is not None and obs.watchdog is not None:
                led["watchdog"] = obs.watchdog.verdict()
            learner.close()
            self.learner = None
            self._thread = None
            self.lives.append(led)
            self.kill_times.append(led["killed_at"])
            return led

    def restart(self) -> None:
        """Boot the next incarnation (restores from the shared dir)."""
        self.start()

    def wait_first_step(self, timeout: float = 30.0, stop: Optional[threading.Event] = None):
        """Monotonic time when the reborn learner's version counter first
        advanced past its resumed value (None if it never did) — the
        learner-side recovery probe."""
        learner = self.learner
        if learner is None:
            return None
        base = self.boots[-1]["resume_version"]
        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline and (stop is None or not stop.is_set()):
            if learner.version > base:
                return time.monotonic()
            time.sleep(0.02)
        return None

    def final_ledger(self) -> dict:
        """Kill any live incarnation cleanly (drain) and sum the lives."""
        with self._lock:
            live = self.learner is not None
        if live:
            self.kill(sig="term")
            self.lives[-1]["killed_at"] = None  # run end, not a chaos kill
        keys = (
            "consumed", "dropped_stale", "dropped_bad", "quarantined",
            "rows_packed", "rows_replayed", "replay_admitted",
            "pending_at_death", "ready_batches_at_death", "reservoir_at_death",
            "resume_pending", "resume_reservoir",
        )
        total = {k: sum(l[k] for l in self.lives) for k in keys}
        total["incarnations"] = len(self.lives)
        return total


class ScheduleRunner:
    """Execute a schedule's kill events on a daemon thread, relative to
    a shared epoch `t0`, routed by each event's kill-target selector:
    broker kills against a BrokerIncarnations, learner kills (SIGTERM or
    SIGKILL variant) against a LearnerIncarnations."""

    def __init__(
        self,
        schedule: FaultSchedule,
        broker: Optional[BrokerIncarnations],
        t0: float,
        learner: Optional[LearnerIncarnations] = None,
        server: Optional[object] = None,
    ):
        self.schedule = schedule
        self.broker = broker
        self.learner_inc = learner
        # kill@T:D@server routing: ServeIncarnations is the real
        # controller; any object with kill()/restart() still routes
        # (duck-typed — the recovery probe engages only when the
        # controller exposes wait_first_request).
        self.server_inc = server
        self.t0 = t0
        for ev in schedule.kills():
            if ev.target == "learner" and learner is None:
                raise ValueError("schedule kills the learner but no LearnerIncarnations given")
            if ev.target == "broker" and broker is None:
                raise ValueError("schedule kills the broker but no BrokerIncarnations given")
            if ev.target == "server" and server is None:
                raise ValueError(
                    "schedule kills the inference server but no server "
                    "controller given (supply a ServeIncarnations, or any "
                    "object with kill()/restart())"
                )
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        # (kill_index, restart_monotonic, first_enqueue_monotonic | None)
        self.recovery: List[dict] = []

    def start(self) -> "ScheduleRunner":
        self._thread = threading.Thread(target=self._run, daemon=True, name="chaos-kills")
        self._thread.start()
        return self

    def _sleep_until(self, at_s: float) -> bool:
        """Sleep to schedule-offset at_s; False if stopped first."""
        while not self._stop.is_set():
            remaining = (self.t0 + at_s) - time.monotonic()
            if remaining <= 0:
                return True
            self._stop.wait(min(remaining, 0.2))
        return False

    def _sleep_wall(self, duration_s: float) -> bool:
        """Sleep a wall-relative duration; False if stopped first. The
        rolling executor paces on wall time, not schedule offsets —
        restart/probe latencies vary and each replica's down window must
        be the configured P regardless of how long the previous
        replica's recovery took."""
        deadline = time.monotonic() + duration_s
        while not self._stop.is_set():
            remaining = deadline - time.monotonic()
            if remaining <= 0:
                return True
            self._stop.wait(min(remaining, 0.2))
        return False

    def _run_rolling(self, k: int, ev) -> bool:
        """Execute one rolling@T:P@server|broker event: kill replica i,
        hold it down P seconds, restart it, wait for its recovery probe,
        then move to replica i+1 — sequential, so at most ONE replica is
        ever down (the property the zero-abandon handoff soak and the
        fabric shard-kill soak both ride on). The controller's
        kill()/restart() rotation supplies the fan-out; a bare
        ServeIncarnations / BrokerIncarnations rolls its single
        replica. Probe: first served step for the serve tier
        (wait_first_request), first re-enqueued frame for a broker
        shard (wait_first_enqueue)."""
        inc = self.server_inc if ev.target == "server" else self.broker
        count_fn = getattr(inc, "replica_count", None)
        n = int(count_fn()) if count_fn is not None else 1
        probe = getattr(inc, "wait_first_request", None) or getattr(
            inc, "wait_first_enqueue", None
        )
        for r in range(n):
            inc.kill()
            if not self._sleep_wall(ev.duration_s):
                return False
            inc.restart()
            restarted = time.monotonic()
            # Bounded probe: with session continuity (serve) or sibling
            # shards (fabric), clients legitimately stay on the
            # survivors — a short probe keeps the roll moving and None
            # is not an error here.
            first = None
            if probe is not None:
                first = probe(timeout=1.5, stop=self._stop)
            self.recovery.append(
                {
                    "kill_index": k,
                    "target": ev.target,
                    "kind": "rolling",
                    "replica": r,
                    "at_s": ev.at_s,
                    "down_s": round(ev.duration_s, 3),
                    "recovery_s": None if first is None else round(first - restarted, 3),
                }
            )
            if self._stop.is_set():
                return False
        return True

    def _run(self) -> None:
        kills = self.schedule.kills()
        for k, ev in enumerate(kills):
            if not self._sleep_until(ev.at_s):
                return
            if ev.kind == "rolling":
                if not self._run_rolling(k, ev):
                    return
                continue
            if ev.target == "learner":
                self.learner_inc.kill(sig=ev.signal)
                if not self._sleep_until(ev.at_s + ev.duration_s):
                    return
                self.learner_inc.restart()
                restarted = time.monotonic()
                first = self.learner_inc.wait_first_step(timeout=30.0, stop=self._stop)
                self.recovery.append(
                    {
                        "kill_index": k,
                        "target": "learner",
                        "sig": ev.signal,
                        "at_s": ev.at_s,
                        "down_s": round(ev.duration_s, 3),
                        "recovery_s": None if first is None else round(first - restarted, 3),
                    }
                )
                continue
            if ev.target == "server":
                self.server_inc.kill()
                if not self._sleep_until(ev.at_s + ev.duration_s):
                    return
                self.server_inc.restart()
                restarted = time.monotonic()
                # Recovery probe = first post-restart SERVED step
                # (ServeIncarnations.wait_first_request); a bare
                # kill()/restart() object (tests) reports None. The wait
                # is bounded by the NEXT scheduled event: in a
                # multi-replica topology sticky clients stay on the
                # survivor, so a reborn replica can legitimately idle —
                # a full 30s probe would silently push every later kill
                # off its schedule.
                probe = getattr(self.server_inc, "wait_first_request", None)
                first = None
                if probe is not None:
                    budget = 30.0
                    if k + 1 < len(kills):
                        budget = max(
                            0.5,
                            min(budget, (self.t0 + kills[k + 1].at_s) - time.monotonic()),
                        )
                    first = probe(timeout=budget, stop=self._stop)
                self.recovery.append(
                    {
                        "kill_index": k,
                        "target": "server",
                        "at_s": ev.at_s,
                        "down_s": round(ev.duration_s, 3),
                        "recovery_s": None if first is None else round(first - restarted, 3),
                    }
                )
                continue
            self.broker.kill()
            if not self._sleep_until(ev.at_s + ev.duration_s):
                return
            self.broker.restart()
            restarted = time.monotonic()
            # recovery probe: poll the reborn incarnation's first-enqueue
            # stamp for up to 30s (clients are backing off with jitter)
            probe = getattr(self.broker, "wait_first_enqueue", None)
            first = probe(timeout=30.0, stop=self._stop) if probe is not None else None
            self.recovery.append(
                {
                    "kill_index": k,
                    "target": "broker",
                    "at_s": ev.at_s,
                    "down_s": round(ev.duration_s, 3),
                    "recovery_s": None if first is None else round(first - restarted, 3),
                }
            )

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
