"""ChaosEnvStub: fault-injecting wrapper around an env stub (the gRPC
dotaservice client surface: reset / observe / act).

Faults stay INSIDE the env protocol so the actor's existing degradation
paths are what gets exercised, not a new exception taxonomy:

- latency:M~J     seeded added await-sleep per RPC (slow env server);
- reset:P         observe() returns a RESOURCE_EXHAUSTED observation —
                  the session-lost signal the actor already handles by
                  abandoning the episode (runtime/actor.py run_episode).

Same (seed, spec, op-index) determinism as ChaosBroker, same schedule
grammar (corrupt/dup/shed/kill clauses are ignored here — they have no
env meaning).
"""

from __future__ import annotations

import asyncio
import threading

from dotaclient_tpu.chaos.schedule import FaultSchedule
from dotaclient_tpu.protos import dotaservice_pb2 as ds


class ChaosEnvStub:
    """Duck-types AsyncDotaServiceStub (reset/observe/act/channel)."""

    def __init__(self, inner, schedule: FaultSchedule):
        self.inner = inner
        self.schedule = schedule
        self.channel = inner.channel
        self._lock = threading.Lock()
        self._ops = 0
        self.sessions_lost = 0
        self.latency_s = 0.0

    def _next_op(self):
        with self._lock:
            i = self._ops
            self._ops += 1
        return self.schedule.decide(i)

    async def _delay(self, faults) -> None:
        if faults.latency_s > 0:
            with self._lock:
                self.latency_s += faults.latency_s
            await asyncio.sleep(faults.latency_s)

    async def reset(self, request):
        await self._delay(self._next_op())
        return await self.inner.reset(request)

    async def observe(self, request):
        f = self._next_op()
        await self._delay(f)
        if f.reset:
            with self._lock:
                self.sessions_lost += 1
            # protocol-level session loss: the actor abandons the episode
            # and starts a fresh one — graceful, no exception needed
            return ds.Observation(status=ds.Observation.RESOURCE_EXHAUSTED)
        return await self.inner.observe(request)

    async def act(self, request):
        await self._delay(self._next_op())
        return await self.inner.act(request)
