"""LIF/WIRE rules: cross-layer lifecycle + wire-spec lint for graftcheck.

Three contracts the last three PRs established live only in prose and
golden-byte tests; these rules make them lint-time mechanical:

LIF001 (error) — TransferRing lease lifecycle. A ``<ring>.acquire(...)``
binding must dispose of the slot on EVERY path: each ``raise`` reachable
after the acquire needs a preceding ``release()``, the function must
either release the lease or return it (ownership transfer to the
learner's fetch), a straight-line double ``release()`` is flagged (two
packers would then write one buffer concurrently), and — the consumer
side — a ``release()`` on a lease obtained from ``last_batch_lease``
must be preceded by an UNCONDITIONAL ``block_until_ready`` sibling
statement: jax may defer the host read of a put numpy buffer, so
releasing at put-dispatch ships the next batch's bytes to the device
(the PR-11 bug, re-introducible in one line — this rule pins it).

LIF002 (error) — drained()-station reachability, the PR-7 zero-loss
contract as a lint. In any class that defines ``drained()`` and spawns
worker threads: every ``queue.Queue`` the class constructs on ``self``
must be referenced from ``drained()``'s closure (a queue is a station
frames can occupy; an unchecked one means a SIGTERM drain can declare
victory over frames it cannot see), and every worker thread that POPS
frames (a broker ``consume_*`` call or a ``.get(...)`` on a self queue)
must, somewhere in its closure, set a ``self.<flag>`` that drained()
reads — the ``_popping``/``_packing`` in-flight-locals pattern.

WIRE001 (error) — cross-language wire-spec consistency. The DTR1/DTR3
header and dtype-map layout lives twice: ``transport/serialize.py``
(struct formats + ``_canonical_codes``) and ``native/packer.cc``
(``kHeaderBytes``/``kTraceExtBytes``/``kWire*`` + the dtype-map
validation loops). Until now that contract was enforced only by
golden-byte tests at runtime; this rule parses BOTH sides into one spec
table (python via ``ast``, C++ via structured regex over the exact
idioms the file uses) and fails on ANY drift: header/trace sizes, wire
code values, or the canonical dtype-map bytes for every
(obs f32/bf16 × aux on/off) combination.

All pure stdlib (ast/re/struct) — linting never imports the package,
numpy, or JAX (the core.py contract).
"""

from __future__ import annotations

import ast
import os
import re
import struct
from typing import Dict, List, Optional, Set, Tuple

from dotaclient_tpu.analysis.core import (
    Finding,
    ModuleUnit,
    RepoContext,
    Rule,
    register,
)
from dotaclient_tpu.analysis.thr_rules import _class_model, _self_attr

# ------------------------------------------------------------------ LIF001


def _attr_chain(node: ast.expr) -> str:
    """Dotted name of an attribute chain ('self._ring', 'staging.ring')."""
    parts: List[str] = []
    cur = node
    while isinstance(cur, ast.Attribute):
        parts.append(cur.attr)
        cur = cur.value
    if isinstance(cur, ast.Name):
        parts.append(cur.id)
    return ".".join(reversed(parts))


def _is_ring_acquire(call: ast.Call) -> bool:
    """An ``acquire`` whose receiver's TERMINAL component names a ring
    (``self._ring``, ``ring``, ``transfer_ring``). Anchored, not a
    substring match — ``self._wiring_lock.acquire(...)`` is an ordinary
    lock and must not be analyzed as a lease (error-severity false
    positives would force misleading suppressions)."""
    fn = call.func
    if not (isinstance(fn, ast.Attribute) and fn.attr == "acquire"):
        return False
    last = _attr_chain(fn.value).rsplit(".", 1)[-1].lower()
    return last in ("ring", "_ring") or last.endswith("_ring")


def _is_lease_read(value: ast.expr) -> bool:
    return isinstance(value, ast.Attribute) and value.attr == "last_batch_lease"


def _release_calls(fn: ast.AST, names: Set[str]) -> List[ast.Call]:
    out = []
    for sub in ast.walk(fn):
        if (
            isinstance(sub, ast.Call)
            and isinstance(sub.func, ast.Attribute)
            and sub.func.attr == "release"
            and isinstance(sub.func.value, ast.Name)
            and sub.func.value.id in names
        ):
            out.append(sub)
    return out


def _lease_aliases(fn: ast.AST, first: str) -> Set[str]:
    """`first` plus every simple Name later bound from an alias (the
    ``out, payload, lease = slot.batch, slot.payload, slot`` idiom)."""
    names = {first}
    changed = True
    while changed:
        changed = False
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Assign):
                continue
            targets = sub.targets[0]
            tgt_elts = targets.elts if isinstance(targets, ast.Tuple) else [targets]
            val = sub.value
            val_elts = val.elts if isinstance(val, ast.Tuple) else [val]
            if len(tgt_elts) != len(val_elts):
                continue
            for t, v in zip(tgt_elts, val_elts):
                if (
                    isinstance(t, ast.Name)
                    and isinstance(v, ast.Name)
                    and v.id in names
                    and t.id not in names
                ):
                    names.add(t.id)
                    changed = True
    return names


@register
class RingLeaseLifecycle(Rule):
    id = "LIF001"
    severity = "error"
    doc = (
        "TransferRing lease must be released or returned on every path "
        "(exception edges included); release only after the transfer retires"
    )

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            findings.extend(self._check_packer_side(module, fn))
            findings.extend(self._check_consumer_side(module, fn))
        return findings

    # -- packer side: <ring>.acquire(...) ------------------------------

    def _check_packer_side(self, module: ModuleUnit, fn: ast.AST) -> List[Finding]:
        # EVERY ring-acquire binding in the function is analyzed — a
        # second acquire (a future double-buffered packer) must not
        # slip past because the first one checked out clean.
        binds: List[Tuple[ast.Assign, str]] = []
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                if _is_ring_acquire(sub.value):
                    tgt = sub.targets[0]
                    if isinstance(tgt, ast.Name):
                        binds.append((sub, tgt.id))
        findings: List[Finding] = []
        seen_alias_sets: List[Set[str]] = []
        for bind, first in binds:
            aliases = _lease_aliases(fn, first)
            findings.extend(
                self._check_one_lease(module, fn, bind, first, aliases)
            )
            if aliases not in seen_alias_sets:
                seen_alias_sets.append(aliases)
                # straight-line double release: two release() statements
                # in one block body with no re-acquire between them
                findings.extend(
                    self._double_release(module, fn, aliases, module.qualname_at(bind))
                )
        return findings

    def _check_one_lease(
        self,
        module: ModuleUnit,
        fn: ast.AST,
        bind: ast.Assign,
        first: str,
        aliases: Set[str],
    ) -> List[Finding]:
        qual = module.qualname_at(bind)
        findings: List[Finding] = []
        releases = _release_calls(fn, aliases)
        release_lines = sorted(c.lineno for c in releases)
        returns_lease = any(
            isinstance(sub, ast.Return)
            and sub.value is not None
            and any(
                isinstance(n, ast.Name) and n.id in aliases
                for n in ast.walk(sub.value)
            )
            for sub in ast.walk(fn)
        )
        if not releases and not returns_lease:
            findings.append(
                self.make(
                    module,
                    bind.lineno,
                    f"ring slot acquired into {first!r} is never released "
                    f"nor returned — the ring leaks a slot per call and "
                    f"stalls after transfer_depth batches",
                    context=qual,
                )
            )
            return findings
        # every raise lexically after the acquire needs a preceding
        # release (or the lease was already handed off via return —
        # approximated lexically, the honest-escape-hatch contract), OR
        # an enclosing try whose FINALLY releases the lease — the
        # idiomatic cleanup shape releases on every path by construction
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Raise) or sub.lineno <= bind.lineno:
                continue
            covered = any(bind.lineno < rl <= sub.lineno for rl in release_lines)
            if not covered and self._finally_releases(module, sub, aliases):
                covered = True
            if not covered:
                findings.append(
                    self.make(
                        module,
                        sub.lineno,
                        f"raise after ring acquire leaks the slot bound to "
                        f"{first!r} — release() it on the exception edge "
                        f"(a leaked slot is gone for the process lifetime)",
                        context=qual,
                    )
                )
        return findings

    @staticmethod
    def _finally_releases(
        module: ModuleUnit, raise_stmt: ast.Raise, aliases: Set[str]
    ) -> bool:
        """True when an enclosing Try's finalbody releases the lease —
        that finally runs on the raise's exception edge, so the raise
        cannot leak the slot."""
        for anc in module.ancestors(raise_stmt):
            if isinstance(anc, ast.Try) and anc.finalbody:
                for stmt in anc.finalbody:
                    for sub in ast.walk(stmt):
                        if (
                            isinstance(sub, ast.Call)
                            and isinstance(sub.func, ast.Attribute)
                            and sub.func.attr == "release"
                            and isinstance(sub.func.value, ast.Name)
                            and sub.func.value.id in aliases
                        ):
                            return True
        return False

    def _double_release(
        self, module: ModuleUnit, fn: ast.AST, aliases: Set[str], qual: str
    ) -> List[Finding]:
        findings: List[Finding] = []
        for block_owner in ast.walk(fn):
            for body in (
                getattr(block_owner, "body", None),
                getattr(block_owner, "orelse", None),
                getattr(block_owner, "finalbody", None),
            ):
                if not isinstance(body, list):
                    continue
                seen_release = False
                for stmt in body:
                    if isinstance(stmt, ast.Expr) and isinstance(
                        stmt.value, ast.Call
                    ):
                        call = stmt.value
                        if (
                            isinstance(call.func, ast.Attribute)
                            and call.func.attr == "release"
                            and isinstance(call.func.value, ast.Name)
                            and call.func.value.id in aliases
                        ):
                            if seen_release:
                                findings.append(
                                    self.make(
                                        module,
                                        stmt.lineno,
                                        "ring slot released twice on one "
                                        "path — the free queue gains a "
                                        "duplicate and two packers write "
                                        "one buffer concurrently",
                                        context=qual,
                                    )
                                )
                            seen_release = True
                    elif isinstance(stmt, ast.Assign) and isinstance(
                        stmt.value, ast.Call
                    ):
                        if _is_ring_acquire(stmt.value):
                            seen_release = False
        return findings

    # -- consumer side: lease = <x>.last_batch_lease -------------------

    def _check_consumer_side(self, module: ModuleUnit, fn: ast.AST) -> List[Finding]:
        lease_names: Set[str] = set()
        bind_line = None
        for sub in ast.walk(fn):
            if isinstance(sub, ast.Assign) and _is_lease_read(sub.value):
                tgt = sub.targets[0]
                if isinstance(tgt, ast.Name):
                    lease_names.add(tgt.id)
                    bind_line = sub.lineno
        if not lease_names:
            return []
        findings: List[Finding] = []
        put_names = self._put_result_names(fn)
        qual = None
        for call in _release_calls(fn, lease_names):
            if qual is None:
                qual = module.qualname_at(call)
            if not self._retired_before(module, fn, call, put_names):
                findings.append(
                    self.make(
                        module,
                        call.lineno,
                        "lease from last_batch_lease released before the "
                        "device transfer retired — no unconditional "
                        "block_until_ready of THIS batch's device_put "
                        "result precedes this release(), so the slot can "
                        "be re-zeroed and repacked under an in-flight H2D "
                        "read (the PR-11 corruption; the prefetch lane "
                        "moves the release off the loop thread but never "
                        "before the retire)",
                        context=qual or module.qualname_at(fn),
                    )
                )
        _ = bind_line
        return findings

    _PUT_CALLS = ("device_put", "make_array_from_process_local_data")

    @classmethod
    def _put_result_names(cls, fn: ast.AST) -> Set[str]:
        """Names bound from a device-transfer dispatch anywhere in the
        function: ``X = jax.device_put(...)`` or any assignment whose
        value CONTAINS a device_put / make_array_from_process_local_data
        call (the ``jax.tree.map(lambda ...: make_array...(...), ...)``
        multihost idiom). These are the only objects whose
        block_until_ready proves the lease's transfer retired — fencing
        anything else (metrics, params) leaves the slot repackable under
        the in-flight read."""
        names: Set[str] = set()
        for sub in ast.walk(fn):
            if not isinstance(sub, ast.Assign):
                continue
            has_put = any(
                isinstance(n, ast.Call)
                and isinstance(n.func, ast.Attribute)
                and n.func.attr in cls._PUT_CALLS
                for n in ast.walk(sub.value)
            )
            if not has_put:
                continue
            for tgt in sub.targets:
                tgt_elts = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                for t in tgt_elts:
                    if isinstance(t, ast.Name):
                        names.add(t.id)
        return names

    @staticmethod
    def _retired_before(
        module: ModuleUnit,
        fn: ast.AST,
        release_call: ast.Call,
        put_names: Optional[Set[str]] = None,
    ) -> bool:
        """True iff an UNCONDITIONAL ``block_until_ready(...)`` sibling
        statement precedes the release in its own block or an ancestor
        block (a block_until_ready nested under some other If does not
        count — the retire fence must dominate the release), AND — when
        the function binds any device-put result names — the fence
        blocks on one of THOSE names: a block_until_ready of some other
        object (the step metrics, a param buffer) orders nothing about
        the lease's own transfer (the prefetch-lane release-site rule)."""
        # the statement that contains the release call
        stmt = release_call
        parents = module.parents
        while stmt in parents and not isinstance(stmt, ast.stmt):
            stmt = parents[stmt]
        while stmt is not None and stmt is not fn:
            parent = parents.get(stmt)
            for body in (
                getattr(parent, "body", None),
                getattr(parent, "orelse", None),
                getattr(parent, "finalbody", None),
            ):
                if isinstance(body, list) and stmt in body:
                    for before in body[: body.index(stmt)]:
                        if isinstance(before, ast.Expr) and isinstance(
                            before.value, ast.Call
                        ):
                            f = before.value.func
                            name = (
                                f.attr
                                if isinstance(f, ast.Attribute)
                                else getattr(f, "id", "")
                            )
                            if name != "block_until_ready":
                                continue
                            if not put_names:
                                return True  # no put bound here: any fence
                            fence_args = {
                                n.id
                                for a in before.value.args
                                for n in ast.walk(a)
                                if isinstance(n, ast.Name)
                            }
                            if fence_args & put_names:
                                return True
                    break
            stmt = parent
        return False


# ------------------------------------------------------------------ LIF002

_CHANNEL_FACTORIES = {"Queue": "queue", "Thread": "thread"}


@register
class DrainedStationCoverage(Rule):
    id = "LIF002"
    severity = "error"
    doc = (
        "queue/thread added to a drained()-bearing class must be visible "
        "to drained()'s station checks (the zero-loss drain contract)"
    )

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for cls in ast.walk(module.tree):
            if not isinstance(cls, ast.ClassDef):
                continue
            model = _class_model(module, cls)
            drained = model.methods.get("drained")
            if drained is None or not model.spawns_thread():
                continue
            drained_reads = self._closure_attr_reads(model, drained)
            # 1. every self.<attr> = queue.Queue(...) must be read by
            #    drained()'s closure
            for meth in model.methods.values():
                for sub in ast.walk(meth):
                    if not (
                        isinstance(sub, ast.Assign)
                        and isinstance(sub.value, ast.Call)
                    ):
                        continue
                    f = sub.value.func
                    name = (
                        f.attr if isinstance(f, ast.Attribute) else getattr(f, "id", "")
                    )
                    if name != "Queue":
                        continue
                    for tgt in sub.targets:
                        attr = _self_attr(tgt)
                        if attr is not None and attr not in drained_reads:
                            findings.append(
                                self.make(
                                    module,
                                    sub.lineno,
                                    f"queue self.{attr} is a station frames "
                                    f"can occupy, but {cls.name}.drained() "
                                    f"never checks it — a SIGTERM drain can "
                                    f"declare victory over frames it cannot "
                                    f"see (the PR-7 loss class)",
                                    context=f"{cls.name}.{model.module.qualname_at(sub).split('.')[-1]}",
                                )
                            )
            # 2. every frame-popping worker must set an in-flight flag
            #    drained() reads (the _popping/_packing pattern)
            for entry in model.worker_entries:
                closure_fns = [entry] + [
                    model.methods[n]
                    for n in model._closure([entry])
                    if n in model.methods
                ]
                if not self._pops_frames(closure_fns):
                    continue
                flags = self._flags_written(closure_fns)
                if not (flags & drained_reads):
                    name = getattr(entry, "name", "<worker>")
                    findings.append(
                        self.make(
                            module,
                            entry.lineno,
                            f"worker {cls.name}.{name} pops frames but sets "
                            f"no in-flight flag drained() reads — frames "
                            f"held in its locals are invisible to the drain "
                            f"(set a self.<flag> under the mutate lock, the "
                            f"_popping/_packing pattern)",
                            context=f"{cls.name}.{name}",
                        )
                    )
        return findings

    @staticmethod
    def _closure_attr_reads(model, drained: ast.FunctionDef) -> Set[str]:
        fns = [drained] + [
            model.methods[n] for n in model._closure([drained]) if n in model.methods
        ]
        reads: Set[str] = set()
        for fn in fns:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute):
                    attr = _self_attr(sub)
                    if attr is not None:
                        reads.add(attr)
        return reads

    @staticmethod
    def _pops_frames(fns: List[ast.AST]) -> bool:
        for fn in fns:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call) and isinstance(
                    sub.func, ast.Attribute
                ):
                    if sub.func.attr.startswith("consume_"):
                        return True
                    if sub.func.attr == "get" and isinstance(
                        sub.func.value, ast.Attribute
                    ):
                        if _self_attr(sub.func.value) is not None:
                            return True
        return False

    @staticmethod
    def _flags_written(fns: List[ast.AST]) -> Set[str]:
        flags: Set[str] = set()
        for fn in fns:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and isinstance(
                    sub.value, ast.Constant
                ):
                    if isinstance(sub.value.value, bool):
                        for tgt in sub.targets:
                            attr = _self_attr(tgt)
                            if attr is not None:
                                flags.add(attr)
        return flags


# ----------------------------------------------------------------- WIRE001


class WireSpec:
    """One side's view of the DTR wire contract."""

    def __init__(self):
        self.header_bytes: Optional[int] = None
        self.trace_ext_bytes: Optional[int] = None
        self.codes: Dict[str, int] = {}  # f32/i32/u8/bf16 → wire code
        # canonical dtype-map bytes per (obs_bf16, aux)
        self.maps: Dict[Tuple[bool, bool], bytes] = {}

    def diffs(self, other: "WireSpec") -> List[str]:
        out = []
        if self.header_bytes != other.header_bytes:
            out.append(
                f"header size {self.header_bytes} (py) vs "
                f"{other.header_bytes} (cc)"
            )
        if self.trace_ext_bytes != other.trace_ext_bytes:
            out.append(
                f"trace extension {self.trace_ext_bytes} (py) vs "
                f"{other.trace_ext_bytes} (cc)"
            )
        for k in sorted(set(self.codes) | set(other.codes)):
            if self.codes.get(k) != other.codes.get(k):
                out.append(
                    f"wire code {k}: {self.codes.get(k)} (py) vs "
                    f"{other.codes.get(k)} (cc)"
                )
        for key in sorted(set(self.maps) | set(other.maps)):
            a, b = self.maps.get(key), other.maps.get(key)
            if a != b:
                obs, aux = key
                out.append(
                    f"canonical dtype-map (obs_bf16={obs}, aux={aux}): "
                    f"{list(a) if a else a} (py) vs {list(b) if b else b} (cc)"
                )
        return out


def parse_serialize_spec(path: str) -> Tuple[Optional[WireSpec], List[str]]:
    """The python side: struct formats + wire-code constants + the
    ``_canonical_codes`` list algebra, all by AST."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    spec = WireSpec()
    errors: List[str] = []
    fmts: Dict[str, str] = {}
    code_names: Dict[str, int] = {}
    canon: Optional[ast.FunctionDef] = None
    for node in ast.walk(tree):
        if isinstance(node, ast.Assign):
            # _HDR = struct.Struct("<...>")
            if (
                isinstance(node.value, ast.Call)
                and isinstance(node.value.func, ast.Attribute)
                and node.value.func.attr == "Struct"
                and node.value.args
                and isinstance(node.value.args[0], ast.Constant)
            ):
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        fmts[tgt.id] = node.value.args[0].value
            # _WIRE_F32, _WIRE_I32, _WIRE_U8, _WIRE_BF16 = 0, 1, 2, 3
            if (
                len(node.targets) == 1
                and isinstance(node.targets[0], ast.Tuple)
                and isinstance(node.value, ast.Tuple)
            ):
                for t, v in zip(node.targets[0].elts, node.value.elts):
                    if (
                        isinstance(t, ast.Name)
                        and t.id.startswith("_WIRE_")
                        and isinstance(v, ast.Constant)
                    ):
                        code_names[t.id] = v.value
        elif isinstance(node, ast.FunctionDef) and node.name == "_canonical_codes":
            canon = node
    for want in ("_HDR", "_HDR2"):
        if want not in fmts:
            errors.append(f"{os.path.basename(path)}: no struct format {want}")
    if errors:
        return None, errors
    try:
        spec.header_bytes = struct.calcsize(fmts["_HDR"])
        spec.trace_ext_bytes = struct.calcsize(fmts["_HDR2"]) - spec.header_bytes
    except struct.error as e:
        errors.append(f"{os.path.basename(path)}: bad struct format: {e}")
        return None, errors
    for name, short in (
        ("_WIRE_F32", "f32"),
        ("_WIRE_I32", "i32"),
        ("_WIRE_U8", "u8"),
        ("_WIRE_BF16", "bf16"),
    ):
        if name in code_names:
            spec.codes[short] = code_names[name]
        else:
            errors.append(f"{os.path.basename(path)}: wire code {name} not found")
    if canon is None:
        errors.append(f"{os.path.basename(path)}: _canonical_codes not found")
        return None, errors
    segments, aux_segments = _parse_canonical_codes(canon, code_names, errors)
    # a segment symbol that is not a known _WIRE_* constant (a local
    # alias refactor, a new code) is an extraction miss, not a KeyError
    # crash — the whole-lint-run-dies failure mode is the one this
    # errors channel exists to prevent
    for sym, _count in segments + aux_segments:
        if sym != "<obs>" and sym not in code_names:
            errors.append(
                f"_canonical_codes: unknown code symbol {sym!r} (not a "
                f"_WIRE_* constant)"
            )
    if errors:
        return None, errors
    for obs_bf16 in (False, True):
        obs_code = spec.codes["bf16"] if obs_bf16 else spec.codes["f32"]
        base = []
        for sym, count in segments:
            code = obs_code if sym == "<obs>" else code_names[sym]
            base += [code] * count
        aux = list(base)
        for sym, count in aux_segments:
            code = obs_code if sym == "<obs>" else code_names[sym]
            aux += [code] * count
        spec.maps[(obs_bf16, False)] = bytes(base)
        spec.maps[(obs_bf16, True)] = bytes(aux)
    return spec, errors


def _parse_canonical_codes(
    fn: ast.FunctionDef, code_names: Dict[str, int], errors: List[str]
) -> Tuple[List[Tuple[str, int]], List[Tuple[str, int]]]:
    """Segments of the ``[code] * n + ...`` list algebra; the obs
    parameter name becomes the ``<obs>`` placeholder. Returns
    (base segments, aux-appended segments)."""
    param_names = {a.arg for a in fn.args.args}

    def segs_of(expr: ast.expr) -> List[Tuple[str, int]]:
        if isinstance(expr, ast.BinOp) and isinstance(expr.op, ast.Add):
            return segs_of(expr.left) + segs_of(expr.right)
        if (
            isinstance(expr, ast.BinOp)
            and isinstance(expr.op, ast.Mult)
            and isinstance(expr.left, ast.List)
            and len(expr.left.elts) == 1
            and isinstance(expr.right, ast.Constant)
        ):
            elt = expr.left.elts[0]
            if isinstance(elt, ast.Name):
                sym = "<obs>" if elt.id in param_names else elt.id
                return [(sym, expr.right.value)]
        errors.append("_canonical_codes: unrecognized list algebra")
        return []

    base: List[Tuple[str, int]] = []
    aux: List[Tuple[str, int]] = []
    for stmt in fn.body:
        if isinstance(stmt, ast.Assign) and isinstance(
            stmt.targets[0], ast.Name
        ):
            base = segs_of(stmt.value)
        elif isinstance(stmt, ast.If):
            for sub in stmt.body:
                if isinstance(sub, ast.AugAssign):
                    aux = segs_of(sub.value)
    if not base:
        errors.append("_canonical_codes: base map not found")
    return base, aux


_CC_CONST_RE = re.compile(
    r"\bconstexpr\s+\w+\s+(kHeaderBytes|kTraceExtBytes)\s*=\s*(\d+)\s*;"
)
_CC_CODE_RE = re.compile(r"\bkWire(F32|I32|U8|Bf16)\s*=\s*(\d+)")
_CC_NMAP_RE = re.compile(r"\bn_map\s*=\s*aux\s*\?\s*(\d+)\s*:\s*(\d+)\s*;")
_CC_OBS_HEAD_RE = re.compile(
    r"\boc\s*!=\s*kWire(\w+)\s*&&\s*oc\s*!=\s*kWire(\w+)"
)
_CC_LOOP_RE = re.compile(
    r"for\s*\(\s*\w+\s+i\s*=\s*(\d+)\s*;\s*i\s*<\s*(n_map|\d+)\s*;\s*\+\+i\s*\)\s*"
    r"if\s*\(\s*m\[i\]\s*!=\s*(oc|kWire\w+)\s*\)\s*return false;"
)


def parse_packer_spec(path: str) -> Tuple[Optional[WireSpec], List[str]]:
    """The C side: constants + the dtype-map validation loops, via
    structured regex over the exact idioms packer.cc uses (a layout
    edit that breaks the extraction is itself a finding — MIGRATION
    documents that packer.cc layout changes must keep this parseable)."""
    with open(path, encoding="utf-8") as f:
        src = f.read()
    spec = WireSpec()
    errors: List[str] = []
    for name, value in _CC_CONST_RE.findall(src):
        if name == "kHeaderBytes":
            spec.header_bytes = int(value)
        else:
            spec.trace_ext_bytes = int(value)
    if spec.header_bytes is None or spec.trace_ext_bytes is None:
        errors.append("packer.cc: kHeaderBytes/kTraceExtBytes not found")
    short = {"F32": "f32", "I32": "i32", "U8": "u8", "Bf16": "bf16"}
    for name, value in _CC_CODE_RE.findall(src):
        spec.codes[short[name]] = int(value)
    if len(spec.codes) != 4:
        errors.append(f"packer.cc: found wire codes {sorted(spec.codes)} of 4")
    n_map = _CC_NMAP_RE.search(src)
    if n_map is None:
        errors.append("packer.cc: n_map = aux ? A : B not found")
    obs_head = _CC_OBS_HEAD_RE.search(src)
    if obs_head is None:
        errors.append("packer.cc: obs-code head check (oc != kWire…) not found")
    loops = _CC_LOOP_RE.findall(re.sub(r"\s+", " ", src))
    if not loops:
        errors.append("packer.cc: dtype-map validation loops not found")
    if errors:
        return None, errors
    n_aux, n_base = int(n_map.group(1)), int(n_map.group(2))
    obs_allowed = {short.get(obs_head.group(1)), short.get(obs_head.group(2))}
    if obs_allowed != {"f32", "bf16"}:
        errors.append(
            f"packer.cc: obs head check allows {sorted(obs_allowed)}, "
            f"expected f32/bf16"
        )
        return None, errors
    for _start, _end, want in loops:
        # a validation loop comparing against a code name this table
        # does not know (a new kWireI64) is an extraction miss, never a
        # KeyError that kills the whole lint run
        if want != "oc" and want[5:] not in short:
            errors.append(f"packer.cc: unknown wire code {want} in a loop")
    if errors:
        return None, errors
    for aux, total in ((False, n_base), (True, n_aux)):
        for obs_bf16 in (False, True):
            obs_code = spec.codes["bf16"] if obs_bf16 else spec.codes["f32"]
            arr: List[Optional[int]] = [None] * total
            arr[0] = obs_code  # m[0] via the oc head check
            for start, end_s, want in loops:
                start = int(start)
                end = total if end_s == "n_map" else int(end_s)
                end = min(end, total)
                if want == "oc":
                    code = obs_code
                else:
                    code = spec.codes[short[want[5:]]]
                for i in range(start, end):
                    arr[i] = code
            if any(v is None for v in arr):
                holes = [i for i, v in enumerate(arr) if v is None]
                errors.append(
                    f"packer.cc: dtype-map entries {holes} not constrained "
                    f"by any validation loop (aux={aux})"
                )
                return None, errors
            spec.maps[(obs_bf16, aux)] = bytes(arr)  # type: ignore[arg-type]
    return spec, errors


@register
class WireSpecDrift(Rule):
    id = "WIRE001"
    severity = "error"
    doc = (
        "DTR wire layout drift between transport/serialize.py and "
        "native/packer.cc"
    )

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        ser = ctx.serialize_path or os.path.join(
            ctx.root, "dotaclient_tpu", "transport", "serialize.py"
        )
        cc = ctx.packer_cc_path or os.path.join(
            ctx.root, "dotaclient_tpu", "native", "packer.cc"
        )
        ser_ok, cc_ok = os.path.exists(ser), os.path.exists(cc)
        if not ser_ok and not cc_ok:
            # a corpus with no wire layer at all (fixture tmp trees) has
            # nothing to cross-check — the one legitimate skip
            return []
        ser_rel = os.path.relpath(ser, ctx.root).replace(os.sep, "/")
        cc_rel = os.path.relpath(cc, ctx.root).replace(os.sep, "/")
        if ser_ok != cc_ok:
            # HALF the pair present = one side was moved/renamed out from
            # under the cross-check; vanishing silently would leave wire
            # drift unchecked forever while the docs promise loudness
            missing = cc_rel if ser_ok else ser_rel
            present = ser_rel if ser_ok else cc_rel
            return [
                self.make(
                    present,
                    1,
                    f"wire-spec cross-check lost half its pair: {missing} "
                    f"is missing — if the file moved, update the WIRE001 "
                    f"default paths (analysis/lif_rules.py) so the "
                    f"serialize.py↔packer.cc drift check keeps running",
                )
            ]
        findings: List[Finding] = []
        # belt and braces: ANY unexpected source shape becomes a loud
        # extraction-failed finding, never an exception that kills the
        # whole lint run and loses every other rule's findings
        try:
            py_spec, py_errs = parse_serialize_spec(ser)
        except Exception as e:  # noqa: BLE001 — the contract is loud-not-dead
            py_spec, py_errs = None, [f"extractor crashed: {e!r}"]
        for e in py_errs:
            findings.append(
                self.make(ser_rel, 1, f"wire-spec extraction failed: {e}")
            )
        try:
            cc_spec, cc_errs = parse_packer_spec(cc)
        except Exception as e:  # noqa: BLE001
            cc_spec, cc_errs = None, [f"extractor crashed: {e!r}"]
        for e in cc_errs:
            findings.append(
                self.make(cc_rel, 1, f"wire-spec extraction failed: {e}")
            )
        if py_spec is None or cc_spec is None:
            return findings
        for diff in py_spec.diffs(cc_spec):
            findings.append(
                self.make(
                    cc_rel,
                    1,
                    f"DTR wire layout drifted between serialize.py and "
                    f"packer.cc: {diff} — one side will quarantine or "
                    f"mis-parse every frame the other emits",
                )
            )
        return findings
