"""JAX rules: static complements to the RecompileSentinel.

The runtime sentinel (obs/compute.py) proves `compute_recompiles_total
== 0` steady-state; these rules catch the patterns that break that
invariant — or silently serialize the host onto the device's critical
path — BEFORE they land.

A "jit region" is any function this module can see entering a
`jax.jit` / `shard_map` / `pmap` compilation boundary:

- decorated with ``@jax.jit`` / ``@jit`` / ``@partial(jax.jit, ...)``
  (or the shard_map/pmap equivalents);
- passed BY NAME to a jit-ish call anywhere in the same file (the
  ``step_fn`` → ``jax.jit(step_fn, ...)`` pattern in
  parallel/train_step.py, including across function scopes — matching
  is by name, deliberately, since the builder functions return the
  callable for a different scope to wrap);
- carrying an explicit ``# graftlint: jit-region`` comment on its `def`
  line (for helpers only ever CALLED from inside a jit, which no static
  name analysis can prove).

Nested defs inside a jit region are traced too and inherit the region.

JAX001 (error) — host syncs inside a jit region: ``.item()``,
``.tolist()``, ``.block_until_ready()``, ``np.asarray``/``np.array``,
``jax.device_get``, ``print``, and ``float()``/``int()``/``bool()`` on
a non-literal (on a tracer these force a blocking device transfer at
best and a ConcretizationTypeError at worst). Shape arithmetic is
exempt: an argument that only touches ``.shape``/``.ndim``/``.dtype``/
``len()``/constants is static at trace time.

JAX002 (warning) — tracer-dependent Python branch: an ``if``/``while``
whose test reads a DATA parameter of the jit region. Python control
flow on a tracer raises at trace time or — when the value sneaks in
concretely — recompiles per distinct value. Tests on shapes/dtypes,
``is None``, ``isinstance``, or declared static args are exempt.

JAX003 (warning) — unstable static args: a call to a known-jitted
function passing a list/dict/set/lambda literal in a position declared
``static_argnums``/``static_argnames`` (unhashable → TypeError;
fresh-lambda-per-call → a new cache entry per call, the unbounded-
recompile failure the sentinel counts).
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from dotaclient_tpu.analysis.core import (
    Finding,
    ModuleUnit,
    RepoContext,
    Rule,
    register,
)

_JIT_WRAPPERS = {"jit", "shard_map", "pmap"}
_HOST_SYNC_METHODS = {"item", "tolist", "block_until_ready"}
_NUMPY_ALIASES = {"np", "numpy", "onp"}
_CAST_BUILTINS = {"float", "int", "bool"}
_JIT_REGION_MARK = re.compile(r"#\s*graftlint:\s*jit-region")


def _call_name(fn: ast.expr) -> str:
    """Trailing name of a (possibly dotted) callable expression."""
    if isinstance(fn, ast.Attribute):
        return fn.attr
    if isinstance(fn, ast.Name):
        return fn.id
    return ""


def _is_jit_call(call: ast.Call) -> bool:
    return _call_name(call.func) in _JIT_WRAPPERS


def _static_decl(call: ast.Call) -> Tuple[List[int], List[str]]:
    """static_argnums/static_argnames literals from a jit call."""
    nums: List[int] = []
    names: List[str] = []
    for kw in call.keywords:
        if kw.arg == "static_argnums":
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            nums.extend(
                v.value
                for v in vals
                if isinstance(v, ast.Constant) and isinstance(v.value, int)
            )
        elif kw.arg == "static_argnames":
            vals = (
                kw.value.elts
                if isinstance(kw.value, (ast.Tuple, ast.List))
                else [kw.value]
            )
            names.extend(
                v.value
                for v in vals
                if isinstance(v, ast.Constant) and isinstance(v.value, str)
            )
    return nums, names


def _jit_index(module: ModuleUnit) -> "_JitIndex":
    """One _JitIndex per ModuleUnit, shared by all three rules (building
    it walks the whole tree — doing that 3x per file tripled lint
    wall time)."""
    cached = getattr(module, "_jit_index_cache", None)
    if cached is None:
        cached = module._jit_index_cache = _JitIndex(module)
    return cached


class _JitIndex:
    """Per-module map of jit regions and jitted-callable names."""

    def __init__(self, module: ModuleUnit):
        self.module = module
        # name → (static_argnums, static_argnames) for names wrapped by a
        # jit call; used both to mark regions and to check call sites.
        self.jitted_names: Dict[str, Tuple[List[int], List[str]]] = {}
        # assigned alias → wrapped function name (w = jax.jit(fn, ...))
        self.alias_of: Dict[str, str] = {}
        # names whose CALLS run jitted (alias targets, @jit decorators,
        # fn = jax.jit(fn) rebinds) — as opposed to raw inner functions
        # that merely got wrapped somewhere and stay callable eagerly
        self.callable_jitted: Set[str] = set()
        self.regions: List[ast.FunctionDef] = []
        self._collect()

    def _collect(self) -> None:
        for node in ast.walk(self.module.tree):
            if isinstance(node, ast.Call) and _is_jit_call(node):
                decl = _static_decl(node)
                # only the FIRST positional is the wrapped callable —
                # later positionals (shard_map's mesh, legacy jit's
                # device) must not mint jit regions for same-named
                # functions elsewhere in the file
                for arg in node.args[:1]:
                    if isinstance(arg, ast.Name):
                        self.jitted_names.setdefault(arg.id, decl)
                # x = jax.jit(fn); calls to x are calls to a jitted fn
                parent = self.module.parents.get(node)
                if isinstance(parent, ast.Assign):
                    for tgt in parent.targets:
                        if isinstance(tgt, ast.Name):
                            self.jitted_names.setdefault(tgt.id, decl)
                            self.callable_jitted.add(tgt.id)
                            if node.args and isinstance(node.args[0], ast.Name):
                                self.alias_of.setdefault(tgt.id, node.args[0].id)
        lines = self.module.source.splitlines()
        for node in ast.walk(self.module.tree):
            if not isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            is_region = node.name in self.jitted_names
            for dec in node.decorator_list:
                target = dec.func if isinstance(dec, ast.Call) else dec
                if _call_name(target) in _JIT_WRAPPERS | {"partial"}:
                    inner = (
                        dec.args[0]
                        if isinstance(dec, ast.Call)
                        and _call_name(target) == "partial"
                        and dec.args
                        else target
                    )
                    if _call_name(inner) in _JIT_WRAPPERS or _call_name(
                        target
                    ) in _JIT_WRAPPERS:
                        is_region = True
                        self.callable_jitted.add(node.name)
                        if isinstance(dec, ast.Call):
                            nums, names = _static_decl(dec)
                            self.jitted_names.setdefault(node.name, (nums, names))
            if 0 < node.lineno <= len(lines) and _JIT_REGION_MARK.search(
                lines[node.lineno - 1]
            ):
                is_region = True
            if is_region:
                self.regions.append(node)

    def static_params(self, region: ast.FunctionDef) -> Set[str]:
        nums, names = self.jitted_names.get(region.name, ([], []))
        params = [a.arg for a in region.args.args]
        out = set(names)
        for i in nums:
            if 0 <= i < len(params):
                out.add(params[i])
        return out


_STATIC_ATTRS = {"shape", "ndim", "dtype", "size", "maxlen", "itemsize"}
_MODULE_ALIASES = {"np", "numpy", "onp", "jnp", "jax", "lax", "math"}
_STATIC_BUILTINS = {
    "len",
    "isinstance",
    "hasattr",
    "getattr",
    "min",
    "max",
    "abs",
    "sum",
    "round",
    "int",
    "float",
    "bool",
    "tuple",
    "prod",
}


def _is_shapey(node: Optional[ast.AST], static_names: frozenset = frozenset()) -> bool:
    """True when EVERY leaf of the expression is static at trace time:
    constants, .shape/.ndim/.dtype reads, len()/isinstance(), module
    aliases, and names in `static_names` (locals assigned from shapey
    expressions). A mixed expression like ``loss * x.shape[0]`` is NOT
    shapey — one traced leaf poisons the whole thing."""
    if node is None:
        return True

    def rec(n: ast.AST) -> bool:
        if isinstance(n, ast.Constant):
            return True
        if isinstance(n, ast.Attribute):
            # x.shape is static whatever x is; np.float32 via the alias
            return n.attr in _STATIC_ATTRS or rec(n.value)
        if isinstance(n, ast.Name):
            return n.id in _MODULE_ALIASES or n.id in static_names
        if isinstance(n, ast.Call):
            fname = _call_name(n.func)
            if fname in _STATIC_BUILTINS:
                return all(rec(a) for a in n.args)
            if isinstance(n.func, ast.Attribute):
                # method chain on a static value: np.asarray(x.shape).prod()
                return rec(n.func) and all(rec(a) for a in n.args)
            return False
        if isinstance(n, ast.BinOp):
            return rec(n.left) and rec(n.right)
        if isinstance(n, ast.UnaryOp):
            return rec(n.operand)
        if isinstance(n, ast.BoolOp):
            return all(rec(v) for v in n.values)
        if isinstance(n, ast.Compare):
            return rec(n.left) and all(rec(c) for c in n.comparators)
        if isinstance(n, ast.Subscript):
            return rec(n.value) and rec(n.slice)
        if isinstance(n, ast.Slice):
            return all(
                rec(part)
                for part in (n.lower, n.upper, n.step)
                if part is not None
            )
        if isinstance(n, (ast.Tuple, ast.List)):
            return all(rec(e) for e in n.elts)
        if isinstance(n, ast.IfExp):
            return rec(n.test) and rec(n.body) and rec(n.orelse)
        return False

    return rec(node)


def _static_locals(region: ast.AST, seed: frozenset = frozenset()) -> frozenset:
    """Names assigned from shapey expressions inside the region, to a
    small fixpoint (rows = int(x.shape[0]); cols = rows * 2)."""
    static = set(seed)
    for _ in range(3):
        grew = False
        for sub in ast.walk(region):
            if isinstance(sub, ast.Assign) and _is_shapey(
                sub.value, frozenset(static)
            ):
                for tgt in sub.targets:
                    if isinstance(tgt, ast.Name) and tgt.id not in static:
                        static.add(tgt.id)
                        grew = True
        if not grew:
            break
    return frozenset(static)


@register
class HostSyncInJit(Rule):
    id = "JAX001"
    severity = "error"
    doc = "host sync / device_get / print inside a jit region"

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        index = _jit_index(module)
        findings: List[Finding] = []
        for region in index.regions:
            qual = module.qualname_at(region)
            statics = _static_locals(region, seed=index.static_params(region))
            for sub in ast.walk(region):
                if not isinstance(sub, ast.Call):
                    continue
                fn = sub.func
                name = _call_name(fn)
                hit = None
                if isinstance(fn, ast.Attribute):
                    if name in _HOST_SYNC_METHODS:
                        hit = f".{name}() forces a blocking device→host sync"
                    elif (
                        isinstance(fn.value, ast.Name)
                        and fn.value.id in _NUMPY_ALIASES
                        and name in ("asarray", "array")
                    ):
                        if not all(_is_shapey(a, statics) for a in sub.args):
                            hit = (
                                f"{fn.value.id}.{name}() on traced data "
                                f"materializes on the host"
                            )
                    elif name == "device_get":
                        hit = "jax.device_get() is a blocking transfer"
                elif isinstance(fn, ast.Name):
                    if name == "print":
                        hit = (
                            "print() in a jit region runs at trace time only "
                            "(silent in steady state) or forces a callback"
                        )
                    elif name in _CAST_BUILTINS and sub.args:
                        if not all(_is_shapey(a, statics) for a in sub.args):
                            hit = (
                                f"{name}() on a tracer forces concretization "
                                f"(host sync or ConcretizationTypeError)"
                            )
                if hit is not None:
                    findings.append(
                        self.make(
                            module,
                            sub.lineno,
                            f"{hit} — inside jit region {qual!r}; hoist to "
                            f"the host side or keep it in jnp",
                            context=qual,
                        )
                    )
        return findings


@register
class TracerBranch(Rule):
    id = "JAX002"
    severity = "warning"
    doc = "Python control flow on a jit-region data parameter"

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        index = _jit_index(module)
        findings: List[Finding] = []
        for region in index.regions:
            statics = index.static_params(region)
            params = {a.arg for a in region.args.args} - statics - {"self", "cfg"}
            if not params:
                continue
            qual = module.qualname_at(region)
            statics_local = _static_locals(region, seed=frozenset(statics))
            for sub in ast.walk(region):
                if not isinstance(sub, (ast.If, ast.While)):
                    continue
                test = sub.test
                if _is_shapey(test, statics_local):
                    continue
                if self._is_none_check(test):
                    continue
                used = {
                    n.id
                    for n in ast.walk(test)
                    if isinstance(n, ast.Name) and isinstance(n.ctx, ast.Load)
                }
                hot = sorted(used & params)
                if not hot:
                    continue
                kind = "if" if isinstance(sub, ast.If) else "while"
                findings.append(
                    self.make(
                        module,
                        sub.lineno,
                        f"`{kind}` on data parameter(s) {', '.join(hot)} of "
                        f"jit region {qual!r} — a tracer here raises at "
                        f"trace time or recompiles per value; use lax.cond/"
                        f"lax.select, or declare the arg static",
                        context=qual,
                    )
                )
        return findings

    @staticmethod
    def _is_none_check(test: ast.AST) -> bool:
        return isinstance(test, ast.Compare) and any(
            isinstance(op, (ast.Is, ast.IsNot)) for op in test.ops
        )


@register
class UnstableStaticArg(Rule):
    id = "JAX003"
    severity = "warning"
    doc = "unhashable/unstable literal passed in a static jit arg position"

    _BAD = (ast.List, ast.Dict, ast.Set, ast.ListComp, ast.DictComp, ast.Lambda)

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        index = _jit_index(module)
        findings: List[Finding] = []
        regions_by_name = {r.name: r for r in index.regions}
        for sub in ast.walk(module.tree):
            if not isinstance(sub, ast.Call):
                continue
            name = _call_name(sub.func)
            decl = index.jitted_names.get(name)
            if decl is None or _call_name(sub.func) in _JIT_WRAPPERS:
                continue
            # the raw inner fn of `jfn = jax.jit(fn, ...)` stays callable
            # eagerly (tests/debugging) — a direct call never enters jit,
            # so static-arg hygiene does not apply to it
            if name not in index.callable_jitted:
                continue
            nums, names = decl
            if not nums and not names:
                continue
            qual = module.qualname_at(sub)
            region = regions_by_name.get(name) or regions_by_name.get(
                index.alias_of.get(name, "")
            )
            params = [a.arg for a in region.args.args] if region is not None else []
            for i, arg in enumerate(sub.args):
                static = i in nums or (i < len(params) and params[i] in names)
                if static and isinstance(arg, self._BAD):
                    findings.append(self._finding(module, arg, name, qual))
            for kw in sub.keywords:
                if kw.arg in names and isinstance(kw.value, self._BAD):
                    findings.append(self._finding(module, kw.value, name, qual))
        return findings

    def _finding(self, module: ModuleUnit, arg: ast.AST, name: str, qual: str):
        what = type(arg).__name__.lower()
        return self.make(
            module,
            arg.lineno,
            f"{what} literal passed in a static arg position of jitted "
            f"{name!r} — unhashable statics TypeError; a fresh lambda/"
            f"container per call is a new cache entry per call (unbounded "
            f"recompiles); pass a module-level tuple/function instead",
            context=qual,
        )
