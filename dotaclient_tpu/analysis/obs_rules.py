"""OBS rules: static drift guards for the observability + config
contracts.

The runtime drift guard (tests/test_obs.py::test_emitted_scalars_are_
registered) catches an unregistered scalar only when a real learner
window emits it; the k8s manifests are not executed by any test at all.
These rules close both gaps at lint time:

OBS001 (error) — every scalar name passed STRING-LITERALLY to
``MetricsLogger.log`` (dict-literal keys, and ``scalars["name"] = ...``
subscript stores on the dict variable later passed to ``.log``) must
exist in ``obs/registry.py`` (SCALARS exact names or PREFIXES
families). Dynamic keys (f-strings, loop variables) are the runtime
guard's job and are skipped here.

OBS002 (error) — every ``--flag`` referenced in ``k8s/*.yaml`` must
exist in the flag namespace of the binary that manifest runs
(``config.py`` dataclass fields flattened the way ``add_flags`` does,
or the broker's argparse). The binary is identified from the
manifest's ``-m dotaclient_tpu...`` command line, and flags are scoped
to the enclosing yaml sequence item that mentions it (the container
block) — a sidecar container's own ``--config``-style flags in the
same manifest are some other program's namespace, not drift. Comment
lines are ignored.

The same rule also covers the ``scripts/`` bench/soak drivers
(graftcheck PR): any LIST LITERAL in a ``scripts/*.py`` file that
names a known ``dotaclient_tpu.<x>`` binary (the subprocess-argv
idiom, ``[sys.executable, "-m", "dotaclient_tpu.serve.server",
"--serve.port", ...]``) has its ``"--flag"`` string elements checked
against that binary's namespace. Scoping to the list literal keeps a
script's OWN argparse flags (self-reinvocation argv with no module
string) and prose mentions out of scope; flag lists composed in a
helper function and concatenated in (``+ _policy_flags(...)``) are a
known blind spot — the k8s manifests remain the deploy-surface source
of truth.

OBS003 (warning) — every leaf config field defined in ``config.py``
must be READ somewhere in the package (an ``.name`` attribute load
outside config.py). A defined-but-never-consumed flag is a lie in the
deploy surface: operators set it and nothing changes. Matching is by
attribute name, deliberately loose — a false "consumed" beats noisy
false positives; the satellite audit is the place to be strict.

Everything is AST/regex over source — no imports, no yaml dependency.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, Optional, Set, Tuple

from dotaclient_tpu.analysis.core import (
    Finding,
    ModuleUnit,
    RepoContext,
    Rule,
    register,
)

_FLAG_RE = re.compile(r"--([A-Za-z0-9_][A-Za-z0-9_.]*)")
_MODULE_RE = re.compile(r"dotaclient_tpu(?:\.[a-z_0-9]+)+")
_ITEM_RE = re.compile(r"^(\s*)-(\s|$)")


def _item_blocks(stripped: List[str]) -> List[Tuple[int, int, int]]:
    """(start, end, dash-indent) 0-based inclusive line ranges of every
    yaml sequence item (``- ...``). An item ends before the first
    non-blank line indented at or left of its dash — enough structure to
    scope a container block without a yaml dependency."""
    blocks: List[Tuple[int, int, int]] = []
    for i, ln in enumerate(stripped):
        m = _ITEM_RE.match(ln)
        if not m:
            continue
        indent = len(m.group(1))
        end = len(stripped) - 1
        for j in range(i + 1, len(stripped)):
            nxt = stripped[j]
            if not nxt.strip():
                continue
            if len(nxt) - len(nxt.lstrip(" ")) <= indent:
                end = j - 1
                break
        blocks.append((i, end, indent))
    return blocks

# manifest binary → root config dataclass in config.py ("argparse:<path>"
# = stdlib argparse binaries, flags parsed from their add_argument calls)
_BINARY_CONFIGS = {
    "dotaclient_tpu.runtime.learner": "LearnerConfig",
    "dotaclient_tpu.runtime.actor": "ActorConfig",
    "dotaclient_tpu.runtime.selfplay": "ActorConfig",
    "dotaclient_tpu.eval.evaluator": "EvalConfig",
    "dotaclient_tpu.serve.server": "InferenceConfig",
    "dotaclient_tpu.serve.handoff": "HandoffConfig",
    "dotaclient_tpu.control.server": "ControlConfig",
    "dotaclient_tpu.obs.fleetd": "FleetConfig",
    "dotaclient_tpu.league.server": "LeagueConfig",
    "dotaclient_tpu.transport.tcp_server": "argparse:transport/tcp_server.py",
    "dotaclient_tpu.transport.fabric": "argparse:transport/fabric.py",
}


def _registry_names(ctx: RepoContext) -> Tuple[Set[str], Set[str]]:
    """parse_registry_names, once per lint run (OBS001 runs per module;
    re-parsing the registry per file is pure waste)."""
    cached = getattr(ctx, "_registry_names_cache", None)
    if cached is None:
        cached = ctx._registry_names_cache = parse_registry_names(
            ctx.registry_path, tree=ctx.ast_of(ctx.registry_path)
        )
    return cached


def parse_registry_names(
    registry_path: str, tree: Optional[ast.Module] = None
) -> Tuple[Set[str], Set[str]]:
    """(exact scalar names, family prefixes) from obs/registry.py — by
    AST, so linting never imports the package. Pass `tree` (the
    RepoContext.ast_of cache) to skip the re-parse."""
    if tree is None:
        with open(registry_path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=registry_path)
    scalars: Set[str] = set()
    prefixes: Set[str] = set()
    for node in ast.walk(tree):
        if not isinstance(node, (ast.Assign, ast.AnnAssign)):
            continue
        targets = node.targets if isinstance(node, ast.Assign) else [node.target]
        value = node.value
        if not isinstance(value, ast.Dict):
            continue
        for tgt in targets:
            name = getattr(tgt, "id", "")
            bucket = {"SCALARS": scalars, "PREFIXES": prefixes}.get(name)
            if bucket is None:
                continue
            for key in value.keys:
                if isinstance(key, ast.Constant) and isinstance(key.value, str):
                    bucket.add(key.value)
    return scalars, prefixes


def _registered(name: str, scalars: Set[str], prefixes: Set[str]) -> bool:
    if name in scalars or name in ("step", "time"):
        return True
    return any(name.startswith(p) for p in prefixes)


def _head_registered(head: str, scalars: Set[str], prefixes: Set[str]) -> bool:
    """Can a dynamically-composed name starting with `head` still land
    inside the registry? True when the head sits inside a PREFIXES
    family (``fleet_ledger_`` under ``fleet_``), when a family starts
    with the head (``staging_`` composing into ``staging_pack_*``), or
    when an exact scalar starts with it (``ckpt_`` + a stats key =
    ``ckpt_save_ms``). Only a head that can NEVER reach a registered
    name is drift — this keeps the check sound without re-deriving
    every runtime tail."""
    if not head:
        return True  # f"{var}..." — nothing static to judge
    if any(head.startswith(p) or p.startswith(head) for p in prefixes):
        return True
    return any(s.startswith(head) for s in scalars)


def _key_violation(
    key: ast.AST, scalars: Set[str], prefixes: Set[str]
) -> Optional[Tuple[str, bool]]:
    """(display name, is_dynamic) when `key` names an unregistered
    scalar; None when registered or out of scope. Constant string keys
    are judged exactly; f-string keys by their constant head (the
    dynamically-composed family blind spot OBS001 used to document
    instead of checking). Keys with no static head stay the runtime
    drift guard's job."""
    if isinstance(key, ast.Constant) and isinstance(key.value, str):
        if not _registered(key.value, scalars, prefixes):
            return key.value, False
        return None
    if isinstance(key, ast.JoinedStr) and key.values:
        first = key.values[0]
        if isinstance(first, ast.Constant) and isinstance(first.value, str):
            if not _head_registered(first.value, scalars, prefixes):
                return first.value + "…", True
    return None


@register
class UnregisteredScalar(Rule):
    id = "OBS001"
    severity = "error"
    doc = "scalar name logged to MetricsLogger but absent from obs/registry.py"

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        if ctx.registry_path is None or not os.path.exists(ctx.registry_path):
            return []
        # the registry documents itself; MetricsLogger's own module holds
        # the logger, not emitters
        if module.relpath.endswith("obs/registry.py"):
            return []
        scalars, prefixes = _registry_names(ctx)
        findings: List[Finding] = []
        for fn in ast.walk(module.tree):
            if not isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef)):
                continue
            log_dict_vars: Set[str] = set()
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Call):
                    continue
                f = sub.func
                if not (isinstance(f, ast.Attribute) and f.attr == "log"):
                    continue
                if not self._is_metrics_receiver(f.value, fn, module):
                    continue
                if len(sub.args) < 2:
                    continue
                payload = sub.args[1]
                if isinstance(payload, ast.Dict):
                    for key in payload.keys:
                        bad = _key_violation(key, scalars, prefixes)
                        if bad:
                            findings.append(
                                self._finding(module, key, bad[0], fn, bad[1])
                            )
                elif isinstance(payload, ast.Name):
                    log_dict_vars.add(payload.id)
            if not log_dict_vars:
                continue
            for sub in ast.walk(fn):
                if not isinstance(sub, ast.Assign):
                    continue
                for tgt in sub.targets:
                    if (
                        isinstance(tgt, ast.Subscript)
                        and isinstance(tgt.value, ast.Name)
                        and tgt.value.id in log_dict_vars
                    ):
                        bad = _key_violation(tgt.slice, scalars, prefixes)
                        if bad:
                            findings.append(
                                self._finding(module, tgt, bad[0], fn, bad[1])
                            )
                    elif (
                        isinstance(tgt, ast.Name)
                        and tgt.id in log_dict_vars
                        and isinstance(sub.value, ast.Dict)
                    ):
                        # the dict-LITERAL initializer of the logged
                        # var: `scalars = {"name": ...}` then
                        # `metrics.log(step, scalars)`
                        for key in sub.value.keys:
                            bad = _key_violation(key, scalars, prefixes)
                            if bad:
                                findings.append(
                                    self._finding(module, key, bad[0], fn, bad[1])
                                )
        return findings

    @staticmethod
    def _is_metrics_receiver(recv: ast.expr, fn: ast.AST, module: ModuleUnit) -> bool:
        # self.metrics.log / metrics.log / <var bound to MetricsLogger()>
        if isinstance(recv, ast.Attribute) and recv.attr == "metrics":
            return True
        if isinstance(recv, ast.Name):
            if recv.id == "metrics":
                return True
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign) and isinstance(sub.value, ast.Call):
                    callee = sub.value.func
                    callee_name = (
                        callee.attr
                        if isinstance(callee, ast.Attribute)
                        else getattr(callee, "id", "")
                    )
                    if callee_name == "MetricsLogger" and any(
                        isinstance(t, ast.Name) and t.id == recv.id
                        for t in sub.targets
                    ):
                        return True
        return False

    def _finding(
        self, module: ModuleUnit, node: ast.AST, name: str, fn, dynamic: bool = False
    ) -> Finding:
        qual = module.qualname_at(node)
        if dynamic:
            msg = (
                f"dynamically-composed scalar head {name!r} is logged here "
                f"but no obs/registry.py PREFIXES family (or SCALARS name) "
                f"can contain it — dashboards select by name; register a "
                f"family for the head or rename"
            )
        else:
            msg = (
                f"scalar {name!r} is logged here but not registered in "
                f"obs/registry.py — dashboards select by name; add it to "
                f"SCALARS (or a documented PREFIXES family) or rename"
            )
        return self.make(module, node.lineno, msg, context=qual)


def config_field_map(
    config_path: str, tree: Optional[ast.Module] = None
) -> Dict[str, Dict[str, Optional[str]]]:
    """{ClassName: {field: nested-ClassName-or-None}} for every
    @dataclass in config.py, resolved the way add_flags recurses.
    Pass `tree` (the RepoContext.ast_of cache) to skip the re-parse."""
    if tree is None:
        with open(config_path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=config_path)
    classes: Dict[str, Dict[str, Optional[str]]] = {}
    names = {
        n.name for n in ast.walk(tree) if isinstance(n, ast.ClassDef)
    }
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        fields: Dict[str, Optional[str]] = {}
        for stmt in node.body:
            if not (isinstance(stmt, ast.AnnAssign) and isinstance(stmt.target, ast.Name)):
                continue
            ann = stmt.annotation
            ann_name = getattr(ann, "id", getattr(ann, "attr", ""))
            fields[stmt.target.id] = ann_name if ann_name in names else None
        classes[node.name] = fields
    return classes


def flatten_flags(
    classes: Dict[str, Dict[str, Optional[str]]], root: str, prefix: str = ""
) -> Set[str]:
    out: Set[str] = set()
    for fname, nested in classes.get(root, {}).items():
        dotted = f"{prefix}{fname}"
        if nested is None:
            out.add(dotted)
        else:
            out |= flatten_flags(classes, nested, prefix=f"{dotted}.")
    return out


def argparse_flags(path: str, tree: Optional[ast.Module] = None) -> Set[str]:
    """--flag names from add_argument calls in a stdlib-argparse binary."""
    if tree is None:
        with open(path, encoding="utf-8") as f:
            tree = ast.parse(f.read(), filename=path)
    out: Set[str] = set()
    for node in ast.walk(tree):
        if (
            isinstance(node, ast.Call)
            and isinstance(node.func, ast.Attribute)
            and node.func.attr == "add_argument"
        ):
            for arg in node.args:
                if (
                    isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("--")
                ):
                    out.add(arg.value[2:])
    return out


@register
class ManifestFlagDrift(Rule):
    id = "OBS002"
    severity = "error"
    doc = "--flag in a k8s manifest that no binary defines"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        if not (ctx.config_path and os.path.exists(ctx.config_path)):
            return []
        classes = config_field_map(ctx.config_path, tree=ctx.ast_of(ctx.config_path))
        findings: List[Finding] = self._scripts_pass(ctx, classes)
        if not (ctx.k8s_dir and os.path.isdir(ctx.k8s_dir)):
            return findings
        for name in sorted(os.listdir(ctx.k8s_dir)):
            if not (name.endswith(".yaml") or name.endswith(".yml")):
                continue
            path = os.path.join(ctx.k8s_dir, name)
            rel = os.path.relpath(path, ctx.root).replace(os.sep, "/")
            with open(path, encoding="utf-8") as f:
                lines = f.read().splitlines()
            stripped = [ln.split("#", 1)[0] for ln in lines]
            if not any(_BINARY_CONFIGS.get(m) for ln in stripped for m in _MODULE_RE.findall(ln)):
                continue  # manifest runs no binary we know (rabbitmq image)
            # A flag is judged against the namespace of the NEAREST
            # enclosing yaml sequence item that mentions a known binary
            # (the `-m dotaclient_tpu...` container block, for args and
            # env nested inside it). Flags with no such enclosing item —
            # a sidecar container's own --config, an annotation — belong
            # to some other program and are none of this rule's business.
            blocks = _item_blocks(stripped)
            resolved: Dict[int, Tuple[Set[str], Set[str]]] = {}
            for bi, (b_start, b_end, _indent) in enumerate(blocks):
                mods = set()
                for ln in stripped[b_start : b_end + 1]:
                    mods.update(_MODULE_RE.findall(ln))
                namespaces, known = self._namespaces(ctx, classes, mods)
                if known:
                    resolved[bi] = (namespaces, known)
            for lineno, ln in enumerate(stripped, start=1):
                flags = _FLAG_RE.findall(ln)
                if not flags:
                    continue
                enclosing = [
                    bi
                    for bi, (b_start, b_end, _indent) in enumerate(blocks)
                    if b_start <= lineno - 1 <= b_end and bi in resolved
                ]
                if not enclosing:
                    continue
                # innermost wins: blocks are emitted in document order,
                # so the last enclosing one starts deepest
                namespaces, known = resolved[enclosing[-1]]
                for flag in flags:
                    if flag not in namespaces:
                        findings.append(
                            self.make(
                                rel,
                                lineno,
                                f"--{flag} is not a flag of "
                                f"{'/'.join(sorted(known))} (config.py "
                                f"defines no such field) — the binary will "
                                f"refuse to start; fix the manifest or add "
                                f"the field",
                            )
                        )
        return findings

    def _scripts_pass(self, ctx: RepoContext, classes) -> List[Finding]:
        """The scripts/ half of OBS002: check subprocess-argv list
        literals in bench/soak drivers against the spawned binary's flag
        namespace. Only lists that NAME a known binary are judged — a
        script's own argparse flags (self-reinvocation lists) never
        mention a module and stay out of scope."""
        findings: List[Finding] = []
        for script in ctx.script_modules():
            rel = script.relpath
            for lst in ast.walk(script.tree):
                if not isinstance(lst, ast.List):
                    continue
                strs = [
                    e
                    for e in lst.elts
                    if isinstance(e, ast.Constant) and isinstance(e.value, str)
                ]
                mods: Set[str] = set()
                for e in strs:
                    mods.update(_MODULE_RE.findall(e.value))
                namespaces, known = self._namespaces(ctx, classes, mods)
                if not known:
                    continue
                for e in strs:
                    if not e.value.startswith("--"):
                        continue
                    flag = e.value[2:].split("=", 1)[0]
                    if flag and flag not in namespaces:
                        findings.append(
                            self.make(
                                rel,
                                e.lineno,
                                f"--{flag} is not a flag of "
                                f"{'/'.join(sorted(known))} (config.py "
                                f"defines no such field) — the spawned "
                                f"binary will refuse to start; fix the "
                                f"driver or add the field",
                            )
                        )
        return findings

    @staticmethod
    def _namespaces(
        ctx: RepoContext, classes, modules: Set[str]
    ) -> Tuple[Set[str], Set[str]]:
        """(flag namespace union, known modules) for a module set."""
        namespaces: Set[str] = set()
        known: Set[str] = set()
        for mod in sorted(modules):
            spec = _BINARY_CONFIGS.get(mod)
            if spec is None:
                continue
            known.add(mod)
            if spec.startswith("argparse:"):
                ap = os.path.join(
                    os.path.dirname(ctx.config_path), *spec.split(":", 1)[1].split("/")
                )
                if os.path.exists(ap):
                    namespaces |= argparse_flags(ap, tree=ctx.ast_of(ap))
            else:
                namespaces |= flatten_flags(classes, spec)
        return namespaces, known


@register
class UnconsumedFlag(Rule):
    id = "OBS003"
    severity = "warning"
    doc = "config field defined but never read anywhere in the package"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        if ctx.config_path is None or not os.path.exists(ctx.config_path):
            return []
        config_rel = os.path.relpath(ctx.config_path, ctx.root).replace(os.sep, "/")
        consumed: Set[str] = set()
        for module in ctx.modules:
            if module.relpath == config_rel:
                continue
            for sub in ast.walk(module.tree):
                if isinstance(sub, ast.Attribute):
                    consumed.add(sub.attr)
                elif (
                    isinstance(sub, ast.Call)
                    and isinstance(sub.func, ast.Name)
                    and sub.func.id == "getattr"
                    and len(sub.args) >= 2
                    and isinstance(sub.args[1], ast.Constant)
                    and isinstance(sub.args[1].value, str)
                ):
                    # getattr(cfg, "field", default) — the compat-read idiom
                    consumed.add(sub.args[1].value)
        tree = ctx.ast_of(ctx.config_path)
        if tree is None:
            return []
        classes = config_field_map(ctx.config_path, tree=tree)
        findings: List[Finding] = []
        for node in ast.walk(tree):
            if not isinstance(node, ast.ClassDef) or node.name not in classes:
                continue
            for stmt in node.body:
                if not (
                    isinstance(stmt, ast.AnnAssign)
                    and isinstance(stmt.target, ast.Name)
                ):
                    continue
                fname = stmt.target.id
                if classes[node.name].get(fname) is not None:
                    continue  # nested config containers are structural
                if fname not in consumed:
                    findings.append(
                        self.make(
                            config_rel,
                            stmt.lineno,
                            f"{node.name}.{fname} is defined (and exposed as "
                            f"a --flag) but never read anywhere in the "
                            f"package — wire it or remove it",
                            context=node.name,
                        )
                    )
        return findings
