"""Runtime lock instrumentation: the dynamic half of the THR rules.

THR002 sees lexically nested ``with self.lock`` pairs; it cannot see an
order established ACROSS objects (staging's stats lock taken while a
reservoir method takes its own) or through callbacks. This module
instruments ``threading.Lock`` at test time and records what actually
happened:

- **lock-order inversions** — per-thread stack of currently held
  instrumented locks; acquiring B while holding A records the directed
  edge A→B (keyed by each lock's CREATION SITE, so every
  ``StagingBuffer._stats_lock`` is one node regardless of instance
  count). A later acquisition establishing B→A is an inversion: two
  threads interleaving those paths deadlock.
- **over-held locks** — a hold longer than ``hold_threshold_s`` is
  recorded; the repo's locks exist to make SNAPSHOTS atomic, so a long
  hold means I/O or compute crept under a lock that scrape/hot-path
  threads contend on (the Watchdog "escalation I/O outside the lock"
  review finding, as a harness check).

Scope discipline keeps this safe and cheap: ``install()`` patches
``threading.Lock``/``RLock``/``Condition``, but the factories only
instrument locks whose creation frame lives inside this repo — stdlib
``queue.Queue``, logging, and JAX internals keep native locks. A bare
``threading.Condition()`` from repo code gets an instrumented backing
RLock attributed to the Condition call site (its default RLock would
otherwise be created inside threading.py and escape the scope filter).
The wrapper implements the Condition wait protocol itself
(``_release_save``/``_acquire_restore``/``_is_owned``), so a
``cond.wait()`` pauses the hold clock — waiting is not holding — and
reacquisition re-enters order tracking.

Production never imports this module; tests opt in via the ``lockcheck``
fixture (tests/conftest.py), which installs, yields the monitor, and
uninstalls — assertions on ``monitor.inversions`` / ``monitor.over_held``
belong to the test.
"""

from __future__ import annotations

import os
import threading
import time
import weakref
from typing import Dict, List, Optional, Tuple

from dotaclient_tpu.analysis.core import bfs_path

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# Captured at import time, before any install() can patch threading:
# the monitor's own state lock must NEVER be instrumented (an
# instrumented state lock would re-enter on_acquired → self-deadlock),
# and uninstall() must restore exactly this factory.
_NATIVE_LOCK = threading.Lock
_NATIVE_RLOCK = threading.RLock
_NATIVE_CONDITION = threading.Condition


def _thread_name(ident: Optional[int] = None) -> str:
    """Name of the thread with `ident` (default: current) WITHOUT
    threading.current_thread(): for an unregistered thread
    (mid-bootstrap, or foreign) current_thread() constructs a
    _DummyThread, whose __init__ creates an Event — under
    scope_root=None that Event's Condition is itself instrumented, and
    acquiring it re-enters on_acquired → unbounded recursion."""
    if ident is None:
        ident = threading.get_ident()
    t = getattr(threading, "_active", {}).get(ident)
    return t.name if t is not None else f"thread-{ident}"


class LockMonitor:
    """Registry + detector state shared by every instrumented lock."""

    def __init__(
        self, hold_threshold_s: float = 0.2, scope_root: Optional[str] = _REPO_ROOT
    ):
        self.hold_threshold_s = hold_threshold_s
        # Only instrument locks created under this path (default: the
        # repo checkout). Pass None to instrument everything (fixture
        # corpus tests use tmp paths).
        self.scope_root = scope_root
        # thread ident → stack of currently held instrumented locks,
        # guarded by _state_lock. Monitor-global (not threading.local):
        # threading.Lock legally allows acquire-in-A/release-in-B
        # handoff, and the releasing thread must be able to strip the
        # entry from the ACQUIRING thread's stack — a thread-local stack
        # would keep a phantom there forever, minting false order edges.
        self._held: Dict[int, List["InstrumentedLock"]] = {}
        # site-pair → (thread name, where the second acquire happened)
        self._edges: Dict[Tuple[str, str], Tuple[str, str]] = {}
        # adjacency mirror of _edges for the cycle search
        self._adj: Dict[str, List[str]] = {}
        self._state_lock = _NATIVE_LOCK()  # guards edges + reports
        self.inversions: List[Dict] = []
        # cycles already reported, keyed by their site set — a hot loop
        # re-nesting a known-inverted pair must not mint one report per
        # iteration (the soak asserts on inversions; a real inversion
        # would otherwise bury its one distinct cycle in thousands of
        # duplicates)
        self._reported_cycles: set = set()
        self.over_held: List[Dict] = []
        self.acquisitions = 0
        self._installed: Optional[Tuple] = None
        # every InstrumentedLock this monitor minted — uninstall() makes
        # them inert. Locks created during a test can outlive it in
        # module/registry state (a broker hub, a cached transport); left
        # live they would keep paying bookkeeping into a dead monitor
        # (over_held growing unboundedly) for the rest of the process.
        self._made: "weakref.WeakSet[InstrumentedLock]" = weakref.WeakSet()

    # ------------------------------------------------------------ factory

    def _creation_site(self) -> Optional[str]:
        """file:line of the frame that called Lock(), skipping ourselves;
        None when out of scope (→ hand back a native lock)."""
        import sys

        frame = sys._getframe(2)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return None
        path = frame.f_code.co_filename
        if self.scope_root is not None:
            # separator-anchored: /repo must not claim /repo-backup/...
            root = self.scope_root.rstrip(os.sep)
            if path != root and not path.startswith(root + os.sep):
                return None
            # a venv installed INSIDE the checkout is not repo code —
            # JAX/numpy locks from repo/.venv/.../site-packages must
            # stay native per the module contract
            if "site-packages" in path.split(os.sep):
                return None
        rel = os.path.relpath(path, self.scope_root) if self.scope_root else path
        return f"{rel}:{frame.f_lineno}"

    def make_lock(self):
        site = self._creation_site()
        if site is None:
            return _NATIVE_LOCK()
        return self._mint(InstrumentedLock(self, _NATIVE_LOCK(), site))

    def make_rlock(self):
        site = self._creation_site()
        if site is None:
            return _NATIVE_RLOCK()
        return self._mint(InstrumentedLock(self, _NATIVE_RLOCK(), site, reentrant=True))

    def _mint(self, lock: "InstrumentedLock") -> "InstrumentedLock":
        self._made.add(lock)
        return lock

    def make_condition(self, lock=None):
        """Condition() with NO lock creates its RLock inside threading.py
        — out of scope for the Lock factory, which would leave every
        default-lock Condition (WeightPublisher._cond, the checkpoint
        mirror) invisible to the monitor. Build the backing RLock HERE,
        attributed to the Condition() call site."""
        if lock is None:
            site = self._creation_site()
            if site is not None:
                lock = self._mint(InstrumentedLock(self, _NATIVE_RLOCK(), site, reentrant=True))
        return _NATIVE_CONDITION(lock) if lock is not None else _NATIVE_CONDITION()

    def install(self) -> "LockMonitor":
        """Patch threading.Lock/RLock/Condition with the scoped factory;
        uninstall restores the import-time natives exactly (idempotent
        both ways, and a nested install of a second monitor is refused —
        two monitors patching over each other would corrupt both
        graphs)."""
        if self._installed is not None:
            return self
        if threading.Lock is not _NATIVE_LOCK:
            raise RuntimeError("another LockMonitor is already installed")
        self._installed = (_NATIVE_LOCK, _NATIVE_RLOCK, _NATIVE_CONDITION)
        threading.Lock = self.make_lock  # type: ignore[assignment]
        threading.RLock = self.make_rlock  # type: ignore[assignment]
        threading.Condition = self.make_condition  # type: ignore[assignment]
        return self

    def uninstall(self) -> None:
        if self._installed is None:
            return
        threading.Lock, threading.RLock, threading.Condition = self._installed  # type: ignore[assignment]
        self._installed = None
        # Inert every lock we minted: locks that outlive the monitor in
        # module/registry state must stop feeding a dead graph (the
        # wrapped native keeps working — only the bookkeeping stops).
        # Under _state_lock: a thread that outlived its test can be
        # inside on_acquired/on_released right now, indexing the very
        # _holders list this clears.
        with self._state_lock:
            for lk in list(self._made):
                lk._monitor = None
                lk._holders.clear()

    def __enter__(self) -> "LockMonitor":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    # ---------------------------------------------------------- callbacks

    def on_acquired(self, lock: "InstrumentedLock") -> None:
        now = time.monotonic()
        tname = _thread_name()
        ident = threading.get_ident()
        with self._state_lock:
            held = self._held.setdefault(ident, [])
            self.acquisitions += 1
            for outer in held:
                if outer.site == lock.site:
                    continue
                edge = (outer.site, lock.site)
                if edge not in self._edges:
                    self._edges[edge] = (tname, lock.site)
                    self._adj.setdefault(outer.site, []).append(lock.site)
                # general cycle, not just the reversed pair: taking
                # outer→lock here deadlocks if lock already reaches
                # outer through ANY recorded chain (A→B, B→C, C→A is
                # as fatal as A→B/B→A under a 3-way interleave)
                back = self._site_path(lock.site, outer.site)
                if back is not None and frozenset([outer.site] + back) not in self._reported_cycles:
                    self._reported_cycles.add(frozenset([outer.site] + back))
                    self.inversions.append(
                        {
                            "first": outer.site,
                            "then": lock.site,
                            "thread": tname,
                            "cycle": [outer.site] + back,
                            "conflicts_with": {
                                "first": lock.site,
                                "then": back[1],
                                "thread": self._edges[(lock.site, back[1])][0],
                            },
                        }
                    )
            held.append(lock)
            lock._holders.append((ident, now))

    def _site_path(self, src: str, dst: str) -> Optional[List[str]]:
        """Shortest [src, …, dst] over recorded order edges, or None.
        Caller holds _state_lock; the graph is a handful of creation
        sites, so BFS per nested acquisition is noise. Shares core's
        bfs_path with THR002 so the static and dynamic detectors agree
        on which cycles they report."""
        return bfs_path(self._adj, src, dst)

    @staticmethod
    def _drop_held(held: List["InstrumentedLock"], lock, all_levels: bool) -> bool:
        # release may be out of LIFO order (rare but legal) — remove by id;
        # all_levels drops every recursion level (Condition.wait on an
        # RLock releases them all at once via _release_save)
        dropped = False
        for i in range(len(held) - 1, -1, -1):
            if held[i] is lock:
                del held[i]
                dropped = True
                if not all_levels:
                    break
        return dropped

    def on_released(
        self, lock: "InstrumentedLock", now: float, all_levels: bool = False
    ) -> int:
        ident = threading.get_ident()
        with self._state_lock:
            # Whose stack owns this entry? Our own acquisition if we
            # have one; otherwise this is a cross-thread handoff
            # release (plain Lock: acquired in A, released here) and
            # the OLDEST recorded holder is the phantom to strip — the
            # real lock was already released before this callback, so
            # any NEWER holder re-acquired it legitimately in the gap
            # and its entry must survive. The acquire timestamp rides
            # in the holder entry (NOT a thread-local clock): a handoff
            # release must consume the ACQUIRER's timestamp, or it
            # lingers and inflates that thread's next hold of this
            # lock into a false over_held report.
            holders = lock._holders
            idents = [h[0] for h in holders]
            target = ident if ident in idents else (idents[0] if idents else None)
            t0 = None
            levels = 0
            if target is not None:
                if all_levels:
                    # Condition.wait on an RLock drops every recursion
                    # level at once; the hold began at the OUTERMOST
                    # (oldest) acquire. The dropped-level count goes
                    # back to the caller so _acquire_restore can mirror
                    # it on wake — restoring one entry for a depth-2
                    # hold would starve the outer release's bookkeeping.
                    mine = [h for h in holders if h[0] == target]
                    t0 = mine[0][1]
                    levels = len(mine)
                    lock._holders = [h for h in holders if h[0] != target]
                else:
                    # own release pops the NEWEST level (LIFO, RLock
                    # recursion); a handoff release strips the OLDEST —
                    # the phantom from the original acquire — so a
                    # holder that re-acquired in the gap between the
                    # real release and this bookkeeping keeps its live
                    # timestamp (consuming the live entry instead would
                    # leave the stale phantom to inflate the holder's
                    # real release into a false over_held)
                    if target == ident:
                        order = range(len(holders) - 1, -1, -1)
                    else:
                        order = range(len(holders))
                    for i in order:
                        if holders[i][0] == target:
                            t0 = holders[i][1]
                            del holders[i]
                            levels = 1
                            break
                self._drop_held(self._held.get(target, []), lock, all_levels)
            held_s = now - t0 if t0 is not None else 0.0
            if held_s > self.hold_threshold_s:
                self.over_held.append(
                    {
                        "site": lock.site,
                        "held_s": round(held_s, 4),
                        # blame the HOLDER: on a handoff release the
                        # current thread is just the messenger, and the
                        # report exists to point at the code path that
                        # kept work under the lock
                        "thread": _thread_name(target),
                    }
                )
            return levels

    def report(self) -> Dict:
        with self._state_lock:
            return {
                "acquisitions": self.acquisitions,
                "edges": len(self._edges),
                "inversions": list(self.inversions),
                "over_held": list(self.over_held),
            }


class InstrumentedLock:
    """Duck-typed threading.Lock recording acquisition order + hold time.

    Works as the lock under a ``threading.Condition`` and inside
    ``with`` statements; anything exotic (``_at_fork_reinit``…)
    delegates to the wrapped native lock.
    """

    def __init__(self, monitor: LockMonitor, real, site: str, reentrant: bool = False):
        # None after the minting monitor uninstalls: the lock keeps
        # working as the wrapped native, with no bookkeeping
        self._monitor: Optional[LockMonitor] = monitor
        self._real = real
        self.site = site
        self._reentrant = reentrant
        # (holder thread ident, monotonic acquire time) pairs, oldest
        # first (guarded by the monitor's state lock; see on_released)
        self._holders: List[Tuple[int, float]] = []

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._real.acquire(blocking, timeout)
        if ok and self._monitor is not None:
            self._monitor.on_acquired(self)
        return ok

    def release(self) -> None:
        now = time.monotonic()
        self._real.release()
        if self._monitor is not None:
            self._monitor.on_released(self, now)

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # -- Condition protocol -------------------------------------------
    # Defined HERE (not delegated raw) so a cond.wait() on this lock
    # pauses the hold clock: waiting is not holding, and raw delegation
    # to an RLock's C-level _release_save would bypass the wrapper and
    # count the whole wait as one giant hold.

    def _release_save(self):
        now = time.monotonic()
        if hasattr(self._real, "_release_save"):
            state = self._real._release_save()  # RLock: all levels at once
        else:
            self._real.release()  # plain lock inside a Condition
            state = None
        levels = (
            self._monitor.on_released(self, now, all_levels=True)
            if self._monitor is not None
            else 0
        )
        # ride the dropped-level count through the opaque saved state:
        # Condition hands it straight back to _acquire_restore
        return (state, levels)

    def _acquire_restore(self, saved) -> None:
        state, levels = saved
        if state is not None and hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(state)
        else:
            self._real.acquire()
        # mirror every dropped recursion level, or the outer release of
        # a nested `with cond:` hold finds no holder entry after a wait
        # and its hold time / order edges vanish from the record
        if self._monitor is not None:
            for _ in range(max(1, levels)):
                self._monitor.on_acquired(self)

    def _is_owned(self) -> bool:
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __getattr__(self, name):
        # anything else the wrapped primitive grows in future pythons
        return getattr(self._real, name)
