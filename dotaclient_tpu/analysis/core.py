"""Graftlint framework: rule registry, suppressions, baseline, driver.

Design constraints, in order:

- PURE AST. Linting the package must never import the package (or JAX,
  or numpy): the tier-1 lint test has to run before — and independent of
  — any accelerator runtime. Rules parse source with ``ast`` and cross-
  reference other files (config.py, obs/registry.py, k8s/*.yaml) by
  parsing them too, never by importing.
- Heuristic rules, honest escape hatches. Static thread/tracer analysis
  over dynamic Python is an approximation; the discipline is enforced by
  making every exception EXPLICIT: an inline
  ``# graftlint: disable=RULE(reason)`` with a non-empty reason, or a
  baseline entry with a non-empty reason. A suppression without a reason
  is itself a finding (GRAFT000) — silence must always be justified.
- Ratchet, don't boil the ocean. The checked-in baseline
  (``analysis/baseline.json``) pins pre-existing findings so only NEW
  violations fail CI; a baseline entry whose finding no longer exists is
  STALE and fails (the baseline can only shrink). Fingerprints hash the
  (rule, path, enclosing-qualname, message) — not line numbers — so
  unrelated edits don't churn the baseline.

Two rule shapes share one registry:

- module rules: ``run(module, ctx)`` called once per parsed file;
- repo rules:  ``run_repo(ctx)`` called once per lint with the whole
  parsed module set (cross-file checks: lock order, flag drift).
"""

from __future__ import annotations

import ast
import hashlib
import io
import json
import os
import re
import time
import tokenize
from collections import deque
from dataclasses import dataclass, field
from typing import Dict, Iterable, Iterator, List, Optional, Tuple

# Severity levels, in escalation order. "error" fails the default gate;
# "warning" fails only under --strict (the nightly invocation).
SEVERITIES = ("warning", "error")

_SUPPRESS_MARK_RE = re.compile(r"#\s*graftlint:\s*disable\s*=\s*")
_SUPPRESS_RULE_RE = re.compile(r"([A-Z]+\d+)")
_SUPPRESS_SEP_RE = re.compile(r"\s*,\s*")


def bfs_path(adj: Dict[str, List[str]], src: str, dst: str) -> Optional[List[str]]:
    """Shortest ``[src, …, dst]`` over directed edges, or None.

    The one cycle-search both lock-order detectors share — THR002's
    lexical edge graph and lockcheck's runtime acquisition graph — so
    the static and dynamic views can't drift on which cycles they
    report. Neighbors expand in sorted order for deterministic output.
    """
    if src == dst:
        return [src]
    prev: Dict[str, Optional[str]] = {src: None}
    queue: deque = deque([src])
    while queue:
        node = queue.popleft()
        for nxt in sorted(adj.get(node, ())):
            if nxt in prev:
                continue
            prev[nxt] = node
            if nxt == dst:
                path = [dst]
                while prev[path[-1]] is not None:
                    path.append(prev[path[-1]])
                return path[::-1]
            queue.append(nxt)
    return None


@dataclass(frozen=True)
class Finding:
    rule: str
    severity: str
    path: str  # repo-relative, forward slashes
    line: int
    message: str
    context: str = ""  # enclosing Class.method qualname (fingerprint stability)

    def fingerprint(self) -> str:
        digest = hashlib.sha1(self.message.encode()).hexdigest()[:10]
        return f"{self.rule}:{self.path}:{self.context}:{digest}"

    def render(self) -> str:
        ctx = f" [{self.context}]" if self.context else ""
        return f"{self.path}:{self.line}: {self.rule} {self.severity}:{ctx} {self.message}"


class Suppressions:
    """Inline ``# graftlint: disable=RULE(reason)`` index for one file.

    A suppression on line N covers findings reported at line N and line
    N+1 (comment-above style), matching how black/flake8 users write
    them. Empty OR MISSING reasons are recorded separately — the bare
    flake8-habit form ``disable=THR001`` with no ``(reason)`` does NOT
    suppress, and the driver reports each as a GRAFT000 error so the
    author learns the required syntax instead of silently keeping the
    finding. Only genuine COMMENT tokens are parsed — prose like this
    docstring mentioning the syntax is not a suppression and cannot
    GRAFT000-fail the gate.
    """

    def __init__(self, source: str):
        self._by_line: Dict[int, Dict[str, str]] = {}
        self.missing_reason: List[Tuple[int, str]] = []  # (line, rule)
        for lineno, text in self._comments(source):
            mark = _SUPPRESS_MARK_RE.search(text)
            if not mark:
                continue
            # comma-separated items from the marker on; stop at the
            # first non-item text so trailing prose can't misparse
            pos = mark.end()
            while True:
                item = _SUPPRESS_RULE_RE.match(text, pos)
                if not item:
                    break
                rule = item.group(1)
                pos = item.end()
                if pos < len(text) and text[pos] == "(":
                    # paren-balanced reason scan — reasons naturally
                    # contain calls ("len() is one GIL-atomic read"),
                    # which a [^)]* capture would silently truncate at
                    # the first close paren
                    depth, start = 1, pos + 1
                    i = start
                    while i < len(text) and depth:
                        if text[i] == "(":
                            depth += 1
                        elif text[i] == ")":
                            depth -= 1
                        i += 1
                    reason = text[start : i - 1] if depth == 0 else text[start:]
                    pos = i
                else:
                    reason = ""
                if not reason.strip():
                    self.missing_reason.append((lineno, rule))
                else:
                    self._by_line.setdefault(lineno, {})[rule] = reason.strip()
                sep = _SUPPRESS_SEP_RE.match(text, pos)
                if not sep:
                    break
                pos = sep.end()

    @staticmethod
    def _comments(source: str) -> Iterator[Tuple[int, str]]:
        """(lineno, text) of every COMMENT token. Tokenizing (vs raw
        line scanning) keeps docstrings and string literals that
        MENTION the disable syntax from registering as suppressions."""
        try:
            for tok in tokenize.generate_tokens(io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    yield tok.start[0], tok.string
        except (tokenize.TokenError, IndentationError, SyntaxError):
            # the file already ast.parse'd, so this is near-unreachable;
            # a tokenize quirk must not crash the whole lint run
            return

    def covers(self, rule: str, line: int) -> bool:
        for candidate in (line, line - 1):
            if rule in self._by_line.get(candidate, {}):
                return True
        return False


class ModuleUnit:
    """One parsed source file plus the derived indexes rules share."""

    def __init__(self, abspath: str, relpath: str, source: str, tree: ast.Module):
        self.abspath = abspath
        self.relpath = relpath
        self.source = source
        self.tree = tree
        self.suppressions = Suppressions(source)
        # parent links: ancestry queries (lock-guard With detection)
        self.parents: Dict[ast.AST, ast.AST] = {}
        for node in ast.walk(tree):
            for child in ast.iter_child_nodes(node):
                self.parents[child] = node

    def qualname_at(self, node: ast.AST) -> str:
        """Dotted Class.method path enclosing `node` (may be "")."""
        parts: List[str] = []
        cur: Optional[ast.AST] = node
        while cur is not None:
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
                parts.append(cur.name)
            cur = self.parents.get(cur)
        return ".".join(reversed(parts))

    def ancestors(self, node: ast.AST) -> Iterable[ast.AST]:
        cur = self.parents.get(node)
        while cur is not None:
            yield cur
            cur = self.parents.get(cur)


@dataclass
class RepoContext:
    """Paths + parsed modules for one lint run. The cross-file inputs
    (config.py, obs/registry.py, k8s/) are overridable so the fixture
    corpus can exercise the OBS rules hermetically.

    Cross-file sources are parsed ONCE per lint run and shared across
    all rule families through `source_of`/`ast_of`/`script_modules` —
    before these caches, every family re-parsed config.py, the argparse
    binaries, and the scripts/ drivers on its own."""

    root: str
    modules: List[ModuleUnit] = field(default_factory=list)
    config_path: Optional[str] = None
    registry_path: Optional[str] = None
    k8s_dir: Optional[str] = None
    scripts_dir: Optional[str] = None
    serialize_path: Optional[str] = None
    packer_cc_path: Optional[str] = None
    _source_cache: Dict[str, Optional[str]] = field(
        default_factory=dict, repr=False, compare=False
    )
    _ast_cache: Dict[str, Optional[ast.Module]] = field(
        default_factory=dict, repr=False, compare=False
    )
    _script_modules: Optional[List[ModuleUnit]] = field(
        default=None, repr=False, compare=False
    )

    def source_of(self, path: str) -> Optional[str]:
        """Memoized file read (None on OSError) — one disk read per
        cross-file input per lint run, shared by every rule family."""
        key = os.path.abspath(path)
        if key not in self._source_cache:
            try:
                with open(key, encoding="utf-8") as f:
                    self._source_cache[key] = f.read()
            except OSError:
                self._source_cache[key] = None
        return self._source_cache[key]

    def ast_of(self, path: str) -> Optional[ast.Module]:
        """Memoized ``ast.parse`` of `path` (None on read/syntax error).
        Package files already parsed into `modules` are served from
        their ModuleUnit, never re-parsed."""
        key = os.path.abspath(path)
        if key not in self._ast_cache:
            for m in self.modules:
                if m.abspath == key:
                    self._ast_cache[key] = m.tree
                    break
            else:
                source = self.source_of(key)
                try:
                    self._ast_cache[key] = (
                        None if source is None else ast.parse(source, filename=key)
                    )
                except SyntaxError:
                    self._ast_cache[key] = None
        return self._ast_cache[key]

    def script_modules(self) -> List[ModuleUnit]:
        """The scripts/ bench+soak drivers as parsed ModuleUnits, once
        per lint run (OBS002 argv scanning and the SVC fleet-graph
        rules both read them)."""
        if self._script_modules is None:
            if self.scripts_dir and os.path.isdir(self.scripts_dir):
                self._script_modules = parse_modules(self.root, [self.scripts_dir])
            else:
                self._script_modules = []
        return self._script_modules


class Rule:
    """Base: subclasses set `id`, `severity`, `doc` and implement
    either run(module, ctx) (per-file) or run_repo(ctx) (whole-repo)."""

    id: str = ""
    severity: str = "error"
    doc: str = ""

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        return []

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        return []

    def make(
        self, module_or_path, line: int, message: str, context: str = ""
    ) -> Finding:
        path = (
            module_or_path.relpath
            if isinstance(module_or_path, ModuleUnit)
            else str(module_or_path)
        )
        return Finding(self.id, self.severity, path, line, message, context)


RULES: Dict[str, Rule] = {}


def register(rule_cls):
    """Class decorator: instantiate and index by rule id."""
    rule = rule_cls()
    assert rule.id and rule.id not in RULES, f"bad/duplicate rule id {rule.id!r}"
    assert rule.severity in SEVERITIES
    RULES[rule.id] = rule
    return rule_cls


def _ensure_rules_loaded() -> None:
    # Import for the registration side effect; deferred so `import
    # dotaclient_tpu.analysis.core` alone stays cheap and cycle-free.
    from dotaclient_tpu.analysis import (  # noqa: F401
        jax_rules,
        lif_rules,
        obs_rules,
        proto_rules,
        thr_rules,
    )


# ------------------------------------------------------------------ baseline


def load_baseline(path: str) -> Tuple[Dict[str, str], List[str]]:
    """Returns ({fingerprint: reason}, [format errors]). Every entry must
    carry a non-empty reason — an unexplained baseline entry is just a
    suppression nobody can audit."""
    if not os.path.exists(path):
        return {}, []
    with open(path) as f:
        data = json.load(f)
    entries = data.get("entries", {})
    errors: List[str] = []
    out: Dict[str, str] = {}
    for fp, meta in entries.items():
        reason = (meta or {}).get("reason", "") if isinstance(meta, dict) else ""
        if not str(reason).strip():
            errors.append(f"baseline entry {fp} has no reason")
            continue
        out[fp] = str(reason).strip()
    return out, errors


def write_baseline(
    path: str,
    findings: List[Finding],
    reason: str,
    keep_reasons: Optional[Dict[str, str]] = None,
) -> None:
    """Regenerate the baseline from current findings (--write-baseline).
    The shared `reason` placeholder applies only to NEW entries — an
    entry already in `keep_reasons` (the loaded baseline) keeps its
    hand-audited justification; regenerating must never erase the audit
    trail. A human is expected to edit the new entries' reasons before
    committing."""
    keep_reasons = keep_reasons or {}
    entries = {}
    for f in findings:
        fp = f.fingerprint()
        entries[fp] = {
            "rule": f.rule,
            "path": f.path,
            "context": f.context,
            "message": f.message,
            "reason": keep_reasons.get(fp, reason),
        }
    parent = os.path.dirname(os.path.abspath(path))
    if parent:
        os.makedirs(parent, exist_ok=True)
    with open(path, "w") as fh:
        json.dump({"entries": entries}, fh, indent=2, sort_keys=True)
        fh.write("\n")


# -------------------------------------------------------------------- driver


@dataclass
class LintReport:
    findings: List[Finding]  # new: not suppressed, not baselined
    suppressed: List[Finding]
    baselined: List[Finding]
    stale_baseline: List[str]  # fingerprints with no current finding
    invalid: List[Finding]  # GRAFT000: suppression/baseline hygiene
    files_scanned: int = 0
    # wall seconds per rule id — the nightly --strict budget ledger:
    # a rule family that grows past its share shows up here, in --json,
    # before it shows up as a timed-out gate
    rule_seconds: Dict[str, float] = field(default_factory=dict)

    def failures(self, strict: bool = False) -> List[str]:
        """Human-readable list of everything that fails this run. The
        baseline hygiene checks (stale entries, reason-less
        suppressions) fail at EVERY strictness — the ratchet only works
        if the escape hatches stay audited."""
        out = [f.render() for f in self.findings if strict or f.severity == "error"]
        out += [f.render() for f in self.invalid]
        out += [
            f"baseline entry is stale (no current finding): {fp}"
            for fp in self.stale_baseline
        ]
        return out

    def to_json(self, strict: bool = False) -> Dict:
        return {
            "ok": not self.failures(strict),
            "files_scanned": self.files_scanned,
            "new": [f.render() for f in self.findings],
            "suppressed": len(self.suppressed),
            "baselined": len(self.baselined),
            "stale_baseline": self.stale_baseline,
            "invalid": [f.render() for f in self.invalid],
            "rule_seconds": {
                rule: round(secs, 4)
                for rule, secs in sorted(self.rule_seconds.items())
            },
        }


def _iter_py_files(paths: List[str]) -> Iterable[str]:
    for p in paths:
        if os.path.isfile(p) and p.endswith(".py"):
            yield p
        elif os.path.isdir(p):
            for dirpath, dirnames, filenames in os.walk(p):
                dirnames[:] = [d for d in dirnames if d != "__pycache__"]
                for name in sorted(filenames):
                    if name.endswith(".py"):
                        yield os.path.join(dirpath, name)


def parse_modules(root: str, paths: List[str]) -> List[ModuleUnit]:
    modules = []
    for abspath in _iter_py_files(paths):
        with open(abspath, encoding="utf-8") as f:
            source = f.read()
        try:
            tree = ast.parse(source, filename=abspath)
        except SyntaxError:
            # not ours to judge — the interpreter/test suite owns syntax
            continue
        rel = os.path.relpath(abspath, root).replace(os.sep, "/")
        modules.append(ModuleUnit(abspath, rel, source, tree))
    return modules


def lint_repo(
    root: str,
    paths: Optional[List[str]] = None,
    baseline_path: Optional[str] = None,
    rules: Optional[List[str]] = None,
) -> LintReport:
    """Lint `paths` (default: the dotaclient_tpu package under `root`)
    against all registered rules (or the `rules` subset).

    With an explicit `paths` subset, the WHOLE package is still parsed
    and analyzed — cross-file rules (lock order, flag consumption) and
    stale-baseline accounting are only meaningful over the full module
    set — but reported findings are restricted to files under `paths`.
    """
    _ensure_rules_loaded()
    root = os.path.abspath(root)
    package = os.path.join(root, "dotaclient_tpu")
    selected_rel: Optional[set] = None
    if paths is None:
        modules = parse_modules(root, [package])
    else:
        by_abs = {m.abspath: m for m in parse_modules(root, [package])}
        subset_abs = [os.path.abspath(p) for p in _iter_py_files(paths)]
        # linted paths may live outside the package; in-package ones are
        # already parsed above — selecting by path costs no second parse
        for m in parse_modules(root, [p for p in subset_abs if p not in by_abs]):
            by_abs[m.abspath] = m
        selected_rel = {
            os.path.relpath(p, root).replace(os.sep, "/") for p in subset_abs
        }
        modules = list(by_abs.values())
    ctx = RepoContext(root=root, modules=modules)
    for default_rel, attr in (
        (os.path.join("dotaclient_tpu", "config.py"), "config_path"),
        (os.path.join("dotaclient_tpu", "obs", "registry.py"), "registry_path"),
        ("k8s", "k8s_dir"),
        ("scripts", "scripts_dir"),
        (
            os.path.join("dotaclient_tpu", "transport", "serialize.py"),
            "serialize_path",
        ),
        (
            os.path.join("dotaclient_tpu", "native", "packer.cc"),
            "packer_cc_path",
        ),
    ):
        cand = os.path.join(root, default_rel)
        if getattr(ctx, attr) is None and os.path.exists(cand):
            setattr(ctx, attr, cand)

    active = [RULES[r] for r in rules] if rules else list(RULES.values())
    raw: List[Finding] = []
    rule_seconds: Dict[str, float] = {}
    for rule in active:
        started = time.perf_counter()
        for module in ctx.modules:
            raw.extend(rule.run(module, ctx))
        raw.extend(rule.run_repo(ctx))
        rule_seconds[rule.id] = time.perf_counter() - started

    # Partition: inline suppressions first, then the baseline.
    by_rel = {m.relpath: m for m in ctx.modules}
    baseline_reasons: Dict[str, str] = {}
    invalid: List[Finding] = []
    if baseline_path is None:
        baseline_path = os.path.join(
            root, "dotaclient_tpu", "analysis", "baseline.json"
        )
    try:
        baseline_reasons, errs = load_baseline(baseline_path)
    except (ValueError, OSError) as e:
        errs = [f"baseline unreadable: {e}"]
    for msg in errs:
        invalid.append(
            Finding("GRAFT000", "error", os.path.relpath(baseline_path, root), 0, msg)
        )
    for m in ctx.modules:
        for line, rule in m.suppressions.missing_reason:
            invalid.append(
                Finding(
                    "GRAFT000",
                    "error",
                    m.relpath,
                    line,
                    f"graftlint suppression for {rule} has an empty reason — "
                    f"write disable={rule}(why this is safe)",
                )
            )

    new: List[Finding] = []
    suppressed: List[Finding] = []
    baselined: List[Finding] = []
    seen_fps = set()
    for f in sorted(raw, key=lambda f: (f.path, f.line, f.rule)):
        # a suppressed finding still EXISTS: its fingerprint counts as
        # seen, or adding a reasoned inline suppression to a baselined
        # finding would fail the gate with a misleading "stale (no
        # current finding)" for an entry whose finding is right there
        fp = f.fingerprint()
        seen_fps.add(fp)
        mod = by_rel.get(f.path)
        if mod is not None and mod.suppressions.covers(f.rule, f.line):
            suppressed.append(f)
            continue
        if fp in baseline_reasons:
            baselined.append(f)
        else:
            new.append(f)
    stale = sorted(fp for fp in baseline_reasons if fp not in seen_fps)
    if selected_rel is not None:
        # Subset lint: the full-package analysis above keeps cross-file
        # rules and stale accounting honest; the REPORT covers only what
        # the caller asked to lint.
        new = [f for f in new if f.path in selected_rel]
        suppressed = [f for f in suppressed if f.path in selected_rel]
        baselined = [f for f in baselined if f.path in selected_rel]
        # module-level hygiene follows the selection; baseline-file
        # errors (non-.py path) always fail
        invalid = [
            f
            for f in invalid
            if not f.path.endswith(".py") or f.path in selected_rel
        ]
    return LintReport(
        findings=new,
        suppressed=suppressed,
        baselined=baselined,
        stale_baseline=stale,
        invalid=invalid,
        files_scanned=len(ctx.modules) if selected_rel is None else len(selected_rel),
        rule_seconds=rule_seconds,
    )
