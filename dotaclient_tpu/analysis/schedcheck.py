"""Schedcheck: deterministic schedule exploration over explicit protocol
models — the model-checking half of graftcheck.

The last three PRs each shipped (and then hand-fixed) a concurrency bug
that no single test schedule would ever hit deterministically: the ring
lease released at put-dispatch (PR 11 — an in-flight H2D observing the
NEXT batch's bytes), ``drained()`` declaring victory while a popped
batch lived only in a consumer thread's locals (PR 7 — a SIGTERM drain
silently losing frames), and checkpoint-teardown coalescing races. Those
protocols are tiny state machines; this module model-checks them as
EXPLICIT models, exhaustively, over every interleaving up to a bound —
so the bug class is excluded by search, not by luck.

Design:

- A model is a plain-Python object over an immutable-ish ``dict`` state:
  ``init()``, ``threads`` (ids), ``enabled(st, tid)``, ``step(st, tid)``
  (mutates a copy the explorer hands it), ``invariant(st)`` (violation
  strings, checked after every step), ``done(st)`` and
  ``final_check(st)``. Every transition is one atomic region of the real
  code — what happens under one lock hold, or between two preemption
  points.
- ``explore()`` runs a DFS over thread choices with two sound
  reductions: a visited-state set (two schedules reaching the same
  (shared state, pcs) need exploring once — the stateful-search
  reduction DPOR approximates), and local-step commutation (a
  transition marked ``local`` touches only its own thread's pc/locals,
  so it commutes with everything and is taken immediately without
  branching). The result says whether the bounded set was EXHAUSTED —
  "zero violations" only counts when it was.
- ``random_walks()`` is the seeded soak mode: long schedules through the
  same models, replayable from the seed.
- Mutants: each model takes a ``mutant=`` knob that re-introduces a
  shipped bug class (``early_release``, ``no_packing_check``,
  ``downstream_first``, ``clear_flag_before_put``, ``no_resubmit``,
  ``per_row_read``). Tests pin that exploration FINDS each mutant's
  violation and that the HEAD protocol explores clean — the
  failing-then-fixed schedule, as a regression.

The models are cross-validated against the real code by tests
(tests/test_schedcheck.py): the lifecycle semantics the ring model
assumes (acquire-from-free only, idempotent release, re-zero on
acquire) are asserted against the real ``TransferRing``/``RingSlot``,
and the drained() station order mirrors ``StagingBuffer.drained()``
check-for-check. Pure stdlib — importing this module never imports
JAX/numpy, so schedule exploration runs before (and independent of) any
accelerator runtime.
"""

from __future__ import annotations

import copy
import random
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

__all__ = [
    "ExploreResult",
    "explore",
    "random_walks",
    "RingLeaseModel",
    "DrainedModel",
    "CoalesceModel",
    "HotSwapModel",
    "HandoffModel",
    "ShardEpochModel",
    "PrefetchModel",
]


def _freeze(x):
    """Recursively hashable snapshot of a state value (dicts sorted)."""
    if isinstance(x, dict):
        return tuple(sorted((k, _freeze(v)) for k, v in x.items()))
    if isinstance(x, (list, tuple)):
        return tuple(_freeze(v) for v in x)
    if isinstance(x, set):
        return tuple(sorted(_freeze(v) for v in x))
    return x


@dataclass
class ExploreResult:
    """Outcome of one exploration. ``exhausted`` is the honesty bit:
    zero violations from a truncated search proves nothing, and the
    acceptance tests assert on BOTH fields."""

    violations: List[str] = field(default_factory=list)
    states: int = 0
    schedules: int = 0  # maximal schedules reaching a terminal state
    exhausted: bool = True

    @property
    def ok(self) -> bool:
        return not self.violations

    def require_exhausted_clean(self) -> "ExploreResult":
        if not self.exhausted:
            raise AssertionError(
                f"exploration truncated at {self.states} states — raise the bound"
            )
        if self.violations:
            raise AssertionError("; ".join(self.violations[:5]))
        return self


def explore(model, max_states: int = 400_000) -> ExploreResult:
    """Exhaustive bounded DFS over every interleaving of `model`.

    Visited-state dedup makes this a stateful search: each reachable
    (shared state, pcs) configuration is expanded once no matter how
    many schedules reach it. Transitions the model marks local (pure
    pc/thread-local moves) are taken immediately without branching —
    they commute with every other transition, the classic
    partial-order-reduction argument. Deadlock (no enabled thread, not
    done) is itself a violation: the cancel-swallow teardown class."""
    res = ExploreResult()
    init = model.init()
    seen = {_freeze(init)}
    stack = [init]
    res.states = 1
    vset = set()

    def report(v: str) -> None:
        if v not in vset:
            vset.add(v)
            res.violations.append(v)

    while stack:
        st = stack.pop()
        enabled = [t for t in model.threads if model.enabled(st, t)]
        if not enabled:
            res.schedules += 1
            if model.done(st):
                for v in model.final_check(st):
                    report(v)
            else:
                report(f"deadlock: no enabled thread in state {model.describe(st)}")
            continue
        local = [t for t in enabled if model.is_local(st, t)]
        choices = local[:1] if local else enabled
        for tid in choices:
            nxt = copy.deepcopy(st)
            model.step(nxt, tid)
            for v in model.invariant(nxt):
                report(v)
            key = _freeze(nxt)
            if key in seen:
                continue
            if res.states >= max_states:
                res.exhausted = False
                continue
            seen.add(key)
            res.states += 1
            stack.append(nxt)
    return res


def random_walks(
    model, runs: int = 200, seed: int = 0, max_steps: int = 10_000
) -> ExploreResult:
    """Seeded random schedules through `model` — the soak mode. Never
    claims exhaustion; replayable from (runs, seed)."""
    res = ExploreResult(exhausted=False)
    rng = random.Random(seed)
    vset = set()
    for _ in range(runs):
        st = model.init()
        for _ in range(max_steps):
            enabled = [t for t in model.threads if model.enabled(st, t)]
            if not enabled:
                break
            tid = rng.choice(enabled)
            model.step(st, tid)
            res.states += 1
            for v in model.invariant(st):
                if v not in vset:
                    vset.add(v)
                    res.violations.append(v)
        res.schedules += 1
        enabled = [t for t in model.threads if model.enabled(st, t)]
        if not enabled:
            if model.done(st):
                for v in model.final_check(st):
                    if v not in vset:
                        vset.add(v)
                        res.violations.append(v)
            else:
                v = f"deadlock: no enabled thread in state {model.describe(st)}"
                if v not in vset:
                    vset.add(v)
                    res.violations.append(v)
    return res


class _Model:
    """Shared trivia: default local/done/describe hooks."""

    threads: Tuple[str, ...] = ()

    def is_local(self, st: dict, tid: str) -> bool:
        return False

    def invariant(self, st: dict) -> List[str]:
        return st.get("violations", [])

    def final_check(self, st: dict) -> List[str]:
        return []

    def describe(self, st: dict) -> str:
        return str({k: v for k, v in sorted(st.items()) if k != "violations"})


# ---------------------------------------------------------------- ring lease


class RingLeaseModel(_Model):
    """The TransferRing slot lifecycle (parallel/fused_io.py):

        free --acquire(packer)--> packing --ready-put--> ready
             --learner-get--> in_transfer --release-after-retire--> free

    One packer (the staging assembler) and one learner share `depth`
    slots; the learner's device_put reads the slot buffer ASYNCHRONOUSLY
    (jax defers the host read of a put numpy buffer), modeled as a
    dispatch step and a separate retire step that observes which batch
    generation the buffer holds at retire time. The protocol invariant:
    the retire must observe the generation the get dispatched — anything
    else is the PR-11 H2D corruption (the next batch's bytes shipped).

    ``mutant="early_release"`` re-introduces the shipped bug: the lease
    returns to the free queue at put-DISPATCH, before the transfer
    retires — exploration finds the packer re-acquiring and repacking
    the slot under the in-flight read. ``mutant="double_release"`` makes
    release non-idempotent twice (models losing ``RingSlot._held``): the
    free queue grows a duplicate and a later acquire hands out a slot
    that is not free."""

    threads = ("packer", "learner")

    def __init__(self, depth: int = 2, batches: int = 3, mutant: Optional[str] = None):
        assert mutant in (None, "early_release", "double_release")
        self.depth = depth
        self.batches = batches
        self.mutant = mutant

    def init(self) -> dict:
        return {
            "free": tuple(range(self.depth)),
            "slot_state": {i: "free" for i in range(self.depth)},
            "slot_gen": {i: 0 for i in range(self.depth)},
            "ready": (),  # (slot, generation at put)
            "in_flight": {},  # slot -> generation the dispatch read
            "p_pc": "acquire",
            "p_slot": None,
            "packed": 0,
            "gen": 0,
            "l_pc": "get",
            "l_slot": None,
            "l_gen": None,
            "consumed": 0,
            "violations": [],
        }

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "packer":
            if st["p_pc"] == "acquire":
                return st["packed"] < self.batches and bool(st["free"])
            if st["p_pc"] == "put":
                return len(st["ready"]) < 2  # the ready queue's maxsize
            return st["p_pc"] != "done"
        if st["l_pc"] == "get":
            return st["consumed"] < self.batches and bool(st["ready"])
        return st["l_pc"] != "done"

    def step(self, st: dict, tid: str) -> None:
        if tid == "packer":
            pc = st["p_pc"]
            if pc == "acquire":
                sid, st["free"] = st["free"][0], st["free"][1:]
                if st["slot_state"][sid] != "free":
                    st["violations"].append(
                        f"acquire handed out slot {sid} in state "
                        f"{st['slot_state'][sid]} — the free queue holds a "
                        f"duplicate (double release)"
                    )
                st["slot_state"][sid] = "packing"
                st["p_slot"] = sid
                st["p_pc"] = "pack"
            elif pc == "pack":
                sid = st["p_slot"]
                st["gen"] += 1
                st["slot_gen"][sid] = st["gen"]
                if sid in st["in_flight"]:
                    st["violations"].append(
                        f"packer wrote slot {sid} while its H2D transfer was "
                        f"in flight — the device receives the next batch's "
                        f"bytes (the PR-11 early-lease-release corruption)"
                    )
                st["p_pc"] = "put"
            elif pc == "put":
                sid = st["p_slot"]
                st["slot_state"][sid] = "ready"
                st["ready"] += ((sid, st["slot_gen"][sid]),)
                st["p_slot"] = None
                st["packed"] += 1
                st["p_pc"] = "acquire" if st["packed"] < self.batches else "done"
            return
        pc = st["l_pc"]
        if pc == "get":
            (sid, gen), st["ready"] = st["ready"][0], st["ready"][1:]
            st["slot_state"][sid] = "in_transfer"
            st["l_slot"], st["l_gen"] = sid, gen
            st["l_pc"] = "dispatch"
        elif pc == "dispatch":
            sid = st["l_slot"]
            st["in_flight"][sid] = st["l_gen"]
            if self.mutant == "early_release":
                # the shipped bug: lease back to the packers at dispatch
                st["slot_state"][sid] = "free"
                st["free"] += (sid,)
            st["l_pc"] = "retire"
        elif pc == "retire":
            sid = st["l_slot"]
            observed = st["slot_gen"][sid]
            if observed != st["l_gen"]:
                st["violations"].append(
                    f"transfer of slot {sid} retired holding generation "
                    f"{observed}, dispatched with {st['l_gen']} — H2D read "
                    f"tore across a repack"
                )
            st["in_flight"].pop(sid, None)
            st["consumed"] += 1
            st["l_pc"] = "release"
        elif pc == "release":
            sid = st["l_slot"]
            if self.mutant != "early_release":
                st["slot_state"][sid] = "free"
                st["free"] += (sid,)
                if self.mutant == "double_release":
                    st["free"] += (sid,)  # _held lost: second put
            st["l_slot"] = st["l_gen"] = None
            st["l_pc"] = "get" if st["consumed"] < self.batches else "done"

    def is_local(self, st: dict, tid: str) -> bool:
        # retire/release touch shared slot state; only the terminal pc
        # moves are local — keep the reduction conservative.
        return False

    def done(self, st: dict) -> bool:
        return st["p_pc"] == "done" and st["l_pc"] == "done"

    def final_check(self, st: dict) -> List[str]:
        out = []
        if st["consumed"] != self.batches:
            out.append(
                f"learner consumed {st['consumed']} of {self.batches} batches"
            )
        if self.mutant is None and sorted(st["free"]) != list(range(self.depth)):
            out.append(f"slots lost: free queue ended as {st['free']}")
        return out


# ------------------------------------------------------------------ drained


class DrainedModel(_Model):
    """The SIGTERM-drain zero-loss protocol (runtime/staging.py pool
    mode): frames move pop-locals → intake → pending → pack-locals →
    ready, and ``drained()`` checks the stations UPSTREAM-first —
    ``_popping`` (under the mutate lock), ``intake.unfinished_tasks``,
    ``(_packing, pending)`` (one lock hold), then ready LAST. The
    controller thread quiesces, trains out ready batches, and polls
    drained(); the invariant is conservation: when drained() returns
    True, every popped frame is either consumed or sitting in _pending
    (the checkpointable leftover) — NEVER in a thread's locals or a
    queue.

    Mutants (each a real bug class):
    - ``no_packing_check``: drained() skips the in-flight pack flag —
      the PR-7 shipped bug (batch in assembler locals declared drained).
    - ``downstream_first``: drained() reads the ready queue FIRST; a
      batch crossing pack-locals→ready between the checks is lost.
    - ``clear_flag_before_put``: the assembler clears ``_packing``
      before the ready-queue put lands (the flag pattern's ordering
      contract, inverted)."""

    threads = ("pop", "assembler", "controller")

    def __init__(
        self,
        frames: int = 2,
        batch: int = 1,
        intake_cap: int = 1,
        ready_cap: int = 1,
        mutant: Optional[str] = None,
    ):
        assert mutant in (
            None,
            "no_packing_check",
            "downstream_first",
            "clear_flag_before_put",
        )
        self.frames = frames
        self.batch = batch
        self.intake_cap = intake_cap
        self.ready_cap = ready_cap
        self.mutant = mutant

    def init(self) -> dict:
        return {
            "broker": self.frames,
            "popping": False,
            "pop_local": 0,
            "intake_items": 0,
            "intake_unfinished": 0,
            "asm_local": 0,
            "pending": 0,
            "packing": False,
            "pack_local": 0,
            "ready": 0,
            "consumed": 0,
            "quiesce": False,
            "pop_pc": "idle",
            "asm_pc": "get",
            "ctl_pc": "quiesce",
            "obs": 0,  # drained() read cursor (0 = not mid-check)
            "drained_true": False,
            "violations": [],
        }

    # -- enabledness ---------------------------------------------------

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "pop":
            if st["pop_pc"] == "idle":
                # loop top: the quiesce check happens BEFORE _popping is
                # set (the real code's loop order)
                return not st["quiesce"] and st["broker"] > 0
            if st["pop_pc"] == "put":
                return st["intake_items"] < self.intake_cap
            return True
        if tid == "assembler":
            if st["asm_pc"] == "get":
                return st["intake_items"] > 0 or st["pending"] >= self.batch
            if st["asm_pc"] == "put_ready":
                return st["ready"] < self.ready_cap
            return True
        # controller: quiesce, then poll drained()/train-out until True
        return not st["drained_true"]

    # -- transitions ---------------------------------------------------

    def step(self, st: dict, tid: str) -> None:
        if tid == "pop":
            pc = st["pop_pc"]
            if pc == "idle":
                st["popping"] = True  # set under the mutate lock
                st["pop_pc"] = "pop"
            elif pc == "pop":
                st["broker"] -= 1
                st["pop_local"] = 1
                st["pop_pc"] = "put"
            elif pc == "put":
                st["intake_items"] += 1
                st["intake_unfinished"] += 1
                st["pop_local"] = 0
                st["pop_pc"] = "clear"
            elif pc == "clear":
                st["popping"] = False  # cleared under the mutate lock
                st["pop_pc"] = "idle"
            return
        if tid == "assembler":
            pc = st["asm_pc"]
            if pc == "get":
                if st["intake_items"] > 0:
                    st["intake_items"] -= 1
                    st["asm_local"] = 1
                    st["asm_pc"] = "ingest"
                else:
                    # nothing in the intake but a batch is pending
                    st["asm_pc"] = "take"
            elif pc == "ingest":
                # one mutate-lock hold: frames land in _pending
                st["pending"] += st["asm_local"]
                st["asm_local"] = 0
                st["asm_pc"] = "task_done"
            elif pc == "task_done":
                st["intake_unfinished"] -= 1
                st["asm_pc"] = "take" if st["pending"] >= self.batch else "get"
            elif pc == "take":
                # ONE lock hold: pop the batch AND set the in-flight flag
                # (the drained() visibility contract)
                st["pending"] -= self.batch
                st["packing"] = True
                st["pack_local"] = self.batch
                st["asm_pc"] = "put_ready"
            elif pc == "put_ready":
                if self.mutant == "clear_flag_before_put":
                    st["packing"] = False
                    st["asm_pc"] = "put_ready2"
                else:
                    st["ready"] += 1
                    st["pack_local"] = 0
                    st["asm_pc"] = "clear_flag"
            elif pc == "put_ready2":
                st["ready"] += 1
                st["pack_local"] = 0
                st["asm_pc"] = "get"
            elif pc == "clear_flag":
                st["packing"] = False
                st["asm_pc"] = "get"
            return
        # controller
        pc = st["ctl_pc"]
        if pc == "quiesce":
            st["quiesce"] = True
            st["ctl_pc"] = "loop"
        elif pc == "loop":
            if st["ready"] > 0:
                # train a ready batch out before re-polling
                st["ready"] -= 1
                st["consumed"] += self.batch
            else:
                st["obs"] = 0
                st["ctl_pc"] = "check"
        elif pc == "check":
            self._drained_read(st)

    def _stations(self) -> List[str]:
        order = ["popping", "unfinished", "packing_pending", "ready"]
        if self.mutant == "no_packing_check":
            order.remove("packing_pending")
            order.append("pending_only")
            order.remove("ready")
            order.append("ready")
        if self.mutant == "downstream_first":
            order = list(reversed(order))
        return order

    def _drained_read(self, st: dict) -> None:
        """One read of the drained() sequence — each check is its own
        interleaving point, exactly like the real method's lock holds."""
        stations = self._stations()
        name = stations[st["obs"]]
        clear = {
            "popping": lambda: not st["popping"],
            "unfinished": lambda: st["intake_unfinished"] == 0,
            "packing_pending": lambda: not st["packing"]
            and st["pending"] < self.batch,
            "pending_only": lambda: st["pending"] < self.batch,
            "ready": lambda: st["ready"] == 0,
        }[name]()
        if not clear:
            st["ctl_pc"] = "loop"  # station busy: retry from the top
            st["obs"] = 0
            return
        st["obs"] += 1
        if st["obs"] < len(stations):
            return
        # every station read clear → drained() returns True
        st["drained_true"] = True
        in_flight = (
            st["pop_local"]
            + st["asm_local"]
            + st["pack_local"]
            + st["intake_items"]
            + st["ready"] * self.batch
        )
        if in_flight:
            st["violations"].append(
                f"drained() returned True with {in_flight} frame(s) still in "
                f"flight (pop_local={st['pop_local']} asm_local={st['asm_local']} "
                f"pack_local={st['pack_local']} intake={st['intake_items']} "
                f"ready={st['ready']}) — a SIGTERM drain would lose them "
                f"(the PR-7 bug class)"
            )

    def done(self, st: dict) -> bool:
        return st["drained_true"]

    def final_check(self, st: dict) -> List[str]:
        popped = self.frames - st["broker"]
        accounted = st["consumed"] + st["pending"]
        if popped != accounted:
            return [
                f"conservation: {popped} frames popped but only {accounted} "
                f"accounted (consumed {st['consumed']} + pending {st['pending']})"
            ]
        return []


# ------------------------------------------------------------- coalescing


class CoalesceModel(_Model):
    """The latest-wins single-slot worker (CheckpointWorker /
    WeightPublisher / the checkpoint aux+mirror queues): submitters
    overwrite one pending slot under the condition lock and start the
    worker iff it is not in flight; the worker drains until the slot is
    empty, then parks (clearing in-flight under the same lock hold as
    the exit decision). Invariants: the NEWEST submission is always the
    last one written (coalescing may skip, never reorder or lose the
    newest), and the system quiesces with the slot empty and the worker
    parked — a worker exiting while the slot is full is the
    cancel-swallow teardown class.

    ``mutant="no_resubmit"`` drops the submit-side wakeup (submit fills
    the slot but never starts a parked worker): exploration finds the
    newest version stranded."""

    threads = ("submitter", "worker")

    def __init__(self, versions: int = 3, mutant: Optional[str] = None):
        assert mutant in (None, "no_resubmit")
        self.versions = versions
        self.mutant = mutant

    def init(self) -> dict:
        return {
            "pending": None,
            "inflight": False,
            "written": 0,
            "superseded": 0,
            "next_v": 1,
            "w_pc": "parked",
            "w_item": None,
            "violations": [],
        }

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "submitter":
            return st["next_v"] <= self.versions
        if st["w_pc"] == "parked":
            return st["inflight"]
        return True

    def step(self, st: dict, tid: str) -> None:
        if tid == "submitter":
            # one condition-lock hold: supersede + fill + maybe start
            if st["pending"] is not None:
                st["superseded"] += 1
            st["pending"] = st["next_v"]
            st["next_v"] += 1
            if not st["inflight"] and self.mutant != "no_resubmit":
                st["inflight"] = True
            return
        pc = st["w_pc"]
        if pc == "parked":
            st["w_pc"] = "take"
        elif pc == "take":
            # one lock hold: take-or-park (exit decision under the lock)
            if st["pending"] is None:
                st["inflight"] = False
                st["w_pc"] = "parked"
            else:
                st["w_item"], st["pending"] = st["pending"], None
                st["w_pc"] = "write"
        elif pc == "write":
            if st["w_item"] < st["written"]:
                st["violations"].append(
                    f"worker wrote version {st['w_item']} after {st['written']} "
                    f"— coalescing reordered"
                )
            st["written"] = st["w_item"]
            st["w_item"] = None
            st["w_pc"] = "take"

    def done(self, st: dict) -> bool:
        return (
            st["next_v"] > self.versions
            and st["w_pc"] == "parked"
            and not st["inflight"]
        )

    def final_check(self, st: dict) -> List[str]:
        out = []
        if st["written"] != self.versions:
            out.append(
                f"newest version {self.versions} lost: worker parked with "
                f"written={st['written']} pending={st['pending']} — the "
                f"latest-wins contract broke"
            )
        return out


# --------------------------------------------------------------- hot swap


class HotSwapModel(_Model):
    """The serve hot-swap no-mixed-tick protocol (serve/server.py
    ``_ServeBatcher``): a swapper thread publishes (params, version)
    bundles by single reference assignment; the batcher reads the bundle
    ONCE per tick and serves every row of that tick from it. Invariant:
    all rows of one tick carry one version.

    ``mutant="per_row_read"`` re-reads the bundle per row (the code
    shape the ONE-read contract exists to forbid): a swap landing
    mid-tick produces a mixed tick."""

    threads = ("swapper", "batcher")

    def __init__(
        self,
        swaps: int = 2,
        ticks: int = 2,
        rows: int = 2,
        mutant: Optional[str] = None,
    ):
        assert mutant in (None, "per_row_read")
        self.swaps = swaps
        self.ticks = ticks
        self.rows = rows
        self.mutant = mutant

    def init(self) -> dict:
        return {
            "bundle": 0,  # published version
            "swapped": 0,
            "tick": 0,
            "row": 0,
            "tick_v": None,  # version read at tick start
            "tick_rows": (),
            "b_pc": "tick_start",
            "violations": [],
        }

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "swapper":
            return st["swapped"] < self.swaps
        return st["tick"] < self.ticks

    def step(self, st: dict, tid: str) -> None:
        if tid == "swapper":
            st["swapped"] += 1
            st["bundle"] = st["swapped"]  # one atomic rebind
            return
        pc = st["b_pc"]
        if pc == "tick_start":
            st["tick_v"] = st["bundle"]  # the ONE bundle read
            st["tick_rows"] = ()
            st["row"] = 0
            st["b_pc"] = "row"
        elif pc == "row":
            v = st["bundle"] if self.mutant == "per_row_read" else st["tick_v"]
            st["tick_rows"] += (v,)
            st["row"] += 1
            if st["row"] >= self.rows:
                if len(set(st["tick_rows"])) > 1:
                    st["violations"].append(
                        f"tick {st['tick']} served rows from versions "
                        f"{sorted(set(st['tick_rows']))} — a client observed a "
                        f"mixed tick"
                    )
                st["tick"] += 1
                st["b_pc"] = "tick_start"

    def done(self, st: dict) -> bool:
        return st["swapped"] >= self.swaps and st["tick"] >= self.ticks

    def final_check(self, st: dict) -> List[str]:
        return []


# ---------------------------------------------------------- carry handoff


class HandoffModel(_Model):
    """The session-continuity carry-handoff lifecycle (serve/handoff.py
    + serve/server.py + serve/client.py): stream → durable → failover-
    read → resume.

    One client steps an episode through a serving tier that can be
    killed (kill = resident carry lost, unacked in-flight reply lost,
    un-landed store writes lost; restart is immediate — the in-process
    ServeIncarnations shape). At every chunk boundary the server
    WRITE-AHEAD streams the boundary carry to a keep-two store, THEN
    acks the chunk-fill step. On a failure the client resumes: restore
    the store entry matching its last OBSERVED boundary exactly (or the
    episode-start zeros when no boundary passed), replay its buffered
    partial chunk, re-issue the failed step.

    The carry is modeled as its episode POSITION: a serve of step k from
    carry position != k is the bitwise-divergence violation (the replay
    count is the client's steps-since-boundary, so a wrong restore point
    shifts every subsequent row); an abandon is itself a violation —
    this protocol exists to make replica death an episode non-event.

    Mutants (each a shipped-bug class the fixed protocol excludes):
    - ``handoff_after_ack``: the server acks the chunk-fill step BEFORE
      the store write lands. A kill in the ack→write window leaves the
      client vouched-for boundary missing from the store — the next
      failover's resume finds nothing matching and the episode abandons.
    - ``resume_from_stale``: the server returns the NEWEST store entry
      regardless of the client's boundary. When they differ (e.g. the
      write landed but the kill ate the ack), the restored carry is at
      the wrong position and every replayed/subsequent row diverges.
    - ``single_entry``: the store keeps only the newest entry. The
      previous boundary is load-bearing — write landed + ack lost means
      the store is one boundary AHEAD of the client, and without the
      previous entry the exact-match resume refuses (abandon).
    - ``dup_shift``: a put whose boundary EQUALS the newest entry's
      shifts instead of replacing. Exploration of THIS model found the
      bug during development: a resumed client re-issues its chunk-fill
      step, the server re-writes the same boundary, the duplicate shift
      evicts the previous entry — and a second kill before the re-issued
      ack lands abandons an episode keep-two was supposed to save.
      CarryStore.put replaces on equal episode_step because of this.
    - ``reshard_primary_only`` (requires ``shards`` > 1): after a
      topology change the failover read consults ONLY the key's NEW
      rendezvous primary. Entries written before the reshard still live
      on the OLD primary (rendezvous moves a key only TO the added
      shard — survivors never trade keys), so a post-reshard resume of
      a pre-reshard boundary finds nothing and abandons. The fixed
      protocol walks the key's full shard preference order until an
      exact match — ShardedCarryStore.get mirrors this rule.

    Sharding (``shards`` > 1): the store is N independent keep-two
    shards plus a bounded ``reshard`` thread that ADDS a shard
    mid-episode. Placement models the adversarial rendezvous case — the
    added shard becomes the key's new primary (rendezvous guarantees
    only that a moved key moves TO the new shard), so writes land on
    the newest shard while older boundaries stay where they were.
    Shard REMOVAL is deliberately out of scope: a removed store pod's
    entries are gone (a drain problem, not a read-protocol problem) —
    k8s store scale-down is operator-gated (MIGRATION)."""

    def __init__(
        self,
        steps: int = 5,
        chunk: int = 2,
        kills: int = 2,
        mutant: Optional[str] = None,
        shards: int = 1,
    ):
        assert mutant in (
            None,
            "handoff_after_ack",
            "resume_from_stale",
            "single_entry",
            "dup_shift",
            "reshard_primary_only",
        )
        assert shards >= 1
        assert mutant != "reshard_primary_only" or shards > 1, (
            "reshard_primary_only only differs from the fixed protocol "
            "once a reshard can happen (shards > 1)"
        )
        self.steps = steps
        self.chunk = chunk
        self.kills = kills
        self.mutant = mutant
        self.shards = shards
        self.keep = 1 if mutant == "single_entry" else 2
        # The reshard thread exists only when a topology change can:
        # shards=1 keeps the thread set (and the explored state space)
        # exactly the single-store model's.
        self.threads = ("client", "server", "chaos") + (
            ("reshard",) if shards > 1 else ()
        )

    def init(self) -> dict:
        return {
            "c_steps": 0,  # completed steps (acks consumed)
            "c_boundary": 0,  # last OBSERVED chunk boundary
            "c_pc": "issue",
            "issued": None,  # step index in flight
            "ack": False,  # reply delivered, not yet consumed
            "failed": False,  # connection failure / UNKNOWN_CLIENT pending
            "carry": None,  # server-resident carry position
            "s_pc": "idle",
            "pending_write": None,  # mutant handoff_after_ack: write after ack
            # per-shard retained entry positions, newest first; topo =
            # shards currently in the ring (grows on reshard)
            "stores": ((),),
            "topo": 1,
            "kills": 0,
            "violations": [],
        }

    # -- enabledness ---------------------------------------------------

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "client":
            if st["c_pc"] == "issue":
                return st["c_steps"] < self.steps and st["issued"] is None
            if st["c_pc"] == "wait":
                return st["ack"] or st["failed"]
            return True  # resume
        if tid == "server":
            if st["s_pc"] == "idle":
                return st["issued"] is not None and not st["ack"] and not st["failed"]
            return True  # write / ack / late_write stages pending
        if tid == "reshard":
            # bounded topology growth while the episode is still running
            return st["topo"] < self.shards and st["c_steps"] < self.steps
        # chaos: bounded kills while the episode is still running
        return st["kills"] < self.kills and st["c_steps"] < self.steps

    # -- transitions ---------------------------------------------------

    @staticmethod
    def _shard_order(st: dict):
        """The key's shard preference order under the CURRENT topology:
        newest shard first (the adversarial-rendezvous primary), older
        shards after — the ordered walk ShardedCarryStore.get runs."""
        return range(st["topo"] - 1, -1, -1)

    def _store_push(self, st: dict, value: int) -> None:
        # Writes land on the key's CURRENT primary (placement is
        # computed at put time, the ShardedCarryStore rule). Per shard,
        # same-boundary puts REPLACE the head entry (a resumed client
        # re-issuing its chunk-fill step re-writes the same boundary;
        # shifting would evict the previous entry keep-two exists for —
        # the dup_shift mutant is that bug, found by exploring this
        # model; CarryStore.put mirrors this rule).
        p = st["topo"] - 1
        shard = st["stores"][p]
        if shard and shard[0] == value and self.mutant != "dup_shift":
            return
        stores = list(st["stores"])
        stores[p] = (value,) + shard[: self.keep - 1]
        st["stores"] = tuple(stores)

    def step(self, st: dict, tid: str) -> None:
        if tid == "client":
            pc = st["c_pc"]
            if pc == "issue":
                st["issued"] = st["c_steps"]
                st["c_pc"] = "wait"
            elif pc == "wait":
                if st["ack"]:
                    st["ack"] = False
                    st["issued"] = None
                    st["c_steps"] += 1
                    if st["c_steps"] % self.chunk == 0:
                        # the reply just consumed vouches for this
                        # boundary (write-ahead made it durable first)
                        st["c_boundary"] = st["c_steps"]
                    st["c_pc"] = "issue"
                else:  # failed
                    st["failed"] = False
                    st["issued"] = None
                    st["c_pc"] = "resume"
            elif pc == "resume":
                if st["c_boundary"] == 0:
                    restored = 0  # episode-start zeros; no store needed
                elif self.mutant == "resume_from_stale":
                    nonempty = [
                        st["stores"][i] for i in self._shard_order(st) if st["stores"][i]
                    ]
                    if not nonempty:
                        st["violations"].append(
                            "episode abandoned: resume found an empty store "
                            "for an observed boundary"
                        )
                        restored = st["c_boundary"]
                    else:
                        restored = nonempty[0][0]  # newest, match ignored
                else:
                    # The fixed read walks the key's FULL shard
                    # preference order (exact match per shard); the
                    # reshard_primary_only mutant stops at the new
                    # primary — pre-reshard boundaries become unreadable.
                    order = list(self._shard_order(st))
                    if self.mutant == "reshard_primary_only":
                        order = order[:1]
                    matches = [
                        e
                        for i in order
                        for e in st["stores"][i]
                        if e == st["c_boundary"]
                    ]
                    if matches:
                        restored = matches[0]
                    else:
                        st["violations"].append(
                            f"episode abandoned: no store entry matches observed "
                            f"boundary {st['c_boundary']} (stores {st['stores']}) — "
                            f"a durable boundary went missing"
                        )
                        restored = st["c_boundary"]  # keep exploring past it
                # replay the buffered partial chunk: steps_since_boundary
                # advances, so a wrong restore point lands off-position
                st["carry"] = restored + (st["c_steps"] - st["c_boundary"])
                st["c_pc"] = "issue"
            return
        if tid == "server":
            pc = st["s_pc"]
            if pc == "idle":
                k = st["issued"]
                if k == 0:
                    st["carry"] = 0  # EPISODE_START reset
                if st["carry"] is None:
                    st["failed"] = True  # UNKNOWN_CLIENT — no resident carry
                    return
                if st["carry"] != k:
                    st["violations"].append(
                        f"served step {k} from carry position {st['carry']} — "
                        f"resumed rows diverge bitwise (stale-carry class)"
                    )
                st["carry"] += 1
                if st["carry"] % self.chunk == 0:  # chunk-fill step
                    if self.mutant == "handoff_after_ack":
                        st["pending_write"] = st["carry"]
                        st["s_pc"] = "ack"
                    else:
                        st["s_pc"] = "write"  # WRITE-AHEAD, then ack
                else:
                    st["s_pc"] = "ack"
            elif pc == "write":
                self._store_push(st, st["carry"])
                st["s_pc"] = "ack"
            elif pc == "ack":
                st["ack"] = True
                st["s_pc"] = "late_write" if st["pending_write"] is not None else "idle"
            elif pc == "late_write":
                self._store_push(st, st["pending_write"])
                st["pending_write"] = None
                st["s_pc"] = "idle"
            return
        if tid == "reshard":
            # controller adds a store shard mid-episode; by adversarial
            # placement it becomes the key's new rendezvous primary.
            # Entries already durable on the old primary stay where they
            # are (rendezvous never moves keys between survivors) — a
            # correct read must keep walking to them.
            st["topo"] += 1
            st["stores"] = st["stores"] + ((),)
            return
        # chaos: kill + immediate restart (the in-process controller
        # shape): resident carry gone, un-landed pipeline work gone, an
        # unacked in-flight step surfaces as a connection failure; a
        # reply already delivered (ack=True) stays delivered.
        st["kills"] += 1
        st["carry"] = None
        st["s_pc"] = "idle"
        st["pending_write"] = None
        if st["issued"] is not None and not st["ack"]:
            st["failed"] = True

    def done(self, st: dict) -> bool:
        return st["c_steps"] >= self.steps

    def final_check(self, st: dict) -> List[str]:
        out = []
        if st["c_steps"] != self.steps:
            out.append(f"episode finished {st['c_steps']} of {self.steps} steps")
        for shard in st["stores"]:
            for e in shard:
                if e % self.chunk != 0:
                    out.append(f"store entry {e} is not a chunk boundary")
        return out


# ------------------------------------------------------------ shard epoch


class ShardEpochModel(_Model):
    """The broker-fabric routing/failover lifecycle (transport/fabric.py
    FabricBroker + ShardFence + the tcp priority admission):
    route → publish → fence-check → apply.

    One client publishes `chunks` trajectory chunks of one route key
    (increasing seq; priority = seq+1 so later chunks rank higher —
    enough to force priority-admission pressure). The key's rendezvous
    primary is shard A; shard B is the failover successor, with a
    bounded admission queue (cap_b). A chaos thread PARTITIONS A once
    (publishes to it fail; frames it already holds are withheld — the
    stale-shard limbo) and later RESURRECTS it (withheld frames start
    delivering again — the late-delivery hazard the epoch fence exists
    for). `land_on_partition` selects the partition's publish fate:
    True = the frame lands but the ack is lost (the duplicate hazard),
    False = the frame is lost with the ack (the liveness hazard) — HEAD
    must explore clean under BOTH.

    Protocol under test (the FabricBroker/ShardFence rules):
    - a failed publish bumps the KEY's epoch BEFORE republishing the
      same seq to the successor;
    - the consumer fence drops epoch-stale arrivals (counted), dedupes
      same-seq arrivals (counted), applies the rest;
    - shard admission above capacity EVICTS the lowest-priority
      resident (counted) rather than refusing the newcomer.

    Invariants: no seq is ever applied twice (double-counted gradient
    data); every attempted seq is accounted — applied, fence-dropped,
    dup-dropped, priority-evicted, or shed with the client told
    (refused) — never silently lost.

    Mutants (each a real bug class the shipped protocol excludes):
    - ``no_fence``: the consumer applies whatever arrives (no epoch
      check, no seq dedup) — a resurrected A's late copy of a
      republished chunk applies twice.
    - ``reroute_before_drain``: the client re-routes the key to B
      without first resolving (republishing) the nacked in-flight
      chunk — that chunk vanishes with no ledger entry.
    - ``shed_newest``: admission above capacity refuses the NEWCOMER
      (the pre-fabric SHED) — a higher-priority chunk is shed while a
      lower-priority resident survives, the inversion priority
      admission exists to prevent.
    """

    threads = ("client", "net_a", "net_b", "chaos")

    def __init__(
        self,
        chunks: int = 3,
        cap_b: int = 1,
        land_on_partition: bool = True,
        mutant: Optional[str] = None,
    ):
        assert mutant in (None, "no_fence", "reroute_before_drain", "shed_newest")
        self.chunks = chunks
        self.cap_b = cap_b
        self.land = land_on_partition
        self.mutant = mutant

    def init(self) -> dict:
        return {
            "a_q": (),  # (epoch, seq) frames resident in shard A
            "b_q": (),  # (epoch, seq) frames resident in shard B
            "a_part": False,  # A partitioned (publishes fail, delivery withheld)
            "parts": 0,  # partitions executed (bounded to 1)
            "c_seq": 0,  # next fresh chunk index
            "c_epoch": 0,  # the key's publish epoch
            "c_down_a": False,  # client-side failover belief
            "pending": None,  # nacked seq awaiting republish
            "acked": (),  # seqs the client got an ack for
            "refused": (),  # seqs shed back to the client (it knows)
            "evicted": (),  # seqs priority-evicted at admission
            "f_epoch": 0,  # consumer fence: highest epoch seen
            "applied": (),  # apply history (a seq twice = violation)
            "fenced": (),  # epoch-stale drops
            "dup": (),  # same-seq dedup drops
            "violations": [],
        }

    # -- enabledness ---------------------------------------------------

    def _client_done(self, st: dict) -> bool:
        return st["c_seq"] >= self.chunks and st["pending"] is None

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "client":
            return not self._client_done(st)
        if tid == "net_a":
            return bool(st["a_q"]) and not st["a_part"]
        if tid == "net_b":
            return bool(st["b_q"])
        # chaos: one partition while the client still publishes, and the
        # matching resurrection whenever A is partitioned
        return (st["parts"] == 0 and not self._client_done(st)) or st["a_part"]

    # -- transitions ---------------------------------------------------

    def _apply(self, st: dict, epoch: int, seq: int) -> None:
        """Consumer fence-check + apply for one delivered frame — the
        ShardFence.admit rules (single producer boot)."""
        if self.mutant != "no_fence":
            if epoch < st["f_epoch"]:
                st["fenced"] += (seq,)
                return
            st["f_epoch"] = max(st["f_epoch"], epoch)
            if seq in st["applied"]:
                st["dup"] += (seq,)
                return
        if seq in st["applied"]:
            st["violations"].append(
                f"chunk seq {seq} applied twice — a stale shard's late "
                f"delivery was double-counted (the epoch-fence bug class)"
            )
        st["applied"] += (seq,)

    def _publish_b(self, st: dict, seq: int) -> None:
        """Publish (epoch, seq) to shard B with bounded priority
        admission (priority = seq+1)."""
        if len(st["b_q"]) >= self.cap_b:
            if self.mutant == "shed_newest":
                # the pre-fabric SHED: refuse the newcomer
                resident_min = min(s for _, s in st["b_q"])
                if seq > resident_min:
                    st["violations"].append(
                        f"admission shed chunk seq {seq} (priority {seq + 1}) "
                        f"while lower-priority seq {resident_min} stayed "
                        f"resident — the inversion priority-shed exists to "
                        f"prevent"
                    )
                st["refused"] += (seq,)
                st["pending"] = None
                if seq == st["c_seq"]:
                    st["c_seq"] += 1
                return
            # HEAD: evict the lowest-priority resident, admit the newcomer
            evict_i = min(range(len(st["b_q"])), key=lambda i: st["b_q"][i][1])
            evicted = st["b_q"][evict_i][1]
            st["b_q"] = st["b_q"][:evict_i] + st["b_q"][evict_i + 1 :]
            st["evicted"] += (evicted,)
        st["b_q"] += ((st["c_epoch"], seq),)
        st["acked"] += (seq,)
        st["pending"] = None
        if seq == st["c_seq"]:
            st["c_seq"] += 1

    def step(self, st: dict, tid: str) -> None:
        if tid == "client":
            seq = st["pending"] if st["pending"] is not None else st["c_seq"]
            if not st["c_down_a"]:
                if st["a_part"]:
                    # publish into the partition: maybe lands, ack lost
                    if self.land:
                        st["a_q"] += ((st["c_epoch"], seq),)
                    st["c_down_a"] = True
                    if self.mutant == "reroute_before_drain":
                        # the bug: move the key to B WITHOUT resolving
                        # the nacked chunk — it simply vanishes
                        st["pending"] = None
                        if seq == st["c_seq"]:
                            st["c_seq"] += 1
                    else:
                        # bump the epoch BEFORE the successor sees the
                        # key, then republish the same seq
                        st["c_epoch"] += 1
                        st["pending"] = seq
                else:
                    st["a_q"] += ((st["c_epoch"], seq),)
                    st["acked"] += (seq,)
                    st["pending"] = None
                    if seq == st["c_seq"]:
                        st["c_seq"] += 1
            else:
                self._publish_b(st, seq)
            return
        if tid == "net_a":
            (epoch, seq), st["a_q"] = st["a_q"][0], st["a_q"][1:]
            self._apply(st, epoch, seq)
            return
        if tid == "net_b":
            (epoch, seq), st["b_q"] = st["b_q"][0], st["b_q"][1:]
            self._apply(st, epoch, seq)
            return
        # chaos
        if st["a_part"]:
            st["a_part"] = False  # resurrect: withheld frames deliver again
        else:
            st["a_part"] = True
            st["parts"] += 1

    def done(self, st: dict) -> bool:
        return (
            self._client_done(st)
            and not st["a_q"]
            and not st["b_q"]
            and not st["a_part"]
        )

    def final_check(self, st: dict) -> List[str]:
        out = []
        for seq in range(self.chunks):
            accounted = (
                seq in st["applied"]
                or seq in st["fenced"]
                or seq in st["dup"]
                or seq in st["evicted"]
                or seq in st["refused"]
            )
            if not accounted:
                out.append(
                    f"chunk seq {seq} lost UNACCOUNTED — attempted but in no "
                    f"ledger (applied/fenced/dup/evicted/refused): the "
                    f"reroute-before-drain bug class"
                )
        for seq in set(st["applied"]):
            # acked chunks the fence later dropped are counted losses;
            # an applied chunk must still be unique (also inline-checked)
            if st["applied"].count(seq) > 1:
                out.append(f"chunk seq {seq} applied {st['applied'].count(seq)}x")
        return out


# ------------------------------------------------------------ prefetch lane


class PrefetchModel(_Model):
    """The overlapped learner pipeline lifecycle (runtime/learner.py
    PrefetchLane + _fetch_next, --learner.prefetch):

        ready --lane-take--> fetch-locals --put-dispatch--> in-flight
              --retire--> retired (lease released) --enqueue--> slot
              --loop-take--> train(N+1)  ‖  device still running step N

    One prefetch lane and one loop thread share a depth-1 handoff slot;
    the lane's device_put reads the staged buffer ASYNCHRONOUSLY (jax
    defers the host read of a put numpy buffer), modeled as a dispatch
    step and a separate retire step, with the ring-slot repack hazard
    carried over from RingLeaseModel: once the lease is released, the
    packer may re-zero and repack the buffer. A drain controller
    quiesces the source and polls the drained() stations — ready,
    lane-locals (the _inflight flag), handoff slot — before declaring
    the zero-loss verdict.

    Invariants: the retire observes the generation the dispatch read
    (anything else is the PR-11 H2D corruption); the loop trains only
    RETIRED batches (a batch handed over before its put retired could
    have its lease released and the buffer repacked under the in-flight
    read); drained()==True implies every popped batch was trained or is
    still visibly pending — never held invisibly by the lane.

    Mutants (the classes this PR's protocol must exclude):
    - ``release_before_retire``: the lane releases the ring lease at
      put-DISPATCH — the packer repacks under the in-flight transfer
      (the PR-11 bug, now one thread further from the loop).
    - ``train_consumes_inflight``: the lane enqueues the batch BEFORE
      the retire, so the loop can train a batch whose transfer is
      un-retired while its lease is already back with the packers.
    - ``drain_ignores_prefetch``: drained() skips the lane stations
      (inflight flag + handoff slot) — a SIGTERM drain declares victory
      over the batch the lane holds (the PR-7 loss class, one station
      further downstream)."""

    threads = ("packer", "lane", "loop", "drainer")

    def __init__(self, depth: int = 2, batches: int = 3, mutant: Optional[str] = None):
        assert mutant in (
            None,
            "release_before_retire",
            "train_consumes_inflight",
            "drain_ignores_prefetch",
        )
        self.depth = depth
        self.batches = batches
        self.mutant = mutant

    def init(self) -> dict:
        return {
            # ring slots (the staging-side buffers the lane leases)
            "free": tuple(range(self.depth)),
            "slot_gen": {i: 0 for i in range(self.depth)},
            "in_flight": {},  # slot -> generation the dispatch read
            "ready": (),  # (slot, generation) packed, awaiting the lane
            "p_pc": "acquire",
            "p_slot": None,
            "packed": 0,
            "gen": 0,
            # prefetch lane
            "lane_pc": "take",
            "lane_slot": None,
            "lane_gen": None,
            "lane_inflight": False,  # the holding() flag drained() reads
            "handoff": (),  # (slot?, gen, retired) — depth-1 queue
            # loop
            "trained": 0,
            # drain controller
            "quiesce": False,
            "drained_true": False,
            "violations": [],
        }

    # -- enabledness ---------------------------------------------------

    def enabled(self, st: dict, tid: str) -> bool:
        if tid == "packer":
            if st["p_pc"] == "acquire":
                return (
                    not st["quiesce"]
                    and st["packed"] < self.batches
                    and bool(st["free"])
                )
            if st["p_pc"] == "put":
                return len(st["ready"]) < 2
            return st["p_pc"] not in ("acquire", "done")
        if tid == "lane":
            if st["lane_pc"] == "take":
                return bool(st["ready"])
            if st["lane_pc"] == "enqueue":
                return not st["handoff"]  # depth-1 handoff slot
            return st["lane_pc"] != "take"
        if tid == "loop":
            return bool(st["handoff"]) and st["trained"] < self.batches
        # drainer: quiesce once the pipe has material, then poll until
        # the verdict lands
        return not st["drained_true"]

    # -- transitions ---------------------------------------------------

    def step(self, st: dict, tid: str) -> None:
        if tid == "packer":
            pc = st["p_pc"]
            if pc == "acquire":
                sid, st["free"] = st["free"][0], st["free"][1:]
                st["p_slot"] = sid
                st["p_pc"] = "pack"
            elif pc == "pack":
                sid = st["p_slot"]
                st["gen"] += 1
                st["slot_gen"][sid] = st["gen"]
                if sid in st["in_flight"]:
                    st["violations"].append(
                        f"packer repacked slot {sid} under an in-flight H2D "
                        f"read — the device receives the next batch's bytes "
                        f"(the PR-11 early-release corruption, via the lane)"
                    )
                st["p_pc"] = "put"
            elif pc == "put":
                sid = st["p_slot"]
                st["ready"] += ((sid, st["slot_gen"][sid]),)
                st["p_slot"] = None
                st["packed"] += 1
                st["p_pc"] = "acquire"
            return
        if tid == "lane":
            pc = st["lane_pc"]
            if pc == "take":
                # one region: the pop AND the inflight flag (the
                # holding() visibility contract — set before the batch
                # can live only in lane locals)
                st["lane_inflight"] = True
                (sid, gen), st["ready"] = st["ready"][0], st["ready"][1:]
                st["lane_slot"], st["lane_gen"] = sid, gen
                st["lane_pc"] = "dispatch"
            elif pc == "dispatch":
                sid = st["lane_slot"]
                st["in_flight"][sid] = st["lane_gen"]
                if self.mutant == "release_before_retire":
                    st["free"] += (sid,)  # lease back at dispatch: the bug
                if self.mutant == "train_consumes_inflight":
                    st["lane_pc"] = "enqueue"  # hand over un-retired
                else:
                    st["lane_pc"] = "retire"
            elif pc == "retire":
                sid = st["lane_slot"]
                observed = st["slot_gen"][sid]
                if observed != st["lane_gen"]:
                    st["violations"].append(
                        f"transfer of slot {sid} retired holding generation "
                        f"{observed}, dispatched with {st['lane_gen']} — H2D "
                        f"read tore across a repack"
                    )
                st["in_flight"].pop(sid, None)
                if self.mutant != "release_before_retire":
                    st["free"] += (sid,)  # release AFTER retire (HEAD)
                st["lane_pc"] = "enqueue"
            elif pc == "enqueue":
                retired = st["lane_slot"] not in st["in_flight"]
                st["handoff"] = ((st["lane_slot"], st["lane_gen"], retired),)
                st["lane_slot"] = st["lane_gen"] = None
                # flag cleared AFTER the handoff put (holding() gap rule)
                st["lane_inflight"] = False
                st["lane_pc"] = "take"
            return
        if tid == "loop":
            (sid, gen, retired), st["handoff"] = st["handoff"][0], ()
            if not retired:
                # the mutant path: finish the lifecycle the lane skipped
                # — but the TRAIN below already consumed an un-retired
                # transfer, which is the violation
                st["violations"].append(
                    f"loop trained a batch whose H2D transfer had not "
                    f"retired (slot {sid}) — with the lease released, the "
                    f"packer can repack the buffer under the read"
                )
                st["in_flight"].pop(sid, None)
                st["free"] += (sid,)
            st["trained"] += 1
            return
        # drainer
        if not st["quiesce"]:
            st["quiesce"] = True
            return
        # drained() poll — stations in downstream order: ready, lane
        # locals, handoff slot. One atomic poll per drainer step is
        # CONSERVATIVE for finding the mutant (the real drained() reads
        # stations one lock at a time, strictly weaker), and the mutant
        # must fail even against the strong form — which it does,
        # because the skipped stations are simply never read.
        stations_clear = not st["ready"]
        if self.mutant != "drain_ignores_prefetch":
            stations_clear = (
                stations_clear
                and not st["lane_inflight"]
                and not st["handoff"]
            )
        if stations_clear:
            st["drained_true"] = True
            held = (1 if st["lane_inflight"] else 0) + len(st["handoff"]) + len(st["ready"])
            if held:
                st["violations"].append(
                    f"drained() returned True with {held} batch(es) still "
                    f"held by the prefetch pipe — a SIGTERM drain would "
                    f"lose them (the PR-7 class, prefetch station)"
                )
            if st["packed"] > st["trained"]:
                st["violations"].append(
                    f"drain verdict with {st['packed'] - st['trained']} "
                    f"packed-but-untrained batch(es) unaccounted"
                )

    def is_local(self, st: dict, tid: str) -> bool:
        return False

    def done(self, st: dict) -> bool:
        return st["drained_true"]

    def final_check(self, st: dict) -> List[str]:
        out = []
        if st["trained"] != st["packed"]:
            out.append(
                f"conservation: {st['packed']} batches packed but "
                f"{st['trained']} trained at drain"
            )
        return out


def head_models() -> Dict[str, _Model]:
    """The HEAD-protocol model set the nightly soak and the acceptance
    tests exhaust — one entry per protocol, no mutants."""
    return {
        "ring_lease": RingLeaseModel(depth=2, batches=3),
        "prefetch": PrefetchModel(depth=2, batches=3),
        "drained": DrainedModel(frames=2),
        "coalesce": CoalesceModel(versions=3),
        "hot_swap": HotSwapModel(swaps=2, ticks=2, rows=2),
        "carry_handoff": HandoffModel(steps=5, chunk=2, kills=2),
        # both partition-publish fates: the frame lands with the ack
        # lost (duplicate hazard) and the frame lost with it (liveness)
        "shard_epoch": ShardEpochModel(chunks=3, land_on_partition=True),
        "shard_epoch_lost": ShardEpochModel(chunks=3, land_on_partition=False),
    }
