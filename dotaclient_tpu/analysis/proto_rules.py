"""SVC rules: graftproto — whole-fleet contract verification.

The fleet's cross-tier contracts are strings: HTTP route paths, meter
names, config-grammar clauses, conservation-ledger identities. Every
one used to be guarded by a hand-written pin in test_obs/test_k8s/
test_control — or by nothing. These rules cross-check the static fleet
contract graph (analysis/fleetgraph.py) so a rename on either side of
any edge fails the lint, not a 3am "meter missing" freeze:

SVC001 (error) — every consumed route (k8s probe paths and
``prometheus.io/path`` annotations, package/scripts URL literals) must
be served by its target binary: probes by the container's ``-m`` binary,
code edges by the binary the endpoint variable names (``_league_
endpoint`` → league.server), unhinted edges by *some* fleet surface.
Subsumes the hand-pinned probe-path checks test_k8s.py used to carry.

SVC002 (error) — every meter a k8s ``--control.policy`` or
``--fleet.alerts`` clause keys decisions on must (a) resolve in
obs/registry.py (exact SCALARS name, PREFIXES family, or an
``aggregate_tier`` special), and (b) be exported by the tier the clause
scrapes — the clause's tier binary for policy, fleetd's own rollups for
alerts. An unresolvable meter holds topology forever ("meter missing"
is a loud HOLD, never a scale): drift here silently disables scaling.

SVC003 (error) — every config-grammar literal (manifest policy/alert/
matchmaking clauses, soak-driver policy constants and chaos argparse
defaults) must parse with the REAL parser that reads it at boot. Runs
the parsers in one memoized subprocess (analysis/grammar_check.py) so
the lint process keeps its never-imports-the-package invariant, and
reports jax/jaxlib leaking into the parser import closure.

SVC004 (error) — the conservation-ledger identities fleetd audits
(obs/fleet.py LEDGERS) must term-for-term name meters that are (a)
registered and (b) exported by the emitting tier's binary — the PR-18
audit contract pinned statically. A LEDGERS tuple the extractor can no
longer read is itself a loud finding (the WIRE001 discipline), never a
silent skip.

All pure AST (SVC003's parsers excepted, by subprocess). Rules skip
cleanly on corpora with no HTTP layer / no manifests / no fleet.py —
synthetic single-file lint trees must not drown in fleet findings.
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
from typing import Dict, List, Tuple

from dotaclient_tpu.analysis.core import Finding, RepoContext, Rule, register
from dotaclient_tpu.analysis.fleetgraph import (
    AGG_SPECIALS,
    TIER_BINARIES,
    GrammarLiteral,
    fleet_graph,
)
from dotaclient_tpu.analysis.obs_rules import _registered, _registry_names

# one subprocess per distinct literal set per lint process — test suites
# lint many tree copies carrying identical manifests; re-spawning the
# interpreter for each would dominate the whole lint's wall clock
_GRAMMAR_MEMO: Dict[Tuple, Dict] = {}


def _check_grammars(literals: List[GrammarLiteral]) -> Dict:
    """{"failures": [...], "banned_imports": [...]} from the real
    parsers, run in grammar_check.py's fresh interpreter. The parsers
    are the LINT'S OWN — fixture corpora exercise the rule against the
    real grammar, and a mutated tree under test can't redefine the
    contract it is being checked against."""
    key = tuple(sorted((lit.grammar, lit.text) for lit in literals))
    cached = _GRAMMAR_MEMO.get(key)
    if cached is None:
        here = os.path.dirname(os.path.abspath(__file__))
        repo_root = os.path.dirname(os.path.dirname(here))
        runner = os.path.join(here, "grammar_check.py")
        payload = {
            "root": repo_root,
            "items": [
                {
                    "grammar": lit.grammar,
                    "text": lit.text,
                    "path": lit.relpath,
                    "line": lit.line,
                }
                for lit in literals
            ],
        }
        try:
            proc = subprocess.run(
                [sys.executable, runner],
                input=json.dumps(payload),
                capture_output=True,
                text=True,
                timeout=60,
            )
            if proc.returncode != 0:
                cached = {"error": proc.stderr.strip()[-500:] or "non-zero exit"}
            else:
                cached = json.loads(proc.stdout)
        except (OSError, subprocess.TimeoutExpired, ValueError) as e:
            cached = {"error": repr(e)}
        _GRAMMAR_MEMO[key] = cached
    return cached


@register
class ConsumedRouteUnserved(Rule):
    id = "SVC001"
    severity = "error"
    doc = "HTTP route consumed by a tier/probe but served by no binary"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        g = fleet_graph(ctx)
        if not g.has_http_layer():
            return []
        findings: List[Finding] = []
        for probe in g.probe_routes():
            served = g.served_by(probe.binary)
            if not served:
                continue  # binary entry not in this corpus
            if probe.route not in served:
                findings.append(
                    self.make(
                        probe.relpath,
                        probe.line,
                        f"probe/scrape path {probe.route!r} is not served by "
                        f"{probe.binary} (serves: "
                        f"{', '.join(sorted(served))}) — kubelet/prometheus "
                        f"will 404; fix the manifest or register the route",
                    )
                )
        union = g.served_union()
        for edge in g.consumed_routes():
            target = edge.hint if edge.hint in g.binaries else None
            if target is not None:
                served_map = g.served_by(target)
                if served_map and edge.route not in served_map:
                    findings.append(
                        self.make(
                            edge.relpath,
                            edge.line,
                            f"route {edge.route!r} is dialed against {target} "
                            f"but that binary serves only "
                            f"{', '.join(sorted(served_map))} — the request "
                            f"404s at runtime; fix the caller or register "
                            f"the route",
                            context=edge.context,
                        )
                    )
            elif edge.route not in union:
                findings.append(
                    self.make(
                        edge.relpath,
                        edge.line,
                        f"route {edge.route!r} is dialed here but NO fleet "
                        f"binary or driver surface serves it — the request "
                        f"can only 404; fix the caller or register the route",
                        context=edge.context,
                    )
                )
        return findings


@register
class PolicyMeterDrift(Rule):
    id = "SVC002"
    severity = "error"
    doc = "policy/alert clause meter that no registry name or scraped tier exports"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        if not (ctx.registry_path and os.path.exists(ctx.registry_path)):
            return []
        g = fleet_graph(ctx)
        scalars, prefixes = _registry_names(ctx)
        findings: List[Finding] = []
        for cm in g.clause_meters():
            if cm.meter in AGG_SPECIALS:
                continue  # aggregate_tier synthesizes up/scraped per tier
            surface = (
                "--control.policy"
                if cm.grammar == "control_policy"
                else "--fleet.alerts"
            )
            if not _registered(cm.meter, scalars, prefixes):
                findings.append(
                    self.make(
                        cm.relpath,
                        cm.line,
                        f"{surface} clause keys on meter {cm.meter!r}, which "
                        f"resolves to no obs/registry.py SCALARS name or "
                        f"PREFIXES family — the clause can only ever read "
                        f"'meter missing' and freeze topology; fix the "
                        f"clause or register the meter",
                    )
                )
                continue
            binary = TIER_BINARIES.get(cm.tier)
            if binary is None or binary not in g.binaries:
                continue
            if not g.exports_meter(binary, cm.meter):
                findings.append(
                    self.make(
                        cm.relpath,
                        cm.line,
                        f"{surface} clause keys on meter {cm.meter!r} for "
                        f"tier {cm.tier!r}, but no module reachable from "
                        f"{binary} exports that name — the scrape never "
                        f"carries it and the clause freezes on 'meter "
                        f"missing'; fix the clause or export the meter",
                    )
                )
        return findings


@register
class GrammarParseDrift(Rule):
    id = "SVC003"
    severity = "error"
    doc = "config-grammar literal that the real parser rejects at boot"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        g = fleet_graph(ctx)
        literals = g.grammar_literals()
        if not literals:
            return []
        result = _check_grammars(literals)
        if "error" in result:
            # the proof infrastructure failing is a gate failure, not a
            # skip — otherwise a broken runner silently passes everything
            first = literals[0]
            return [
                self.make(
                    first.relpath,
                    first.line,
                    f"grammar check subprocess failed "
                    f"({result['error']}) — cannot prove any config "
                    f"grammar literal parses; fix "
                    f"analysis/grammar_check.py",
                )
            ]
        findings: List[Finding] = []
        for failure in result.get("failures", ()):
            findings.append(
                self.make(
                    failure["path"],
                    int(failure["line"]),
                    f"{failure['grammar']} literal does not parse with the "
                    f"real parser — the binary refuses to boot: "
                    f"{failure['error']}",
                )
            )
        for mod in result.get("banned_imports", ()):
            first = literals[0]
            findings.append(
                self.make(
                    first.relpath,
                    first.line,
                    f"importing the config-grammar parsers pulled {mod!r} "
                    f"into the interpreter — the control/league/fleet "
                    f"tiers are jax-free by contract; gate the import",
                )
            )
        return findings


@register
class LedgerTermDrift(Rule):
    id = "SVC004"
    severity = "error"
    doc = "conservation-ledger term whose meter the emitting tier does not export"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        g = fleet_graph(ctx)
        terms, err = g.ledger_terms()
        fleet_rel = "dotaclient_tpu/obs/fleet.py"
        if err is not None:
            return [
                self.make(
                    fleet_rel,
                    1,
                    f"conservation-ledger extraction failed ({err}) — the "
                    f"audit identities can no longer be statically "
                    f"verified; keep LEDGERS a literal tuple of "
                    f"LedgerSpec(name=…, terms=(LedgerTerm(\"meter\", "
                    f"\"tier\", …), …))",
                )
            ]
        if not terms:
            return []
        have_registry = bool(
            ctx.registry_path and os.path.exists(ctx.registry_path)
        )
        scalars_prefixes = ((), ())
        if have_registry:
            scalars_prefixes = _registry_names(ctx)
        findings: List[Finding] = []
        for term in terms:
            if have_registry and not _registered(
                term.meter, scalars_prefixes[0], scalars_prefixes[1]
            ):
                findings.append(
                    self.make(
                        fleet_rel,
                        term.line,
                        f"ledger {term.ledger!r} term {term.meter!r} is not "
                        f"in obs/registry.py — the audit sums a meter no "
                        f"dashboard can select; register it or drop the "
                        f"term",
                        context="LEDGERS",
                    )
                )
                continue
            binary = TIER_BINARIES.get(term.tier)
            if binary is None:
                findings.append(
                    self.make(
                        fleet_rel,
                        term.line,
                        f"ledger {term.ledger!r} term {term.meter!r} names "
                        f"unknown tier {term.tier!r} — fleetd scrapes no "
                        f"such target class; fix the tier name",
                        context="LEDGERS",
                    )
                )
                continue
            if binary not in g.binaries:
                continue  # tier binary not in this corpus
            if not g.exports_meter(binary, term.meter):
                findings.append(
                    self.make(
                        fleet_rel,
                        term.line,
                        f"ledger {term.ledger!r} sums {term.meter!r} over "
                        f"tier {term.tier!r}, but no module reachable from "
                        f"{binary} exports that name — the audit term reads "
                        f"permanently absent and the identity silently "
                        f"loses a leg; fix the term or export the meter",
                        context="LEDGERS",
                    )
                )
        return findings
