"""graftproto extraction: the static fleet contract graph.

The fleet's tiers talk over three string-typed surfaces — HTTP routes
(obs/http.py registrations), Prometheus meter names (obs/registry.py +
the per-tier stats sources), and config grammars (--control.policy,
--fleet.alerts, --league.policy, chaos specs). This module extracts all
three sides of every such contract by AST/regex, never by import:

- **served routes per binary**: starting from each ``_BINARY_CONFIGS``
  entrypoint module, walk the package-internal import graph (including
  function-body gated imports — the transport/base.py ``connect``
  idiom) and collect every ``MetricsHTTPServer(...)`` construction
  reached. ``/metrics`` + ``/healthz`` are unconditional; ``/profile``,
  ``/debug/flight`` and the ``json_routes``/``query_routes``/
  ``post_routes`` dict-literal keys follow the constructor keywords.
- **emitted meters per binary**: every meter-shaped string constant and
  every f-string constant head in the binary's reachable module set —
  deliberately an over-approximation (a name anywhere in the tier's
  code counts as exported); the drift class this catches is the RENAME,
  which removes the literal everywhere at once.
- **consumer demands**: constant route tails of ``f"http://…"`` URL
  literals and of the ``urlopen``/``Request``/``_get``/``_post``/
  ``_get_json`` call idioms across the package and the scripts/
  drivers; k8s probe paths and ``prometheus.io/path`` annotations
  scoped to their container's binary; policy/alert clause meters and
  grammar literals from the manifests and soak drivers.
- **ledger identities**: the ``LEDGERS`` tuple in obs/fleet.py, term by
  term — (ledger, meter, tier) — the PR-18 conservation-audit contract.

proto_rules.py cross-checks consumer edges against producers (SVC001–
SVC004). Everything here is pure AST — the lint process must never
import the package, JAX, or numpy; SVC003's grammar proof runs the real
parsers in a subprocess precisely to keep that invariant.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Dict, List, NamedTuple, Optional, Set, Tuple

from dotaclient_tpu.analysis.core import ModuleUnit, RepoContext
from dotaclient_tpu.analysis.obs_rules import (
    _BINARY_CONFIGS,
    _MODULE_RE,
    _item_blocks,
)

# policy-clause tier vocabulary → the binary whose reachable modules
# must export the clause's meter (control/policy.py VALID_TIERS plus
# the fleetd alert surface, which scrapes its own rollups)
TIER_BINARIES = {
    "actor": "dotaclient_tpu.runtime.actor",
    "broker": "dotaclient_tpu.transport.fabric",
    "server": "dotaclient_tpu.serve.server",
    "store": "dotaclient_tpu.serve.handoff",
    "learner": "dotaclient_tpu.runtime.learner",
    "league": "dotaclient_tpu.league.server",
    "control": "dotaclient_tpu.control.server",
    "fleet": "dotaclient_tpu.obs.fleetd",
    "fleetd": "dotaclient_tpu.obs.fleetd",
}

# control/scrape.py aggregate_tier suffixes + synthesized specials —
# "serve_load_occupancy.mean" resolves through the base name; "up" and
# "scraped" exist for every tier without any exporter
AGG_SUFFIXES = (".mean", ".max", ".sum")
AGG_SPECIALS = ("up", "scraped")

# endpoint-variable keywords → target binary, checked in order against
# the identifiers inside the URL expression (NOT the enclosing scope:
# serve/server.py fetches league routes from inside InferenceServer).
# Generic words (ep, endpoint, server…) deliberately resolve to no
# target — those edges are checked against the whole-fleet route union.
_HINTS: Tuple[Tuple[str, str], ...] = (
    ("fleetd", "dotaclient_tpu.obs.fleetd"),
    ("fleet", "dotaclient_tpu.obs.fleetd"),
    ("league", "dotaclient_tpu.league.server"),
    ("control", "dotaclient_tpu.control.server"),
    ("handoff", "dotaclient_tpu.serve.handoff"),
    ("broker", "dotaclient_tpu.transport.fabric"),
    ("fabric", "dotaclient_tpu.transport.fabric"),
)

# call names whose string/f-string args carry route literals
_URL_CALLS = frozenset({"urlopen", "Request"})
_HELPER_CALLS = frozenset({"_get", "_post", "_get_json", "get_json"})

_ROUTE_RE = re.compile(r"^/[A-Za-z0-9_\-./]*$")
_METER_RE = re.compile(r"^[a-z][a-z0-9]*(?:_[a-z0-9]+)+$")
_METER_HEAD_RE = re.compile(r"^[a-z][a-z0-9_]*_$")

# yaml arg-item: - "--flag" / - --flag / - "--flag=value"
_ARG_ITEM_RE = re.compile(r'^\s*-\s*"?(--[A-Za-z0-9_.\-]+?)(?:=(.*?))?"?\s*$')
_VALUE_ITEM_RE = re.compile(r'^\s*-\s*"?(.*?)"?\s*$')
_HTTPGET_FLOW_RE = re.compile(r"httpGet:\s*\{\s*path:\s*\"?([^\s,}\"]+)")
_PROM_PATH_RE = re.compile(r"prometheus\.io/path:\s*\"?([^\s\"]+)")

# manifest/driver grammar surfaces → the real parser that owns each one
# (grammar_check.py maps these ids to import paths in the subprocess)
GRAMMAR_FLAGS = {
    "control.policy": "control_policy",
    "fleet.alerts": "fleet_alerts",
    "league.policy": "league_policy",
    "chaos.spec": "chaos_spec",
    "chaos": "chaos_spec",
    "faults": "chaos_spec",
}
GRAMMAR_CONSTS = {
    "POLICY": "control_policy",
    "ALERTS": "fleet_alerts",
    "MATCH_POLICY": "league_policy",
    "CHAOS": "chaos_spec",
    "FAULTS": "chaos_spec",
}


class ServedRoute(NamedTuple):
    route: str
    relpath: str
    line: int


class ConsumedRoute(NamedTuple):
    route: str
    relpath: str
    line: int
    hint: Optional[str]  # target binary module, or None = union check
    context: str


class ProbeRoute(NamedTuple):
    route: str
    relpath: str
    line: int
    binary: str


class ClauseMeter(NamedTuple):
    meter: str  # base name, aggregation suffix stripped
    tier: str
    relpath: str
    line: int
    grammar: str  # "control_policy" | "fleet_alerts"


class GrammarLiteral(NamedTuple):
    grammar: str
    text: str
    relpath: str
    line: int


class LedgerRef(NamedTuple):
    ledger: str
    meter: str
    tier: str
    line: int


def _pkg_rel(dotted: str) -> Tuple[str, str]:
    """Candidate relpaths (module file, package __init__) for a dotted
    package-internal module name."""
    base = dotted.replace(".", "/")
    return f"{base}.py", f"{base}/__init__.py"


class FleetGraph:
    """The contract graph for one lint run (build once, cached on the
    RepoContext — every SVC rule reads the same extraction)."""

    def __init__(self, ctx: RepoContext):
        self.ctx = ctx
        self.by_rel: Dict[str, ModuleUnit] = {m.relpath: m for m in ctx.modules}
        self._imports: Dict[str, Set[str]] = {}
        self._reach_cache: Dict[str, Set[str]] = {}
        self._served_cache: Dict[str, Dict[str, ServedRoute]] = {}
        self._emit_cache: Dict[str, Tuple[Set[str], Set[str]]] = {}
        # binaries present in this corpus: dotted module → entry relpath
        self.binaries: Dict[str, str] = {}
        for dotted in _BINARY_CONFIGS:
            for rel in _pkg_rel(dotted):
                if rel in self.by_rel:
                    self.binaries[dotted] = rel
                    break
        for m in ctx.modules:
            self._imports[m.relpath] = self._module_imports(m)

    # ------------------------------------------------------ import graph

    def _resolve(self, dotted: str) -> Optional[str]:
        for rel in _pkg_rel(dotted):
            if rel in self.by_rel:
                return rel
        return None

    def _module_imports(self, m: ModuleUnit) -> Set[str]:
        """Package-internal import edges, including function-body gated
        imports (ast.walk, not just module top level — the lazy-import
        idiom is exactly how binaries defer their heavy deps)."""
        out: Set[str] = set()
        # enclosing package parts — identical for x/y.py and
        # x/__init__.py (level-1 relative imports resolve to x.*)
        pkg_parts = m.relpath.split("/")[:-1]
        for node in ast.walk(m.tree):
            if isinstance(node, ast.Import):
                for alias in node.names:
                    if alias.name.startswith("dotaclient_tpu"):
                        rel = self._resolve(alias.name)
                        if rel:
                            out.add(rel)
            elif isinstance(node, ast.ImportFrom):
                base = node.module or ""
                if node.level:
                    anchor = pkg_parts[: len(pkg_parts) - (node.level - 1)]
                    base = ".".join(anchor + ([base] if base else []))
                if not base.startswith("dotaclient_tpu"):
                    continue
                rel = self._resolve(base)
                if rel:
                    out.add(rel)
                for alias in node.names:
                    sub = self._resolve(f"{base}.{alias.name}")
                    if sub:
                        out.add(sub)
        out.discard(m.relpath)
        return out

    def reachable(self, entry_rel: str) -> Set[str]:
        """Transitive import closure from an entrypoint, self included."""
        cached = self._reach_cache.get(entry_rel)
        if cached is None:
            seen = {entry_rel}
            frontier = [entry_rel]
            while frontier:
                rel = frontier.pop()
                for nxt in self._imports.get(rel, ()):
                    if nxt not in seen:
                        seen.add(nxt)
                        frontier.append(nxt)
            cached = self._reach_cache[entry_rel] = seen
        return cached

    # ------------------------------------------------------ served routes

    @staticmethod
    def _served_in(m: ModuleUnit) -> List[ServedRoute]:
        out: List[ServedRoute] = []
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else getattr(node.func, "id", "")
            )
            if fname != "MetricsHTTPServer":
                continue
            routes = {"/metrics", "/healthz"}
            for kw in node.keywords:
                val = kw.value
                none_const = isinstance(val, ast.Constant) and val.value is None
                if kw.arg in ("json_routes", "query_routes", "post_routes"):
                    if isinstance(val, ast.Dict):
                        for key in val.keys:
                            if (
                                isinstance(key, ast.Constant)
                                and isinstance(key.value, str)
                                and key.value.startswith("/")
                            ):
                                routes.add(key.value)
                elif kw.arg == "flight_provider" and not none_const:
                    routes.add("/debug/flight")
                elif kw.arg == "profile_handler" and not none_const:
                    routes.add("/profile")
            for route in sorted(routes):
                out.append(ServedRoute(route, m.relpath, node.lineno))
        return out

    def served_by(self, binary: str) -> Dict[str, ServedRoute]:
        """route → registration site, over the binary's reachable set."""
        cached = self._served_cache.get(binary)
        if cached is None:
            cached = {}
            entry = self.binaries.get(binary)
            if entry:
                for rel in sorted(self.reachable(entry)):
                    for sr in self._served_in(self.by_rel[rel]):
                        cached.setdefault(sr.route, sr)
            self._served_cache[binary] = cached
        return cached

    def served_union(self) -> Set[str]:
        """Every route served by any binary or any scripts/ driver's own
        surface (soak harnesses stand up fake tiers; their self-dialed
        routes are contracts too, just not any production binary's)."""
        cached = getattr(self, "_served_union", None)
        if cached is None:
            cached = set()
            for binary in self.binaries:
                cached.update(self.served_by(binary))
            for script in self.ctx.script_modules():
                cached.update(sr.route for sr in self._served_in(script))
            self._served_union = cached
        return cached

    def has_http_layer(self) -> bool:
        """False when the corpus contains no MetricsHTTPServer call at
        all (a synthetic lint tree with no wire/obs layer): the route
        rules skip rather than flag every consumer of a surface the
        corpus doesn't model."""
        return bool(self.served_union())

    # ----------------------------------------------------- emitted meters

    def emitted_by(self, binary: str) -> Tuple[Set[str], Set[str]]:
        """(exact literals, f-string heads) over the binary's reachable
        modules. Membership test for meter M: exact, or startswith a
        head (the ``out[f"fleet_ledger_{name}_…"]`` compose idiom)."""
        cached = self._emit_cache.get(binary)
        if cached is None:
            exact: Set[str] = set()
            heads: Set[str] = set()
            entry = self.binaries.get(binary)
            if entry:
                for rel in self.reachable(entry):
                    for node in ast.walk(self.by_rel[rel].tree):
                        if isinstance(node, ast.Constant) and isinstance(
                            node.value, str
                        ):
                            if _METER_RE.match(node.value):
                                exact.add(node.value)
                        elif isinstance(node, ast.JoinedStr) and node.values:
                            first = node.values[0]
                            if (
                                isinstance(first, ast.Constant)
                                and isinstance(first.value, str)
                                and _METER_HEAD_RE.match(first.value)
                            ):
                                heads.add(first.value)
            cached = self._emit_cache[binary] = (exact, heads)
        return cached

    def exports_meter(self, binary: str, meter: str) -> bool:
        exact, heads = self.emitted_by(binary)
        if meter in exact:
            return True
        return any(meter.startswith(h) for h in heads)

    # -------------------------------------------------- consumed routes

    def consumed_routes(self) -> List[ConsumedRoute]:
        out: List[ConsumedRoute] = []
        for m in list(self.ctx.modules) + self.ctx.script_modules():
            if m.relpath.startswith("dotaclient_tpu/analysis/"):
                continue  # the lint's own extraction patterns aren't edges
            out.extend(self._consumed_in(m))
        return out

    def _consumed_in(self, m: ModuleUnit) -> List[ConsumedRoute]:
        out: List[ConsumedRoute] = []
        claimed: Set[int] = set()
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            fname = (
                node.func.attr
                if isinstance(node.func, ast.Attribute)
                else getattr(node.func, "id", "")
            )
            if fname not in _URL_CALLS and fname not in _HELPER_CALLS:
                continue
            for arg in node.args:
                if isinstance(arg, ast.JoinedStr):
                    claimed.add(id(arg))
                    ref = self._route_of_joined(arg, m)
                    if ref:
                        out.append(ref)
                elif (
                    fname in _HELPER_CALLS
                    and isinstance(arg, ast.Constant)
                    and isinstance(arg.value, str)
                    and arg.value.startswith("/")
                ):
                    route = self._clean_route(arg.value)
                    if route:
                        # hint: endpoint-arg identifiers, else the
                        # enclosing class (LeagueClient._get("/match"))
                        idents = set()
                        for other in node.args:
                            if other is not arg:
                                idents |= _idents(other)
                        if isinstance(node.func, ast.Attribute):
                            idents |= _idents(node.func.value)
                        hint = _hint_of(idents) or _hint_of(
                            {m.qualname_at(node).split(".")[0].lower()}
                        )
                        out.append(
                            ConsumedRoute(
                                route, m.relpath, arg.lineno, hint,
                                m.qualname_at(node),
                            )
                        )
        # URL f-strings bound to a variable first (base = f"http://…";
        # urlopen(f"{base}/metrics") is caught above, the direct
        # url = f"http://{ep}/route" assignment here)
        for node in ast.walk(m.tree):
            if isinstance(node, ast.JoinedStr) and id(node) not in claimed:
                first = node.values[0] if node.values else None
                if (
                    isinstance(first, ast.Constant)
                    and isinstance(first.value, str)
                    and first.value.startswith(("http://", "https://"))
                ):
                    ref = self._route_of_joined(node, m)
                    if ref:
                        out.append(ref)
        return out

    @staticmethod
    def _clean_route(raw: str) -> Optional[str]:
        route = raw.split("?", 1)[0]
        if route in ("", "/") or not _ROUTE_RE.match(route):
            return None
        return route

    def _route_of_joined(self, j: ast.JoinedStr, m: ModuleUnit) -> Optional[ConsumedRoute]:
        """Constant route tail of a URL-shaped f-string: the last "/…"
        constant after the first formatted field (the host), or a "/…"
        constant head (helper-relative f"/snapshot?name={…}"). A tail
        that is itself dynamic (f"http://{ep}{path}") has no static
        route — the call-site constants cover those."""
        parts = j.values
        route_raw: Optional[str] = None
        first = parts[0] if parts else None
        if (
            isinstance(first, ast.Constant)
            and isinstance(first.value, str)
            and first.value.startswith("/")
        ):
            route_raw = first.value
        else:
            seen_field = False
            for part in parts:
                if isinstance(part, ast.FormattedValue):
                    seen_field = True
                elif (
                    seen_field
                    and isinstance(part, ast.Constant)
                    and isinstance(part.value, str)
                    and part.value.startswith("/")
                ):
                    route_raw = part.value
        if route_raw is None:
            return None
        route = self._clean_route(route_raw)
        if route is None:
            return None
        return ConsumedRoute(
            route, m.relpath, j.lineno, _hint_of(_idents(j)), m.qualname_at(j)
        )

    # ------------------------------------------------------ k8s surfaces

    def _manifests(self) -> List[Tuple[str, List[str]]]:
        cached = getattr(self, "_manifest_cache", None)
        if cached is None:
            cached = []
            k8s = self.ctx.k8s_dir
            if k8s and os.path.isdir(k8s):
                for name in sorted(os.listdir(k8s)):
                    if not name.endswith((".yaml", ".yml")):
                        continue
                    path = os.path.join(k8s, name)
                    rel = os.path.relpath(path, self.ctx.root).replace(os.sep, "/")
                    source = self.ctx.source_of(path)
                    if source is None:
                        continue
                    stripped = [
                        ln.split("#", 1)[0] for ln in source.splitlines()
                    ]
                    cached.append((rel, stripped))
            self._manifest_cache = cached
        return cached

    def _block_binaries(
        self, stripped: List[str]
    ) -> Tuple[List[Tuple[int, int, str]], Set[str]]:
        """([(start, end, binary)] for item blocks naming exactly one
        known binary, every known binary in the file). Lines are
        0-based inclusive, matching _item_blocks."""
        blocks = []
        file_mods: Set[str] = set()
        for b_start, b_end, _indent in _item_blocks(stripped):
            mods = set()
            for ln in stripped[b_start : b_end + 1]:
                mods.update(
                    mod for mod in _MODULE_RE.findall(ln) if mod in _BINARY_CONFIGS
                )
            file_mods |= mods
            if len(mods) == 1:
                blocks.append((b_start, b_end, next(iter(mods))))
        return blocks, file_mods

    def probe_routes(self) -> List[ProbeRoute]:
        """k8s liveness/readiness httpGet paths + prometheus.io/path
        scrape annotations, each attributed to the binary whose
        container block (probes) or manifest (annotations, when the
        file runs exactly one known binary) declares them."""
        out: List[ProbeRoute] = []
        for rel, stripped in self._manifests():
            blocks, file_mods = self._block_binaries(stripped)
            sole = next(iter(file_mods)) if len(file_mods) == 1 else None
            prev_nonblank = ""
            for i, ln in enumerate(stripped):
                route: Optional[str] = None
                flow = _HTTPGET_FLOW_RE.search(ln)
                if flow:
                    route = flow.group(1)
                elif ln.strip().startswith("path:") and prev_nonblank.strip().endswith(
                    "httpGet:"
                ):
                    route = ln.split(":", 1)[1].strip().strip('"')
                if route and route.startswith("/"):
                    binary = sole
                    for b_start, b_end, mod in blocks:
                        if b_start <= i <= b_end:
                            binary = mod  # innermost resolved block wins
                    if binary:
                        out.append(ProbeRoute(route, rel, i + 1, binary))
                else:
                    prom = _PROM_PATH_RE.search(ln)
                    if prom and sole and prom.group(1).startswith("/"):
                        out.append(ProbeRoute(prom.group(1), rel, i + 1, sole))
                if ln.strip():
                    prev_nonblank = ln
        return out

    def _manifest_flag_values(
        self, stripped: List[str]
    ) -> List[Tuple[str, str, int]]:
        """(flag-without-dashes, value, 1-based line-of-value) for every
        ``- "--flag"`` arg item, taking the inline ``=value`` or the
        next arg item as the value."""
        out = []
        i = 0
        while i < len(stripped):
            m = _ARG_ITEM_RE.match(stripped[i])
            if m:
                flag = m.group(1)[2:]
                if m.group(2) is not None:
                    out.append((flag, m.group(2), i + 1))
                else:
                    for j in range(i + 1, len(stripped)):
                        if not stripped[j].strip():
                            continue
                        vm = _VALUE_ITEM_RE.match(stripped[j])
                        if vm and not vm.group(1).startswith("--"):
                            out.append((flag, vm.group(1), j + 1))
                        break
            i += 1
        return out

    def clause_meters(self) -> List[ClauseMeter]:
        """Meter names the k8s manifests' --control.policy and
        --fleet.alerts clauses key decisions on. Scripts are excluded
        deliberately: the soak drivers watch harness-synthetic meters
        (their fake tiers export them); the manifests are the deploy
        surface of record."""
        out: List[ClauseMeter] = []
        for rel, stripped in self._manifests():
            for flag, value, line in self._manifest_flag_values(stripped):
                if not value.strip():
                    continue
                if flag == "control.policy":
                    for clause in value.split(";"):
                        head = clause.split(",", 1)[0]
                        tier, sep, meter = head.partition(":")
                        if not sep:
                            continue  # malformed — SVC003's finding
                        meter = meter.strip()
                        for suffix in AGG_SUFFIXES:
                            if meter.endswith(suffix):
                                meter = meter[: -len(suffix)]
                                break
                        if meter:
                            out.append(
                                ClauseMeter(
                                    meter, tier.strip(), rel, line, "control_policy"
                                )
                            )
                elif flag == "fleet.alerts":
                    for clause in value.split(";"):
                        meter = clause.split(",", 1)[0].strip()
                        if meter:
                            out.append(
                                ClauseMeter(meter, "fleetd", rel, line, "fleet_alerts")
                            )
        return out

    # -------------------------------------------------- grammar literals

    def grammar_literals(self) -> List[GrammarLiteral]:
        """Every config-grammar string the fleet would parse at boot:
        manifest flag values, soak-driver module constants (POLICY/
        ALERTS/…), argparse defaults, and subprocess-argv flag pairs."""
        out: List[GrammarLiteral] = []
        for rel, stripped in self._manifests():
            for flag, value, line in self._manifest_flag_values(stripped):
                grammar = GRAMMAR_FLAGS.get(flag)
                if grammar and value.strip():
                    out.append(GrammarLiteral(grammar, value, rel, line))
        for script in self.ctx.script_modules():
            for node in ast.walk(script.tree):
                if isinstance(node, ast.Assign):
                    if not (
                        isinstance(node.value, ast.Constant)
                        and isinstance(node.value.value, str)
                        and node.value.value.strip()
                    ):
                        continue
                    for tgt in node.targets:
                        grammar = GRAMMAR_CONSTS.get(getattr(tgt, "id", ""))
                        if grammar:
                            out.append(
                                GrammarLiteral(
                                    grammar, node.value.value,
                                    script.relpath, node.lineno,
                                )
                            )
                elif isinstance(node, ast.Call):
                    fname = (
                        node.func.attr
                        if isinstance(node.func, ast.Attribute)
                        else getattr(node.func, "id", "")
                    )
                    if fname == "add_argument":
                        flag_name = ""
                        for arg in node.args:
                            if (
                                isinstance(arg, ast.Constant)
                                and isinstance(arg.value, str)
                                and arg.value.startswith("--")
                            ):
                                flag_name = arg.value[2:]
                        grammar = GRAMMAR_FLAGS.get(flag_name)
                        if grammar:
                            for kw in node.keywords:
                                if (
                                    kw.arg == "default"
                                    and isinstance(kw.value, ast.Constant)
                                    and isinstance(kw.value.value, str)
                                    and kw.value.value.strip()
                                ):
                                    out.append(
                                        GrammarLiteral(
                                            grammar, kw.value.value,
                                            script.relpath, kw.value.lineno,
                                        )
                                    )
                elif isinstance(node, ast.List):
                    elts = node.elts
                    for i, elt in enumerate(elts[:-1]):
                        if not (
                            isinstance(elt, ast.Constant)
                            and isinstance(elt.value, str)
                            and elt.value.startswith("--")
                        ):
                            continue
                        grammar = GRAMMAR_FLAGS.get(elt.value[2:])
                        nxt = elts[i + 1]
                        if (
                            grammar
                            and isinstance(nxt, ast.Constant)
                            and isinstance(nxt.value, str)
                            and nxt.value.strip()
                        ):
                            out.append(
                                GrammarLiteral(
                                    grammar, nxt.value, script.relpath, nxt.lineno
                                )
                            )
        return out

    # --------------------------------------------------- ledger identities

    def ledger_terms(self) -> Tuple[List[LedgerRef], Optional[str]]:
        """((ledger, meter, tier) terms of obs/fleet.py LEDGERS, error).
        No fleet.py in the corpus → ([], None): nothing to pin. A
        fleet.py whose LEDGERS can't be extracted → loud error — the
        WIRE001 discipline: an auditor the lint can no longer read is
        itself drift, never a silent skip."""
        m = self.by_rel.get("dotaclient_tpu/obs/fleet.py")
        if m is None:
            return [], None
        assign = None
        for node in ast.walk(m.tree):
            targets = []
            if isinstance(node, ast.Assign):
                targets = node.targets
            elif isinstance(node, ast.AnnAssign) and node.value is not None:
                targets = [node.target]
            if any(getattr(t, "id", "") == "LEDGERS" for t in targets):
                assign = node
                break
        if assign is None:
            return [], "obs/fleet.py defines no LEDGERS assignment"
        value = assign.value
        if not isinstance(value, (ast.Tuple, ast.List)):
            return [], "LEDGERS is not a literal tuple of LedgerSpec(...) calls"
        terms: List[LedgerRef] = []
        for spec in value.elts:
            if not (
                isinstance(spec, ast.Call)
                and getattr(spec.func, "id", getattr(spec.func, "attr", ""))
                == "LedgerSpec"
            ):
                return [], "LEDGERS entry is not a LedgerSpec(...) call"
            name = ""
            term_nodes: List[ast.expr] = []
            for kw in spec.keywords:
                if kw.arg == "name" and isinstance(kw.value, ast.Constant):
                    name = str(kw.value.value)
                elif kw.arg == "terms" and isinstance(
                    kw.value, (ast.Tuple, ast.List)
                ):
                    term_nodes = kw.value.elts
            if spec.args and isinstance(spec.args[0], ast.Constant):
                name = str(spec.args[0].value)
            if not name or not term_nodes:
                return [], "LedgerSpec without a literal name= and terms= tuple"
            for tn in term_nodes:
                if not (
                    isinstance(tn, ast.Call)
                    and getattr(tn.func, "id", getattr(tn.func, "attr", ""))
                    == "LedgerTerm"
                ):
                    return [], f"ledger {name!r} has a non-LedgerTerm term"
                fields: Dict[str, ast.expr] = {}
                for pos, arg in enumerate(tn.args):
                    fields[("meter", "tier", "sign")[pos] if pos < 3 else str(pos)] = arg
                for kw in tn.keywords:
                    if kw.arg:
                        fields[kw.arg] = kw.value
                meter = fields.get("meter")
                tier = fields.get("tier")
                if not (
                    isinstance(meter, ast.Constant)
                    and isinstance(meter.value, str)
                    and isinstance(tier, ast.Constant)
                    and isinstance(tier.value, str)
                ):
                    return [], f"ledger {name!r} term without literal meter/tier"
                terms.append(LedgerRef(name, meter.value, tier.value, tn.lineno))
        if not terms:
            return [], "LEDGERS extracted to zero terms"
        return terms, None


def _idents(node: ast.AST) -> Set[str]:
    """Lowercased identifier words inside an expression — the hint text
    for endpoint-variable → binary resolution."""
    out: Set[str] = set()
    for sub in ast.walk(node):
        if isinstance(sub, ast.Name):
            out.add(sub.id.lower())
        elif isinstance(sub, ast.Attribute):
            out.add(sub.attr.lower())
    return out


def _hint_of(idents: Set[str]) -> Optional[str]:
    text = " ".join(sorted(idents))
    for key, binary in _HINTS:
        if key in text:
            return binary
    return None


def fleet_graph(ctx: RepoContext) -> FleetGraph:
    """The per-lint-run FleetGraph, built once and cached on the ctx
    (the _registry_names idiom — four SVC rules share one extraction)."""
    cached = getattr(ctx, "_fleet_graph_cache", None)
    if cached is None:
        cached = ctx._fleet_graph_cache = FleetGraph(ctx)
    return cached
