"""SVC003 subprocess: parse config-grammar literals with the REAL
parsers, import-isolated from the lint.

The graftlint process must never import the package (the no-JAX proof
in tests/test_graftlint.py asserts it) — but SVC003's whole point is
that a grammar literal must parse with the parser that will read it at
boot, not with a lint-side reimplementation that could drift. So the
rule ships each literal here, in a fresh interpreter, and this runner
imports exactly the four stdlib-only parser modules. It also reports
any jax/jaxlib module that sneaks into sys.modules: a parser module
growing an accelerator import is itself a contract break (the control/
league/fleet tiers are documented jax-free), surfaced as a finding
rather than a mysterious cold-start regression. numpy is deliberately
NOT banned here — importing league.policy runs league/__init__, whose
registry is numpy-for-snapshot-trees by contract; the LINT process
itself still bans both (tests/test_graftlint.py's subprocess proof).

stdin:  {"root": <repo root>, "items": [{"grammar","text","path","line"}]}
stdout: {"failures": [{"path","line","grammar","error"}],
         "banned_imports": ["jax", ...]}

Grammar ids → parsers:
    control_policy → dotaclient_tpu.control.policy.parse_policy
    fleet_alerts   → dotaclient_tpu.obs.fleet.parse_alerts
    league_policy  → dotaclient_tpu.league.policy.parse_match_policy
    chaos_spec     → dotaclient_tpu.chaos.schedule.FaultSchedule.parse
"""

from __future__ import annotations

import json
import sys


def _parsers():
    from dotaclient_tpu.chaos.schedule import FaultSchedule
    from dotaclient_tpu.control.policy import parse_policy
    from dotaclient_tpu.league.policy import parse_match_policy
    from dotaclient_tpu.obs.fleet import parse_alerts

    return {
        "control_policy": parse_policy,
        "fleet_alerts": parse_alerts,
        "league_policy": parse_match_policy,
        "chaos_spec": lambda spec: FaultSchedule.parse(spec, seed=0),
    }


def main() -> int:
    payload = json.load(sys.stdin)
    sys.path.insert(0, payload["root"])
    failures = []
    try:
        parsers = _parsers()
    except Exception as e:  # import failure IS the finding
        json.dump(
            {
                "failures": [
                    {
                        "path": item["path"],
                        "line": item["line"],
                        "grammar": item["grammar"],
                        "error": f"parser import failed: {e!r}",
                    }
                    for item in payload["items"]
                ],
                "banned_imports": sorted(
                    {"jax", "jaxlib"} & set(sys.modules)
                ),
            },
            sys.stdout,
        )
        return 0
    for item in payload["items"]:
        parser = parsers.get(item["grammar"])
        if parser is None:
            continue
        try:
            parser(item["text"])
        except Exception as e:
            failures.append(
                {
                    "path": item["path"],
                    "line": item["line"],
                    "grammar": item["grammar"],
                    "error": f"{type(e).__name__}: {e}",
                }
            )
    json.dump(
        {
            "failures": failures,
            "banned_imports": sorted({"jax", "jaxlib"} & set(sys.modules)),
        },
        sys.stdout,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
