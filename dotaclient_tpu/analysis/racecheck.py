"""Racecheck: a vector-clock happens-before race sanitizer for the
repo's threads — the dynamic half of graftcheck.

lockcheck (the PR-4 substrate) sees lock-ORDER hazards; it cannot see a
plain data race: two threads writing one attribute with no
happens-before edge between them at all. This module detects exactly
that, mechanically, from one test run:

- **Happens-before tracking.** Every thread carries a vector clock.
  Repo-created sync objects carry shadow clocks and convey HB edges the
  way the runtime actually synchronizes: ``Lock``/``RLock`` release →
  next acquire; ``Condition`` rides its lock (wait = release +
  reacquire); ``Event.set`` → a ``wait()``/``is_set()`` that observes
  it; ``queue.Queue`` put → the get that receives that item (FIFO
  shadow), plus ``task_done`` → ``join``; ``Thread.start`` → the
  child's first step, child's last step → ``join``. The scope
  discipline is lockcheck's: only objects whose creation frame lives in
  this repo are instrumented — stdlib/JAX internals stay native.
- **Attribute-write tracing.** Opted-in instances (``monitor.watch(obj)``
  — the staging consumer/assembler/pack pool, TransferRing/RingSlot,
  CheckpointWorker, WeightPublisher, ``_ServeBatcher``,
  RemotePolicyClient are the intended set) get their class
  ``__setattr__`` wrapped; every attribute REBIND is checked
  FastTrack-style against the last write's epoch. Two writes to one
  attribute with neither ordered before the other is a race report
  carrying both sites. Writes only, by design: the repo's sanctioned
  read patterns (single GIL-atomic reads of rebound references) are
  exactly the ones a read-tracer would drown in, and the write-write
  case is the one that corrupts state.
- **Reasoned suppressions.** ``monitor.suppress("Class.attr", reason)``
  files matching reports under ``monitor.suppressed`` — an empty reason
  raises, the graftlint GRAFT000 discipline. The nightly soak asserts
  ``monitor.races == []`` with every suppression justified.

Production never imports this module; tests opt in via the ``racecheck``
fixture (tests/conftest.py) which installs, yields, uninstalls. One
instrumentation substrate may own ``threading`` at a time — racecheck
and lockcheck fixtures are mutually exclusive within a test (install
refuses a patched ``threading.Lock``). Pure stdlib: importing this
module never imports JAX/numpy.
"""

from __future__ import annotations

import collections
import os
import queue as _queue_mod
import sys
import threading
import weakref
from typing import Dict, List, Optional, Tuple

_REPO_ROOT = os.path.dirname(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
)

# Captured at import time, before any install() can patch them: the
# monitor's own state lock must never be instrumented, and uninstall()
# must restore exactly these.
_NATIVE_LOCK = threading.Lock
_NATIVE_RLOCK = threading.RLock
_NATIVE_CONDITION = threading.Condition
_NATIVE_EVENT = threading.Event
_NATIVE_THREAD = threading.Thread
_NATIVE_QUEUE = _queue_mod.Queue


def _join(dst: Dict[int, int], src: Optional[Dict[int, int]]) -> None:
    if not src:
        return
    for k, v in src.items():
        if dst.get(k, 0) < v:
            dst[k] = v


def _leq_epoch(tid: int, clock: int, vc: Dict[int, int]) -> bool:
    """epoch (tid, clock) happened-before (or equals) vc."""
    return vc.get(tid, 0) >= clock


class RaceMonitor:
    """Registry + vector-clock state shared by every instrumented object."""

    def __init__(self, scope_root: Optional[str] = _REPO_ROOT):
        self.scope_root = scope_root
        self._state_lock = _NATIVE_LOCK()
        # uid lives in a threading.local, NOT on the thread object and
        # NEVER via threading.current_thread(): for an unregistered
        # thread (mid-bootstrap, foreign) current_thread() constructs a
        # _DummyThread whose __init__ touches an Event — under
        # scope_root=None that Event is itself instrumented and the
        # bookkeeping re-enters unboundedly (the lockcheck _thread_name
        # lesson). A thread-local also survives OS ident recycling: a
        # new thread on a reused ident gets a fresh slot, never a dead
        # thread's clock.
        self._tls = threading.local()
        # thread uid → vector clock. uids are monitor-assigned (thread
        # idents get recycled by the OS; a reused ident would inherit a
        # dead thread's clock and mint false HB edges).
        self._vcs: Dict[int, Dict[int, int]] = {}
        self._uid_counter = 0
        # sync-object shadow clocks, keyed by the wrapper's own id —
        # wrappers hold the key alive for their lifetime.
        self._sync_vc: Dict[int, Dict[int, int]] = {}
        # (id(obj), attr) → (writer uid, writer clock, thread name, site)
        self._last_write: Dict[Tuple[int, str], Tuple[int, int, str, str]] = {}
        self.races: List[Dict] = []
        self.suppressed: List[Dict] = []
        self._suppressions: Dict[str, str] = {}  # "Class.attr" → reason
        self._race_keys: set = set()  # dedupe: one report per (cls, attr, pair)
        self.writes_traced = 0
        self._watched: "weakref.WeakSet" = weakref.WeakSet()
        self._ignore_attrs: Dict[type, set] = {}
        self._patched_setattr: Dict[type, object] = {}
        self._installed = False
        # every wrapper this monitor minted — uninstall() makes them
        # inert (the lockcheck contract: objects that outlive the test
        # in module/registry state must stop feeding a dead monitor).
        self._made: "weakref.WeakSet" = weakref.WeakSet()
        # id-recycling defense, the sync-object/watched-instance analog
        # of the thread-uid rule above: _sync_vc and _last_write key by
        # id(), and CPython reuses addresses after GC — a new lock at a
        # dead lock's address would inherit its clock and mint false HB
        # edges that MASK real races. weakref finalizers enqueue dead
        # ids here (list.append is GIL-atomic; the finalizer must NOT
        # take _state_lock — GC can fire inside a locked region and
        # deadlock on the non-reentrant lock), and every monitored op
        # drains the queue under the lock BEFORE touching the tables.
        # An address can only be reused after its finalizer ran, so the
        # stale entry is always gone before a recycled id is consulted.
        self._dead_ids: List[int] = []

    # ------------------------------------------------------------- clocks

    def _uid(self) -> int:
        u = getattr(self._tls, "uid", None)
        if u is None:
            with self._state_lock:
                self._uid_counter += 1
                u = self._uid_counter
            self._tls.uid = u  # each thread writes only its own slot
        return u

    @staticmethod
    def _thread_name() -> str:
        """Current thread's name WITHOUT threading.current_thread() —
        see the _tls comment in __init__ for why."""
        ident = threading.get_ident()
        t = getattr(threading, "_active", {}).get(ident)
        return t.name if t is not None else f"thread-{ident}"

    def _vc(self, uid: int) -> Dict[int, int]:
        """Caller holds _state_lock."""
        vc = self._vcs.get(uid)
        if vc is None:
            vc = self._vcs[uid] = {uid: 1}
        return vc

    def _snapshot_and_tick(self, uid: int) -> Dict[int, int]:
        """Caller holds _state_lock: copy the thread's clock, then
        advance it — the release/send half of every HB edge."""
        vc = self._vc(uid)
        snap = dict(vc)
        vc[uid] = vc.get(uid, 0) + 1
        return snap

    # ----------------------------------------------------- HB primitives

    def _on_collected(self, oid: int) -> None:
        """GC finalizer: queue the dead object's id for pruning. Runs
        at collection time — never takes _state_lock (see _dead_ids)."""
        self._dead_ids.append(oid)

    def _prune_dead_locked(self) -> None:
        """Caller holds _state_lock: drop table entries whose object
        died, so a recycled address starts from a clean slate."""
        while self._dead_ids:
            oid = self._dead_ids.pop()
            self._sync_vc.pop(oid, None)
            for key in [k for k in self._last_write if k[0] == oid]:
                del self._last_write[key]

    def hb_send(self, channel_id: int) -> None:
        """This thread's clock flows into `channel_id` (lock release,
        Event.set, task_done)."""
        uid = self._uid()
        with self._state_lock:
            self._prune_dead_locked()
            slot = self._sync_vc.setdefault(channel_id, {})
            _join(slot, self._snapshot_and_tick(uid))

    def hb_recv(self, channel_id: int) -> None:
        """`channel_id`'s clock flows into this thread (lock acquire,
        observed Event, queue join)."""
        uid = self._uid()
        with self._state_lock:
            self._prune_dead_locked()
            _join(self._vc(uid), self._sync_vc.get(channel_id))

    def hb_reset(self, channel_id: int) -> None:
        """Drop `channel_id`'s shadow clock (Event.clear): a wait that
        observes a LATER set must join only post-clear setters —
        accumulated pre-clear clocks would order the observer after
        threads it never synchronized with, masking real races."""
        with self._state_lock:
            self._sync_vc.pop(channel_id, None)

    def hb_transfer_out(self, fifo: "collections.deque") -> None:
        """Queue put: the putter's clock rides the item (FIFO shadow)."""
        uid = self._uid()
        with self._state_lock:
            fifo.append(self._snapshot_and_tick(uid))

    def hb_transfer_in(self, fifo: "collections.deque") -> None:
        """Queue get: join the clock that rode the received item."""
        uid = self._uid()
        with self._state_lock:
            if fifo:
                _join(self._vc(uid), fifo.popleft())

    # -------------------------------------------------------- write check

    def _site(self) -> str:
        frame = sys._getframe(2)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return "<unknown>"
        path = frame.f_code.co_filename
        if self.scope_root and path.startswith(self.scope_root + os.sep):
            path = os.path.relpath(path, self.scope_root)
        return f"{path}:{frame.f_lineno}"

    def record_write(self, obj, cls_name: str, attr: str) -> None:
        site = self._site()
        uid = self._uid()
        tname = self._thread_name()
        key = (id(obj), attr)
        with self._state_lock:
            self._prune_dead_locked()
            self.writes_traced += 1
            vc = self._vc(uid)
            prev = self._last_write.get(key)
            if prev is not None:
                p_uid, p_clock, p_tname, p_site = prev
                if p_uid != uid and not _leq_epoch(p_uid, p_clock, vc):
                    label = f"{cls_name}.{attr}"
                    # unordered site pair: the same race observed in both
                    # directions by a hot loop is ONE report, not two
                    race_key = (label, frozenset((p_site, site)))
                    if race_key not in self._race_keys:
                        self._race_keys.add(race_key)
                        report = {
                            "attr": label,
                            "first_thread": p_tname,
                            "first_site": p_site,
                            "second_thread": tname,
                            "second_site": site,
                        }
                        reason = self._suppressions.get(label)
                        if reason is not None:
                            report["reason"] = reason
                            self.suppressed.append(report)
                        else:
                            self.races.append(report)
            self._last_write[key] = (uid, vc.get(uid, 0), tname, site)

    # ------------------------------------------------------------ opt-in

    def watch(self, obj, ignore: Tuple[str, ...] = ()) -> None:
        """Trace attribute rebinds on `obj`. The class __setattr__ is
        wrapped once per class; only watched INSTANCES pay the check.
        `ignore` names attrs excluded for this object's class (pure
        construction-time scratch, etc.)."""
        cls = type(obj)
        self._ignore_attrs.setdefault(cls, set()).update(ignore)
        if cls not in self._patched_setattr:
            orig = cls.__setattr__

            def traced_setattr(inst, name, value, _orig=orig, _cls=cls):
                m = _ACTIVE_MONITOR
                if (
                    m is not None
                    and inst in m._watched
                    and name not in m._ignore_attrs.get(_cls, ())
                ):
                    m.record_write(inst, _cls.__name__, name)
                _orig(inst, name, value)

            cls.__setattr__ = traced_setattr
            self._patched_setattr[cls] = orig
        self._watched.add(obj)
        weakref.finalize(obj, self._on_collected, id(obj))

    def suppress(self, attr_label: str, reason: str) -> None:
        """Suppress races on "Class.attr" WITH a reason — the graftlint
        escape-hatch discipline: silence must always be justified."""
        if not reason or not reason.strip():
            raise ValueError(
                f"racecheck suppression for {attr_label!r} needs a non-empty "
                f"reason — silence must always be justified"
            )
        self._suppressions[attr_label] = reason.strip()

    # ----------------------------------------------------------- factories

    def _creation_in_scope(self) -> bool:
        frame = sys._getframe(2)
        while frame is not None and frame.f_code.co_filename == __file__:
            frame = frame.f_back
        if frame is None:
            return False
        path = frame.f_code.co_filename
        if self.scope_root is None:
            return True
        root = self.scope_root.rstrip(os.sep)
        if path != root and not path.startswith(root + os.sep):
            return False
        return "site-packages" not in path.split(os.sep)

    def _mint(self, obj):
        self._made.add(obj)
        weakref.finalize(obj, self._on_collected, id(obj))
        return obj

    def make_lock(self):
        if not self._creation_in_scope():
            return _NATIVE_LOCK()
        return self._mint(_HBLock(self, _NATIVE_LOCK()))

    def make_rlock(self):
        if not self._creation_in_scope():
            return _NATIVE_RLOCK()
        return self._mint(_HBLock(self, _NATIVE_RLOCK()))

    def make_condition(self, lock=None):
        # Same rationale as lockcheck.make_condition: a default-lock
        # Condition builds its RLock inside threading.py (out of scope),
        # so build the instrumented backing lock HERE.
        if lock is None and self._creation_in_scope():
            lock = self._mint(_HBLock(self, _NATIVE_RLOCK()))
        return _NATIVE_CONDITION(lock) if lock is not None else _NATIVE_CONDITION()

    def make_event(self):
        if not self._creation_in_scope():
            return _NATIVE_EVENT()
        return self._mint(_HBEvent(self, _NATIVE_EVENT()))

    def make_queue(self, maxsize: int = 0):
        if not self._creation_in_scope():
            return _NATIVE_QUEUE(maxsize)
        return self._mint(_HBQueue(self, maxsize))

    def make_thread(self, *args, **kwargs):
        if not self._creation_in_scope():
            return _NATIVE_THREAD(*args, **kwargs)
        return self._mint(_HBThread(self, *args, **kwargs))

    # ----------------------------------------------------------- lifecycle

    def install(self) -> "RaceMonitor":
        global _ACTIVE_MONITOR
        if self._installed:
            return self
        if threading.Lock is not _NATIVE_LOCK:
            raise RuntimeError(
                "another instrumentation (racecheck or lockcheck) already "
                "owns threading — the fixtures are mutually exclusive"
            )
        self._installed = True
        _ACTIVE_MONITOR = self
        threading.Lock = self.make_lock  # type: ignore[assignment]
        threading.RLock = self.make_rlock  # type: ignore[assignment]
        threading.Condition = self.make_condition  # type: ignore[assignment]
        threading.Event = self.make_event  # type: ignore[assignment]
        threading.Thread = self.make_thread  # type: ignore[assignment]
        _queue_mod.Queue = self.make_queue  # type: ignore[assignment]
        return self

    def uninstall(self) -> None:
        global _ACTIVE_MONITOR
        if not self._installed:
            return
        self._installed = False
        _ACTIVE_MONITOR = None
        threading.Lock = _NATIVE_LOCK  # type: ignore[assignment]
        threading.RLock = _NATIVE_RLOCK  # type: ignore[assignment]
        threading.Condition = _NATIVE_CONDITION  # type: ignore[assignment]
        threading.Event = _NATIVE_EVENT  # type: ignore[assignment]
        threading.Thread = _NATIVE_THREAD  # type: ignore[assignment]
        _queue_mod.Queue = _NATIVE_QUEUE  # type: ignore[assignment]
        # restore every patched __setattr__: watched instances that
        # outlive the test must stop paying the trace into a dead monitor
        for cls, orig in self._patched_setattr.items():
            cls.__setattr__ = orig
        self._patched_setattr.clear()
        # inert every wrapper we minted: sync objects that outlive the
        # test in module/registry state keep working as the wrapped
        # native with no bookkeeping (the lockcheck uninstall contract)
        for obj in list(self._made):
            obj._monitor = None

    def __enter__(self) -> "RaceMonitor":
        return self.install()

    def __exit__(self, *exc) -> None:
        self.uninstall()

    def report(self) -> Dict:
        with self._state_lock:
            return {
                "writes_traced": self.writes_traced,
                "threads": len(self._vcs),
                "races": list(self.races),
                "suppressed": len(self.suppressed),
            }


# The one active monitor (install() refuses nesting). Module-global so
# the per-class traced __setattr__ closures go inert on uninstall even
# when an instance outlives its test.
_ACTIVE_MONITOR: Optional[RaceMonitor] = None


class _HBLock:
    """Duck-typed Lock/RLock conveying happens-before: release sends this
    thread's clock into the lock's shadow, acquire joins it. Condition
    protocol implemented (wait = full release + reacquire) so waits
    convey the same edge."""

    def __init__(self, monitor: RaceMonitor, real):
        self._monitor: Optional[RaceMonitor] = monitor
        self._real = real

    def acquire(self, blocking: bool = True, timeout: float = -1) -> bool:
        ok = self._real.acquire(blocking, timeout)
        if ok and self._monitor is not None:
            self._monitor.hb_recv(id(self))
        return ok

    def release(self) -> None:
        if self._monitor is not None:
            self._monitor.hb_send(id(self))
        self._real.release()

    def locked(self) -> bool:
        return self._real.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc) -> None:
        self.release()

    # Condition protocol (threading.Condition drives these on its lock)
    def _release_save(self):
        if self._monitor is not None:
            self._monitor.hb_send(id(self))
        if hasattr(self._real, "_release_save"):
            return self._real._release_save()
        self._real.release()
        return None

    def _acquire_restore(self, saved) -> None:
        if saved is not None and hasattr(self._real, "_acquire_restore"):
            self._real._acquire_restore(saved)
        else:
            self._real.acquire()
        if self._monitor is not None:
            self._monitor.hb_recv(id(self))

    def _is_owned(self) -> bool:
        if hasattr(self._real, "_is_owned"):
            return self._real._is_owned()
        if self._real.acquire(False):
            self._real.release()
            return False
        return True

    def __getattr__(self, name):
        return getattr(self._real, name)


class _HBEvent:
    """threading.Event conveying happens-before: set() publishes the
    setter's clock; a wait() or is_set() that OBSERVES the set joins it
    — the flag-handshake HB edge the THR rules assume."""

    def __init__(self, monitor: RaceMonitor, real):
        self._monitor: Optional[RaceMonitor] = monitor
        self._real = real

    def set(self) -> None:
        if self._monitor is not None:
            self._monitor.hb_send(id(self))
        self._real.set()

    def clear(self) -> None:
        if self._monitor is not None:
            self._monitor.hb_reset(id(self))
        self._real.clear()

    def is_set(self) -> bool:
        v = self._real.is_set()
        if v and self._monitor is not None:
            self._monitor.hb_recv(id(self))
        return v

    def wait(self, timeout: Optional[float] = None) -> bool:
        v = self._real.wait(timeout)
        if v and self._monitor is not None:
            self._monitor.hb_recv(id(self))
        return v

    def __getattr__(self, name):
        return getattr(self._real, name)


class _HBQueue(_NATIVE_QUEUE):
    """queue.Queue conveying happens-before per ITEM: the putter's clock
    rides a FIFO shadow and joins into whichever thread receives that
    item. ``task_done``→``join`` conveys the completion edge. The
    shadow ops run inside ``_put``/``_get`` — under the queue's own
    mutex, so shadow order is exactly item order."""

    def __init__(self, monitor: RaceMonitor, maxsize: int = 0):
        self._monitor: Optional[RaceMonitor] = monitor
        self._hb_fifo: "collections.deque" = collections.deque()
        super().__init__(maxsize)

    def _put(self, item) -> None:
        if self._monitor is not None:
            self._monitor.hb_transfer_out(self._hb_fifo)
        super()._put(item)

    def _get(self):
        if self._monitor is not None:
            self._monitor.hb_transfer_in(self._hb_fifo)
        return super()._get()

    def task_done(self) -> None:
        if self._monitor is not None:
            self._monitor.hb_send(id(self))
        super().task_done()

    def join(self) -> None:
        super().join()
        if self._monitor is not None:
            self._monitor.hb_recv(id(self))


class _HBThread(_NATIVE_THREAD):
    """threading.Thread conveying fork/join happens-before: start()
    snapshots the parent's clock for the child's first step; join()
    (and is_alive() observing death) joins the child's final clock."""

    def __init__(self, monitor: RaceMonitor, *args, **kwargs):
        self._monitor: Optional[RaceMonitor] = monitor
        self._hb_parent: Optional[Dict[int, int]] = None
        self._hb_final: Optional[Dict[int, int]] = None
        super().__init__(*args, **kwargs)

    def start(self) -> None:
        m = self._monitor
        if m is not None:
            uid = m._uid()
            with m._state_lock:
                self._hb_parent = m._snapshot_and_tick(uid)
        super().start()

    def run(self) -> None:
        m = self._monitor
        if m is not None:
            uid = m._uid()
            with m._state_lock:
                _join(m._vc(uid), self._hb_parent)
        try:
            super().run()
        finally:
            if m is not None:
                uid = m._uid()
                with m._state_lock:
                    self._hb_final = dict(m._vc(uid))

    def join(self, timeout: Optional[float] = None) -> None:
        super().join(timeout)
        m = self._monitor
        if m is not None and not self.is_alive() and self._hb_final is not None:
            uid = m._uid()
            with m._state_lock:
                _join(m._vc(uid), self._hb_final)
