"""Graftlint: repo-native static analysis for the hazards this codebase
actually ships — thread-safety discipline around the seven daemon
threads, JAX hot-path recompile/host-sync hazards, and observability
contract drift.

Three rule families (see the sibling modules for the full rule docs):

- THR (thr_rules.py)  — classes that spawn a ``threading.Thread`` must
  guard worker-written attributes read from public methods with the
  instance lock, or read them as a single atomic rebound reference (the
  MetricsLogger ``_latest_rec`` pattern PR 3's review converged on);
  plus cross-module lock-acquisition-order consistency.
- JAX (jax_rules.py)  — inside jit/shard_map regions: host syncs
  (``.item()``, ``float()`` on tracers, ``np.asarray``, ``device_get``,
  ``print``), tracer-dependent Python branches, unstable static args —
  the static complement to the RecompileSentinel's
  ``compute_recompiles_total == 0`` runtime invariant.
- OBS (obs_rules.py)  — scalar names logged to MetricsLogger must exist
  in ``obs/registry.py``; ``--flags`` in ``k8s/*.yaml`` must exist in
  ``config.py`` (or the broker argparse); defined flags must be consumed
  somewhere in the package.

Runtime counterpart: ``lockcheck.py`` — an instrumented
``threading.Lock`` that records per-thread acquisition order and
detects lock-order inversions and over-held locks. Enabled by the
``lockcheck`` fixture in tests; nothing imports it in production.

Everything here is pure stdlib + ``ast`` — linting the package never
imports the package (and never imports JAX), so the tier-1 lint test
costs ~a second of wall clock. Entry point: ``scripts/lint_graft.py``.
"""

from __future__ import annotations

from dotaclient_tpu.analysis.core import (
    Finding,
    LintReport,
    lint_repo,
    load_baseline,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintReport",
    "lint_repo",
    "load_baseline",
    "write_baseline",
]
