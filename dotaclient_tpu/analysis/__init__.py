"""Graftcheck: repo-native static analysis + dynamic sanitizers for the
hazards this codebase actually ships — thread-safety discipline around
the daemon threads, JAX hot-path recompile/host-sync hazards,
observability contract drift, and (since the graftcheck PR) the
concurrency protocols the parallel host feed and the serve tier live
by.

Static rule families (see the sibling modules for the full rule docs):

- THR (thr_rules.py)  — classes that spawn a ``threading.Thread`` must
  guard worker-written attributes read from public methods with the
  instance lock, or read them as a single atomic rebound reference (the
  MetricsLogger ``_latest_rec`` pattern PR 3's review converged on);
  plus cross-module lock-acquisition-order consistency.
- JAX (jax_rules.py)  — inside jit/shard_map regions: host syncs
  (``.item()``, ``float()`` on tracers, ``np.asarray``, ``device_get``,
  ``print``), tracer-dependent Python branches, unstable static args —
  the static complement to the RecompileSentinel's
  ``compute_recompiles_total == 0`` runtime invariant.
- OBS (obs_rules.py)  — scalar names logged to MetricsLogger must exist
  in ``obs/registry.py``; ``--flags`` in ``k8s/*.yaml`` AND in the
  ``scripts/`` bench/soak drivers' subprocess argv lists must exist in
  the spawned binary's namespace; defined flags must be consumed
  somewhere in the package.
- LIF/WIRE (lif_rules.py) — TransferRing lease lifecycle (released or
  returned on every path, never before the H2D retire fence),
  drained()-station reachability (the PR-7 zero-loss drain contract),
  and WIRE001: the DTR wire layout extracted from BOTH
  transport/serialize.py (ast) and native/packer.cc (structured regex)
  into one spec table, failing on any drift.

Runtime counterparts (test-fixture-enabled only, production-inert):

- ``lockcheck.py``  — instrumented ``threading.Lock`` recording
  per-thread acquisition order: lock-order inversions + over-held locks.
- ``racecheck.py``  — vector-clock happens-before race sanitizer:
  repo-created locks/conditions/events/queues/threads convey HB edges,
  opted-in instances get attribute-write tracing, write-write pairs
  with no HB ordering are race reports (reasoned suppressions only).
- ``schedcheck.py`` — deterministic schedule exploration: the ring-slot
  lifecycle, drained()-station, checkpoint-coalescing, and serve
  hot-swap protocols as explicit models, every bounded interleaving
  exhausted, with mutants re-introducing the shipped bug classes.

The lint path is pure stdlib + ``ast`` — linting the package never
imports the package (and never imports JAX), so the tier-1 lint test
costs ~a second of wall clock. Entry point: ``scripts/lint_graft.py``.
"""

from __future__ import annotations

from dotaclient_tpu.analysis.core import (
    Finding,
    LintReport,
    lint_repo,
    load_baseline,
    write_baseline,
)

__all__ = [
    "Finding",
    "LintReport",
    "lint_repo",
    "load_baseline",
    "write_baseline",
]
