"""THR rules: thread-safety discipline for thread-spawning classes.

The repo's concurrency model is deliberately narrow: a class owns its
daemon thread(s), worker methods write instance attributes, and public
methods on other threads read them. PR 3's review cycle was spent
repairing exactly the failures this invites (torn multi-read state,
stale-window double-judging), and the repaired code converged on two
disciplines these rules now enforce:

THR001 — every attribute WRITTEN from the worker body and READ from a
public method must either be (a) lock-guarded on both sides by a lock
attribute of the instance, or (b) written only by atomic REBINDING
(``self.x = <fresh object>``) and read exactly once in the reading
method (bind to a local, then use the local) — the ``MetricsLogger.
_latest_rec`` single-tuple pattern. In-place mutation from the worker
(``self.d[k] = v``, ``self.l.append(...)``, ``del self.l[:n]``) never
qualifies for (b): a reader iterating or double-reading sees torn
state. When a class spawns MULTIPLE worker threads (Thread() under a
loop/comprehension), augmented assignment (``self.n += 1``) is also
demoted to a mutation — concurrent read-modify-write loses updates.

THR002 — lock-acquisition ORDER must be consistent package-wide. Every
lexically nested ``with self.lockA: ... with self.lockB:`` contributes
a directed edge (Class.lockA → Class.lockB); a cycle in the package-
wide graph is a potential deadlock (the runtime counterpart,
analysis/lockcheck.py, catches the dynamic cross-object cases static
analysis cannot see).

Known approximations (by design — suppress with a reason where the code
is right and the rule is blind): cross-OBJECT mutation
(``self._reservoir.offer(...)`` mutating reservoir internals) is
invisible; ``queue.Queue``/``Event`` method calls are treated as
thread-safe; happens-before established by ``Event.wait`` handshakes is
not modeled.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from dotaclient_tpu.analysis.core import (
    Finding,
    ModuleUnit,
    RepoContext,
    Rule,
    bfs_path,
    register,
)

# In-place mutators on plain containers. Deliberately EXCLUDES the
# thread-safe queue/event idioms (put/get/set/clear-on-Event...) — a
# queue.Queue attribute is the sanctioned cross-thread channel.
_MUTATORS = {
    "append",
    "appendleft",
    "extend",
    "insert",
    "remove",
    "discard",
    "add",
    "update",
    "setdefault",
    "popleft",
    "popitem",
}

_LOCK_FACTORIES = {"Lock", "RLock", "Condition"}

REBIND, MUTATE = "rebind", "mutate"


def _self_attr(node: ast.AST) -> Optional[str]:
    """'x' for a `self.x` expression, else None."""
    if (
        isinstance(node, ast.Attribute)
        and isinstance(node.value, ast.Name)
        and node.value.id == "self"
    ):
        return node.attr
    return None


def _is_lock_factory(call: ast.expr) -> bool:
    if not isinstance(call, ast.Call):
        return False
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
    return name in _LOCK_FACTORIES


def _thread_target(call: ast.Call) -> Optional[ast.expr]:
    """The target= expr of a threading.Thread(...) construction."""
    fn = call.func
    name = fn.attr if isinstance(fn, ast.Attribute) else getattr(fn, "id", "")
    if name != "Thread":
        return None
    for kw in call.keywords:
        if kw.arg == "target":
            return kw.value
    return None


def _class_model(module: ModuleUnit, node: ast.ClassDef) -> "_ClassModel":
    """One _ClassModel per class, shared by THR001 and THR002 (building
    one walks every method; doing it twice doubled THR lint time)."""
    cache = getattr(module, "_class_model_cache", None)
    if cache is None:
        cache = module._class_model_cache = {}
    model = cache.get(id(node))
    if model is None:
        model = cache[id(node)] = _ClassModel(module, node)
    return model


class _ClassModel:
    """Everything THR001 needs to know about one class."""

    def __init__(self, module: ModuleUnit, node: ast.ClassDef):
        self.module = module
        self.node = node
        self.methods: Dict[str, ast.FunctionDef] = {
            n.name: n
            for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        self.lock_attrs: Set[str] = set()
        self.worker_entries: List[ast.AST] = []  # method or nested def nodes
        self.multi_worker = False
        self._collect_locks_and_targets()

    def _collect_locks_and_targets(self) -> None:
        for meth in self.methods.values():
            for sub in ast.walk(meth):
                if (
                    isinstance(sub, (ast.Assign, ast.AnnAssign))
                    and sub.value is not None
                    and _is_lock_factory(sub.value)
                ):
                    # `self._lock: threading.Lock = threading.Lock()` is
                    # the same lock as the unannotated form
                    targets = sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                    for tgt in targets:
                        attr = _self_attr(tgt)
                        if attr is not None:
                            self.lock_attrs.add(attr)
                if isinstance(sub, ast.Call):
                    target = _thread_target(sub)
                    if target is None:
                        continue
                    # Thread() under a loop/comprehension → several
                    # workers share the written attributes.
                    for anc in self.module.ancestors(sub):
                        if isinstance(
                            anc, (ast.For, ast.While, ast.ListComp, ast.GeneratorExp)
                        ):
                            self.multi_worker = True
                        if anc is meth:
                            break
                    attr = _self_attr(target)
                    if attr is not None and attr in self.methods:
                        self.worker_entries.append(self.methods[attr])
                    elif isinstance(target, ast.Name):
                        # nested def used as target (watchdog's _run)
                        for sub2 in ast.walk(meth):
                            if (
                                isinstance(sub2, ast.FunctionDef)
                                and sub2.name == target.id
                            ):
                                self.worker_entries.append(sub2)

    def spawns_thread(self) -> bool:
        return bool(self.worker_entries)

    def _closure(self, entries: List[ast.AST]) -> Set[str]:
        """Method names reachable from `entries` via self.m() calls."""
        names: Set[str] = set()
        frontier = list(entries)
        while frontier:
            fn = frontier.pop()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Call):
                    attr = _self_attr(sub.func)
                    if attr in self.methods and attr not in names:
                        names.add(attr)
                        frontier.append(self.methods[attr])
        return names

    def worker_method_names(self) -> Set[str]:
        direct = {
            e.name for e in self.worker_entries if isinstance(e, ast.FunctionDef)
        }
        return direct | self._closure(self.worker_entries)

    def is_guarded(self, node: ast.AST, boundary: ast.AST) -> bool:
        """Is `node` under a `with self.<lock>:` inside `boundary`?"""
        for anc in self.module.ancestors(node):
            if isinstance(anc, ast.With):
                for item in anc.items:
                    attr = _self_attr(item.context_expr)
                    if attr in self.lock_attrs:
                        return True
            if anc is boundary:
                break
        return False

    def writes_in(self, fns: List[ast.AST]) -> Dict[str, List[Tuple[str, bool, int]]]:
        """{attr: [(kind, guarded, line)]} for worker-side writes."""
        out: Dict[str, List[Tuple[str, bool, int]]] = {}

        def record(attr: str, kind: str, node: ast.AST, fn: ast.AST) -> None:
            if self.multi_worker and kind == REBIND:
                # With several workers, any read-modify-write of the same
                # attribute loses updates: `+=`, and equally
                # `self.n = self.n + 1`.
                rhs = getattr(node, "value", None)
                reads_self = rhs is not None and any(
                    _self_attr(s) == attr
                    for s in ast.walk(rhs)
                    if isinstance(s, ast.Attribute)
                )
                if isinstance(node, ast.AugAssign) or reads_self:
                    kind = MUTATE
            out.setdefault(attr, []).append(
                (kind, self.is_guarded(node, fn), node.lineno)
            )

        for fn in fns:
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Assign):
                    for tgt in sub.targets:
                        targets = tgt.elts if isinstance(tgt, ast.Tuple) else [tgt]
                        for t in targets:
                            attr = _self_attr(t)
                            if attr is not None:
                                record(attr, REBIND, sub, fn)
                            elif isinstance(t, ast.Subscript):
                                attr = _self_attr(t.value)
                                if attr is not None:
                                    record(attr, MUTATE, sub, fn)
                elif isinstance(sub, ast.AugAssign):
                    attr = _self_attr(sub.target)
                    if attr is not None:
                        record(attr, REBIND, sub, fn)
                    elif isinstance(sub.target, ast.Subscript):
                        attr = _self_attr(sub.target.value)
                        if attr is not None:
                            record(attr, MUTATE, sub, fn)
                elif isinstance(sub, ast.Delete):
                    for t in sub.targets:
                        base = t.value if isinstance(t, ast.Subscript) else t
                        attr = _self_attr(base)
                        if attr is not None:
                            record(attr, MUTATE, sub, fn)
                elif isinstance(sub, ast.Call):
                    # self.attr.append(...) style container mutation
                    if isinstance(sub.func, ast.Attribute):
                        attr = _self_attr(sub.func.value)
                        if attr is not None and sub.func.attr in _MUTATORS:
                            record(attr, MUTATE, sub, fn)
        return out


@register
class UnguardedSharedAttr(Rule):
    id = "THR001"
    severity = "error"
    doc = (
        "attribute written by a worker thread and read from a public "
        "method without the instance lock or a single atomic read"
    )

    def run(self, module: ModuleUnit, ctx: RepoContext) -> List[Finding]:
        findings: List[Finding] = []
        for node in ast.walk(module.tree):
            if isinstance(node, ast.ClassDef):
                findings.extend(self._check_class(module, node))
        return findings

    def _check_class(self, module: ModuleUnit, node: ast.ClassDef) -> List[Finding]:
        model = _class_model(module, node)
        if not model.spawns_thread():
            return []
        worker_names = model.worker_method_names()
        worker_fns: List[ast.AST] = list(model.worker_entries) + [
            model.methods[n] for n in worker_names if n in model.methods
        ]
        writes = model.writes_in(worker_fns)
        if not writes:
            return []

        # Reader closure: public methods (and private helpers they call)
        # that are NOT part of the worker body. __init__ and dunders are
        # construction-time, not cross-thread readers.
        public = [
            name
            for name in model.methods
            if not name.startswith("_") and name not in worker_names
        ]
        reader_names = set(public) | model._closure(
            [model.methods[n] for n in public]
        )
        reader_names -= worker_names

        findings: List[Finding] = []
        for rname in sorted(reader_names):
            fn = model.methods.get(rname)
            if fn is None:
                continue
            reads: Dict[str, List[ast.Attribute]] = {}
            written_in_reader: Set[str] = set()
            for sub in ast.walk(fn):
                if isinstance(sub, ast.Attribute) and isinstance(
                    sub.ctx, ast.Load
                ):
                    attr = _self_attr(sub)
                    if attr in writes:
                        reads.setdefault(attr, []).append(sub)
                elif isinstance(sub, (ast.Assign, ast.AugAssign)):
                    tgts = (
                        sub.targets if isinstance(sub, ast.Assign) else [sub.target]
                    )
                    for t in tgts:
                        attr = _self_attr(t)
                        if attr is not None:
                            written_in_reader.add(attr)
            for attr, sites in sorted(reads.items()):
                wkinds = writes[attr]
                writes_guarded = all(g for _, g, _ in wkinds)
                all_rebind = all(k == REBIND for k, _, _ in wkinds)
                reads_guarded = all(model.is_guarded(s, fn) for s in sites)
                if writes_guarded and reads_guarded:
                    continue
                if all_rebind and len(sites) == 1 and attr not in written_in_reader:
                    # single atomic read of a rebound reference — the
                    # sanctioned lock-free pattern
                    continue
                # No line numbers or read counts in the message — it
                # feeds the baseline fingerprint, which must not churn
                # on unrelated edits (core.py fingerprint contract).
                what = (
                    "mutated in place" if not all_rebind else "rebound unguarded"
                )
                findings.append(
                    self.make(
                        module,
                        sites[0].lineno,
                        f"self.{attr} is {what} by worker thread(s) of "
                        f"{node.name} and read from {node.name}.{rname}() "
                        f"without the instance lock or the single-atomic-"
                        f"read discipline — guard both sides with the "
                        f"lock, or rebind atomically and read once into a "
                        f"local",
                        context=f"{node.name}.{rname}",
                    )
                )
        return findings


@register
class LockOrderConsistency(Rule):
    id = "THR002"
    severity = "error"
    doc = "inconsistent lock acquisition order across the package"

    def run_repo(self, ctx: RepoContext) -> List[Finding]:
        # edge: (Class.lockA → Class.lockB) from lexically nested withs
        edges: Dict[Tuple[str, str], Tuple[str, int, str]] = {}
        for module in ctx.modules:
            for cls in ast.walk(module.tree):
                if not isinstance(cls, ast.ClassDef):
                    continue
                model = _class_model(module, cls)
                if not model.lock_attrs:
                    continue
                for outer in ast.walk(cls):
                    if not isinstance(outer, ast.With):
                        continue
                    o_locks = self._locks_of(outer, model)
                    if not o_locks:
                        continue
                    # (held, acquired, site) pairs. `with self.a, self.b:`
                    # is sugar for nesting — items acquire left to right,
                    # so every ordered pair within one With is an edge too
                    pairs = []
                    for i, o_attr in enumerate(o_locks):
                        for i_attr in o_locks[i + 1 :]:
                            if i_attr != o_attr:
                                pairs.append((o_attr, i_attr, outer))
                    for inner in ast.walk(outer):
                        if inner is outer or not isinstance(inner, ast.With):
                            continue
                        for i_attr in self._locks_of(inner, model):
                            for o_attr in o_locks:
                                if i_attr != o_attr:
                                    pairs.append((o_attr, i_attr, inner))
                    for o_attr, i_attr, site in pairs:
                        # module-qualified: two unrelated classes that
                        # happen to share a name in different modules
                        # hold DISTINCT locks — merging them would mint
                        # a spurious inversion. Every lexical edge for a
                        # class comes from its defining module, so real
                        # inversions still pair up.
                        key = (
                            f"{module.relpath}:{cls.name}.{o_attr}",
                            f"{module.relpath}:{cls.name}.{i_attr}",
                        )
                        edges.setdefault(
                            key,
                            (
                                module.relpath,
                                site.lineno,
                                module.qualname_at(site),
                            ),
                        )
        adj: Dict[str, List[str]] = {}
        for a, b in edges:
            adj.setdefault(a, []).append(b)

        def back_path(src: str, dst: str) -> Optional[List[str]]:
            """Shortest [src, …, dst] over recorded edges (core.bfs_path,
            shared with lockcheck's runtime graph)."""
            return bfs_path(adj, src, dst)

        findings: List[Finding] = []
        reported: set = set()
        for (a, b), (path, line, qual) in sorted(edges.items()):
            # general cycles, not just reversed pairs: A→B, B→C, C→A
            # deadlocks under a 3-way interleave exactly like A→B/B→A
            back = back_path(b, a)
            if back is None:
                continue
            # report each cycle once, from its lexicographically first
            # edge (sorted iteration) — dedupe on the node set
            cycle_nodes = frozenset(back)
            if cycle_nodes in reported:
                continue
            reported.add(cycle_nodes)
            # qualname, not file:line, in the message: it feeds the
            # baseline fingerprint, which must survive line shifts
            rpath, _rline, rqual = edges[(b, back[1] if len(back) > 1 else a)]
            if len(back) == 2:
                detail = f"{b} → {a} in {rpath} ({rqual})"
            else:
                detail = f"the chain {' → '.join(back)} elsewhere (via {rqual})"
            findings.append(
                self.make(
                    path,
                    line,
                    f"lock order inversion: {a} → {b} here, but "
                    f"{detail} — pick one order package-wide or deadlock "
                    f"is one unlucky schedule away",
                    context=qual,
                )
            )
        return findings

    @staticmethod
    def _locks_of(node: ast.With, model: _ClassModel) -> List[str]:
        out = []
        for item in node.items:
            attr = _self_attr(item.context_expr)
            if attr in model.lock_attrs:
                out.append(attr)
        return out
