"""Flat dataclass configs, one per binary, overridable by CLI flags.

The reference configures each entrypoint with argparse flags and k8s env
vars and deliberately has no config framework (SURVEY.md §5 "Config / flag
system"); we mirror that: plain dataclasses + an argparse bridge.
"""

from __future__ import annotations

import argparse
import copy
import dataclasses
from dataclasses import dataclass, field


@dataclass
class PolicyConfig:
    """Architecture of the LSTM actor-critic (reference: policy.py)."""

    # Temporal core family: "lstm" (flagship, the reference architecture)
    # or "transformer" (long-context family: causal attention over the
    # chunk, chunk-local context, ring-shardable time axis —
    # models/transformer_policy.py).
    arch: str = "lstm"
    unit_embed_dim: int = 128
    lstm_hidden: int = 128  # temporal-core width (d_model for the transformer family)
    mlp_hidden: int = 128
    # Transformer-family shape (ignored for arch="lstm").
    tf_layers: int = 2
    tf_heads: int = 4
    # Actor KV-cache capacity. Invariant (enforced in make_actor_step):
    # >= rollout_len — the actor steps at most rollout_len frames per
    # chunk before next_chunk resets the cache (the bootstrap obs is
    # never stepped). Default leaves one slot of headroom over the
    # default rollout_len=16.
    tf_context: int = 17
    # Learner-side sequence parallelism: name of the mesh axis to shard
    # the time dimension over ("" = off). Engages ring attention
    # (ops/ring_attention.py) inside the unroll; requires the unrolled
    # frame count (seq_len+1) to divide by the axis size.
    tf_sp_axis: str = ""
    # Collective pattern for sequence-parallel attention: "ring"
    # (ppermute K/V streaming, any topology, no head constraint) or
    # "ulysses" (all-to-all head re-sharding; needs tf_heads divisible
    # by the sp axis). Same math either way — ops/ring_attention.py.
    tf_sp_mode: str = "ring"
    # Key-block size for the blockwise (flash-formulation) LOCAL
    # attention in the learner unroll: caps peak intermediates at
    # [N, T, block] instead of [N, T, T] for long single-device chunks.
    # 0 = dense. Engages only when the key axis exceeds the block.
    # Applies to local attention AND to the ulysses SP path (whose
    # per-head-group attention sees the full time axis); the ring is
    # blockwise by construction and ignores it.
    tf_attn_block: int = 0
    # Rematerialize transformer blocks in the learner unroll
    # (jax.checkpoint): activations are recomputed in the backward
    # instead of stored, trading ~1/3 more FLOPs for O(L) less
    # activation memory — the standard long-context lever. No effect on
    # actor stepping (no backward) or on the math (tested identical).
    tf_remat: bool = False
    n_move_bins: int = 9  # 9-way discretized move offsets per axis
    move_step: float = 350.0  # map units per outermost move-grid cell
    # Auxiliary value heads (benchmark config 5: win-prob, last-hit, net-worth).
    aux_heads: bool = False
    dtype: str = "bfloat16"  # compute dtype on TPU; params stay f32
    # LSTM recurrence implementation (ops/lstm.py): "auto" = fused Pallas
    # kernel on TPU when the block fits VMEM, lax.scan elsewhere.
    lstm_impl: str = "auto"


@dataclass
class PPOConfig:
    """PPO + GAE hyperparameters (reference: optimizer.py)."""

    gamma: float = 0.98
    gae_lambda: float = 0.95
    clip_eps: float = 0.2
    value_coef: float = 0.5
    value_clip: float = 0.2
    entropy_coef: float = 0.01
    lr: float = 1e-4
    adam_eps: float = 1e-5
    max_grad_norm: float = 0.5
    # Experience older than this many learner versions is dropped on the host
    # (reference drops/weights stale experience by model version).
    max_staleness: int = 4
    # Sample reuse (classic PPO): each consumed batch drives
    # epochs x minibatches gradient updates inside ONE compiled step —
    # advantages/returns computed once from the pre-update policy, then a
    # lax.scan over per-epoch shuffles and minibatch slices. At TPU speed
    # the learner is data-starved (device sits idle waiting for actors),
    # so reuse converts idle FLOPs into sample efficiency. 1/1 = the
    # single-update path (exactly the previous behavior).
    epochs: int = 1
    minibatches: int = 1
    # Approximate-KL early stop: when > 0, once a minibatch update's
    # approx_kl exceeds this, the REMAINING updates for the batch are
    # skipped (lax.cond no-ops — semantics of the classic mid-loop
    # `break`, with static shapes). 0 disables. Typical: 0.03.
    kl_stop: float = 0.0
    # ACER-style truncated-importance-weight cap (c-bar, arxiv 1611.01224)
    # applied to REPLAYED rows only: where a batch row's stamped
    # behavior-policy staleness is > 0, the IS ratio entering the clipped
    # surrogate is min(ratio, replay_rho_bar) — bounding the variance of
    # stale-ratio gradients (the A<0, ratio>>1 corner plain PPO clipping
    # leaves unbounded). Fresh rows (staleness 0) are untouched, so with
    # replay disabled the loss is bit-identical to plain PPO.
    replay_rho_bar: float = 2.0


@dataclass
class ReplayConfig:
    """Host-side prioritized replay reservoir between staging and the
    learner (dotaclient_tpu/replay/). Default OFF: with enabled=False the
    staging/learner data plane is bit-identical to the drop-on-stale
    pipeline (reference behavior)."""

    # Master switch. When on, rollouts that aged past ppo.max_staleness
    # (previously dropped on the host) are retained in the reservoir and
    # re-sampled into batches with ACER truncated importance weights.
    enabled: bool = False
    # Target fraction of each packed batch drawn from the reservoir
    # (0 <= ratio < 1); the rest stays fresh-from-the-broker. Batches
    # never block on the reservoir — a short reservoir just means more
    # fresh rows.
    ratio: float = 0.25
    # The reservoir's OWN staleness window, in learner versions: frames
    # older than this are expired/rejected outright (the pre-replay drop).
    # Must exceed ppo.max_staleness to retain anything.
    max_staleness: int = 32
    # Hard bound on resident reservoir bytes (serialized-frame sizes);
    # lowest-priority entries are evicted first. Default 256 MiB.
    byte_budget: int = 256 << 20
    # PER priority exponent on the |TD-error| key (0 = uniform).
    alpha: float = 0.6
    # Age decay half-life for sampling/eviction priority, in learner
    # versions: an entry this many versions old weighs half as much.
    age_half_life: float = 8.0
    # Per-entry sample cap before retirement (0 = unlimited): bounds how
    # often one surprising chunk can recur in the gradient.
    max_replays: int = 4
    # Compressed spill of cold entries: once occupancy crosses
    # spill_threshold * byte_budget, the coldest entries are zlib-
    # compressed in place (still sampleable), buying headroom before
    # eviction has to throw data away.
    spill_compress: bool = True
    spill_threshold: float = 0.5


@dataclass
class StagingConfig:
    """Parallel host feed (runtime/staging.py): multi-worker sharded
    pack into a ring of preallocated transfer buffers. Default
    pack_workers=1 keeps the single-consumer-thread staging path
    byte-for-byte (no pool threads, no ring — the inertness contract;
    tests/test_staging.py proves it in a subprocess)."""

    # Packer worker threads. 1 (default) = the classic path: one
    # consumer thread pops, parses, and packs inline. N>1 = the parallel
    # feed: a dedicated pop thread keeps draining the broker, an
    # assembler thread parses/filters (the batched C header parse
    # releases the GIL), and N pool workers each pack a disjoint
    # row-slice of the SAME transfer buffer concurrently (the C packer
    # releases the GIL — real parallelism). Output is BITWISE identical
    # to the single-thread pack for any worker count and any row split.
    # Sizing rule (README "Host feed pipeline"): ~1 worker per 4 host
    # cores feeding the learner, capped at 4 — pack is memcpy-bound, so
    # workers beyond the memory bandwidth knee only add contention.
    pack_workers: int = 1
    # Transfer-buffer ring depth (fused-H2D mode, pack_workers > 1
    # only): preallocated buffer sets with explicit ownership handoff
    # (free → packing → ready → in-transfer → free), so pack of batch
    # N+1 overlaps the H2D of N and the device step of N-1. The
    # learner's fetch returns a lease released once the device_put
    # retires. 2 = classic double buffering; raise it only if H2D
    # latency (not pack) is the longest stage.
    transfer_depth: int = 2
    # In-network batch assembly (--staging.assemble): consume DTB1
    # blocks of rows the fabric shards already packed into the native
    # row layout (shards run --broker.assemble); the learner-side pack
    # collapses to a per-row memcpy into a TransferRing slot. Requires
    # the fused-H2D path (the assembled rows ARE the transfer layout)
    # and pack_workers=1 (there is nothing left for a pool to do).
    # Default off keeps the classic consume path byte-for-byte.
    assemble: bool = False


@dataclass
class LearnerPipelineConfig:
    """Overlapped learner step loop (runtime/learner.py PrefetchLane):
    a dedicated prefetch thread runs the whole host side of batch N+1 —
    staging pop, pack-pool pack, device_put dispatch, lease retire —
    WHILE the device executes train step N, so the host wall disappears
    behind the device step (ROADMAP item 1; OPPO 2509.25762 pipeline
    overlap, PAPERS.md). Batch ORDER is unchanged (the lane is the same
    single staging consumer, FIFO), so the pipelined loop's params are
    BITWISE identical to the serial loop over the same frame schedule —
    OVERLAP_AB.json commits the proof. The PR-7 SIGTERM-drain contract
    survives: an in-flight prefetched batch is trained out (never
    dropped) and staging.drained() gains the prefetch-lane station."""

    # Master switch. True (default) = the pipelined loop. False restores
    # the serial fetch-after-step loop byte-for-byte (no lane thread, no
    # pipeline_* scalars — the rollback path, MIGRATION item 15).
    prefetch: bool = True
    # Batches the lane may hold fetched-ahead (the handoff queue bound).
    # 1 = classic double buffering: batch N+1 fully staged while step N
    # runs. Sizing rule (README "Pipelined learner"): every queued batch
    # ages one extra learner version before training, so keep
    # prefetch_depth well under ppo.max_staleness (default 4) — depth 1
    # is right unless a single fetch is slower than a device step.
    prefetch_depth: int = 1


@dataclass
class WireConfig:
    """Experience-wire quantization (transport/serialize.py DTR3).
    Producer-side only — consumers (staging, the native packer) accept
    DTR1/2/3 unconditionally, so the rolling-upgrade order is
    consumers-first: roll the learner, then flip actors to bf16."""

    # Wire dtype of the float obs leaves in published rollout frames:
    # "f32" (default) ships byte-identical legacy DTR1/DTR2 frames;
    # "bf16" casts obs f32→bf16 AT THE SOURCE (the exact RNE rounding
    # staging's compute-dtype cast applies anyway, so the TrainBatch is
    # bitwise unchanged) and ships DTR3 — roughly halving broker queue
    # memory, wire bandwidth, and staging intake bytes
    # (WIRE_QUANT_AB.json). Pinned f32 in prod manifests until the soak.
    obs_dtype: str = "f32"


@dataclass
class ServeConfig:
    """Centralized inference service — SERVER-side knobs (the
    `python -m dotaclient_tpu.serve.server` binary; dotaclient_tpu/serve/).
    The server owns one param tree, holds per-client LSTM carries
    resident, and runs continuous batching over a bounded gather window
    (the PR-5 InferenceBatcher semantics: fire at capacity or
    gather_window_s after the tick's first request, pad partial ticks to
    ONE jit signature, drop pad rows)."""

    # TCP port the inference service listens on (0 = pick a free port,
    # bench/test use; the k8s Service pins 13380).
    port: int = 13380
    # Batch capacity of one inference tick — the jit signature's row
    # count. Size to the expected concurrent in-flight requests (the
    # fan-in env count); partial ticks pad up to this, so oversizing
    # costs pad-row FLOPs, undersizing costs extra ticks.
    max_batch: int = 16
    # Bounded gather window, seconds: a tick fires at capacity or this
    # long after its FIRST request — one slow client stalls only itself.
    gather_window_s: float = 0.005
    # Cadence of the weight-fanout poll (the server subscribes to the
    # same broker weight fanout actors use; WeightPublisher's
    # on_published hook can poke the poll awake for same-tick swaps).
    weight_poll_s: float = 0.5
    # Session continuity (serve/handoff.py): "host:port" of the shared
    # carry store this replica streams (client_key, carry, version,
    # episode_step) deltas to at every chunk-boundary step — the
    # write-ahead happens BEFORE the chunk-fill reply, so a boundary a
    # client observed is always durably restorable. "" (default) = off:
    # no store connection, no extra bytes, replica death abandons
    # in-flight episodes exactly like PR-10. Requires fleet-unique
    # client keys (the actor_id scheme already guarantees this).
    # A COMMA list ("s0:13390,s1:13390") shards the store by rendezvous
    # hash of client_key (ShardedCarryStore): puts go to the key's
    # primary, failover reads walk the key's full preference order so
    # boundaries written before a shard ADD stay restorable. One
    # endpoint (no comma) is byte-for-byte the PR-13 single-store path.
    handoff_endpoint: str = ""
    # Per-RPC budget against the carry store. A store outage never
    # stops serving: the write is skipped (counted in
    # serve_handoff_store_errors_total) and the affected sessions
    # degrade to the PR-10 abandon semantics on the next failover.
    handoff_timeout_s: float = 2.0
    # Resident model slots. 1 (default) is byte-identical to the
    # single-model server: one live tree, no per-model anything. N > 1
    # adds N-1 FROZEN slots (league opponents) behind the same wire
    # port: slot 0 stays the live hot-swapped tree, slots 1..N-1 are
    # installed via swap_model() or synced from a league service
    # (--serve.league_endpoint). Each slot gets its own continuous
    # batcher (per-model tick bundles) sharing ONE compiled jit
    # signature — extra slots cost memory, not compiles.
    models: int = 1
    # League service "host:port" to sync frozen slots from (GET
    # /assignments → slot map, GET /snapshot → params). "" (default) =
    # no sync: slots hold their boot init until swap_model() is called
    # in-process. Ignored with --serve.models 1.
    league_endpoint: str = ""
    # Cadence of the league assignment poll, seconds.
    league_sync_s: float = 5.0


@dataclass
class ServeClientConfig:
    """Centralized inference service — ACTOR-side opt-in
    (dotaclient_tpu/serve/client.py). Default OFF: with endpoint empty
    the actor's inference hot path is byte-identical to the local jit
    path (the serve package is never imported — subprocess inertness
    proof in tests/test_serve.py)."""

    # Inference-service endpoint(s): "host:port" or a comma-separated
    # failover list "h1:p1,h2:p2,...". Each client STICKS to one replica
    # (server-side carry residency demands affinity) and fails over to
    # the next healthy one on connection loss or reply-deadline expiry
    # — in-flight episodes are abandoned (the UNKNOWN_CLIENT semantics),
    # never split across replicas. "" (default) = local inference,
    # exactly the pre-serve actor. Malformed lists fail loudly at boot.
    endpoint: str = ""
    # Per-request reply timeout, seconds: a server that dies without RST
    # must surface as a retryable RemoteInferenceError, not a hung env.
    timeout_s: float = 30.0
    # Per-dial TCP connect + handshake timeout, seconds. Deliberately
    # much shorter than timeout_s: a failover pass tries every healthy
    # endpoint in sequence, and each dead-but-blackholed replica costs
    # one of these.
    connect_timeout_s: float = 5.0
    # Seconds a failed endpoint sits out of the rotation before it is
    # probed again — a flapping replica is not hammered, and a fleet's
    # return-to-remote probes pace at this cadence.
    cooldown_s: float = 5.0
    # Graceful degradation: keep a broker-fanout-refreshed LOCAL param
    # tree warm, and when EVERY endpoint has been down for longer than
    # fallback_after_s, step episodes locally (versions stamped from the
    # local tree under the PR-5 chunk-boundary rule) until an endpoint
    # recovers — the fleet never stops generating experience, it just
    # pays local compute. Default off: remote-only actors keep params=()
    # and never pay a local init/compile.
    fallback_local: bool = False
    # All-endpoints-down budget before the local fallback engages,
    # seconds. Size it to ride out a single replica restart (failover
    # already covers those when a sibling replica is up): engaging is
    # cheap but flips the fleet off the accelerator tier.
    fallback_after_s: float = 10.0
    # Session continuity (the server side is --serve.handoff_endpoint):
    # with resume on, a remote-inference failure mid-episode no longer
    # abandons the episode — the client reconnects (failing over if
    # needed), presents its session (client_key + last chunk-boundary
    # step), the new replica restores the boundary carry from the
    # shared store, and the client REPLAYS its buffered partial-chunk
    # observations to rebuild the mid-chunk carry bitwise (at most one
    # chunk of recompute; replay outputs are discarded — the env
    # already acted on the originals). Default off: failure semantics
    # are byte-identical to PR-10 (abandon + ledger).
    resume: bool = False
    # Wall budget for one resume procedure (reconnect + restore +
    # replay, retried across failovers). Past it the episode abandons —
    # the PR-10 path. Keep it under fallback_after_s when both are
    # armed, or the fallback decision starves behind resume retries.
    resume_window_s: float = 20.0
    # Endpoint placement at (re)connect time: "order" (PR-10 list-order
    # rotation, the default) or "load" — probe every in-rotation
    # endpoint's S_INFO load report (connected clients + tick occupancy
    # from the actor_tick_rows_* histogram) and dial the least-loaded.
    # Affinity is untouched: the pick happens only when a connection is
    # (re)established, never mid-episode.
    route: str = "order"
    # Model id this client's sessions step against (multi-model serve).
    # 0 (default) = the live hot-swapped tree, and the S_INFO handshake
    # payload stays EMPTY — byte-identical to the single-model client
    # on every frame (the inertness rule; rollback = this flag). N > 0
    # binds the connection to frozen serve slot N (a league opponent);
    # a server without that slot resident refuses at handshake, loudly.
    model: int = 0
    # League service "host:port" (dotaclient_tpu/league/server.py).
    # League-opponent fleets (--opponent league + --serve.endpoint) ask
    # it GET /match at each episode for an opponent model id and POST
    # /result with the outcome — the matchmaking/rating loop. "" with
    # --serve.model 0 keeps the league fleet refusal (no served
    # opponents to play).
    league: str = ""


@dataclass
class RetryConfig:
    """Broker-client retry policy (transport/base.py RetryPolicy): one
    policy shared by the tcp transport's reconnect loop and the actor's
    SHED throttle, so a fleet tunes its backpressure behavior in ONE
    place. The jitter exists for the thundering-herd case: 256 actors
    whose broker restarts must not reconnect (or resume publishing after
    a shed) in lockstep."""

    # Seconds a failed broker request keeps reconnect-retrying before
    # giving up and raising (the old hardcoded _Conn retry_window).
    window_s: float = 60.0
    # First backoff sleep; doubles per attempt up to cap_s.
    backoff_base_s: float = 0.1
    backoff_cap_s: float = 2.0
    # Uniform jitter fraction: each sleep is drawn from
    # [b*(1-jitter), b*(1+jitter)]. 0 = the old deterministic lockstep.
    jitter: float = 0.5


@dataclass
class CkptConfig:
    """Preemption-tolerant checkpointing (runtime/checkpoint.py aux
    manifest + runtime/learner.py drain). Default OFF on every switch:
    with the defaults, checkpoint bytes on disk and the step loop are
    byte-identical to the params/opt/step-only behavior (asserted by the
    resume soak's inertness proof), so a rolling upgrade can land this
    build before any deployment opts in."""

    # Transactional full-state checkpoints: alongside the orbax step, an
    # aux manifest (written tmp + fsync + os.replace, so a crash mid-save
    # leaves the previous step fully restorable) captures the host RNG
    # streams, the replay-reservoir contents/priorities/staleness stamps,
    # staged-but-untrained pending frames, and the weight-publisher
    # version high-water mark — everything a learner kill would otherwise
    # lose. Restore re-injects all of it, and bumps the version counter
    # to the published high-water mark so in-flight rollout staleness
    # stamps stay monotonic (never under-aged for max_staleness/ACER).
    full_state: bool = False
    # Move the checkpoint off the step critical path: the loop thread
    # only dispatches an on-device state copy (async, donation-safe —
    # same stream-ordering argument as the weight publisher's
    # ParamFlattener); a dedicated worker thread pays the blocking host
    # read + reservoir snapshot + orbax/aux write, latest-wins coalesced.
    async_save: bool = False
    # Install a SIGTERM handler (learner main only): stop fetching,
    # finish the in-flight step, train out already-staged batches, save
    # full state with wait=True, exit 0 — the k8s preemption drain. The
    # matching manifests pair terminationGracePeriodSeconds/preStop with
    # drain_budget_s.
    drain_on_sigterm: bool = False
    # Hard wall-clock budget for the SIGTERM drain: a watchdog timer
    # force-exits (nonzero) if the drain has not completed by then, so a
    # wedged save can never outlive the pod's grace period into SIGKILL
    # with a half-written step.
    drain_budget_s: float = 45.0


@dataclass
class ChaosConfig:
    """Seeded fault injection (dotaclient_tpu/chaos/). Default OFF and
    import-free: with enabled=False no chaos module is ever imported and
    the broker/env objects are exactly the production ones —
    byte-identical wire behavior (asserted in tests/test_chaos.py)."""

    # Master switch: wrap this binary's broker in a ChaosBroker driving
    # the schedule below. NEVER set in production manifests (k8s pins it
    # false explicitly so a copy-pasted soak flag can't leak in).
    enabled: bool = False
    # Seed for every fault decision: same seed + spec -> the same faults
    # at the same operation indices (reproducible failure hunts).
    seed: int = 0
    # Fault schedule spec, e.g.
    # "latency:0.002~0.001,corrupt:0.01,dup:0.02,reset:0.005,
    #  stall@8:1.5,kill@10:2,kill@25:2" (chaos/schedule.py docstring is
    # the grammar). Empty = no faults even when enabled.
    spec: str = ""


@dataclass
class WatchdogConfig:
    """Learner liveness watchdog (dotaclient_tpu/obs/watchdog.py): a
    side thread that reads MetricsLogger.latest() + live gauges and
    escalates on stall / input starvation / NaN loss / steps/s
    regression: log -> flight-recorder dump -> flip /healthz to 503 (so
    a k8s liveness probe restarts the pod). Default OFF; requires
    obs.enabled."""

    enabled: bool = False
    # Seconds between checks (also the granularity of every window below).
    interval_s: float = 5.0
    # STALL: no learner-version advance for this many seconds. Must
    # comfortably exceed a worst-case batch wait + checkpoint write.
    stall_s: float = 120.0
    # Until the FIRST version advance the stall threshold is
    # max(stall_s, boot_grace_s): cold start legitimately spends minutes
    # in compile + checkpoint restore + waiting for the first published
    # rollouts, and a 120s stall_s would trip /healthz into a liveness
    # restart that replays the identical slow boot — an unbounded
    # crashloop. 600s covers multihost cluster formation with margin.
    boot_grace_s: float = 600.0
    # STARVATION: fraction of recent step wall time spent in the fetch
    # phase (compute_phase_fetch_frac) above this for consecutive checks.
    # 0 disables — the DEFAULT, deliberately: starvation is usually an
    # UPSTREAM failure (actors dead, fleet undersized) and restarting the
    # learner adds no actors; a single-actor smoke trips it instantly.
    # Opt in where a restart genuinely helps (wedged broker consumer) —
    # the k8s manifests set 0.95 against a sized actor fleet. Needs obs
    # step phases (the scalar it reads), so it is inert when
    # step_phases is off.
    starvation_frac: float = 0.0
    # NaN/inf guard on the latest logged `loss`. On by default when the
    # watchdog is on: a NaN loss never self-heals, restart is correct.
    nan_check: bool = True
    # REGRESSION: current env_steps_per_sec below this fraction of the
    # trailing-window median. 0 disables (CI smokes and phased drivers
    # have legitimately spiky rates).
    regression_frac: float = 0.0
    # Trailing window (number of metric samples) the regression baseline
    # is computed over.
    window: int = 12
    # Consecutive failing checks before each escalation stage: strike 1
    # logs, strike `dump_after` dumps the flight recorder, strike
    # `trip_after` flips /healthz to 503.
    dump_after: int = 2
    trip_after: int = 3


@dataclass
class ObsConfig:
    """Pipeline observability (dotaclient_tpu/obs/): rollout tracing,
    flight recorder, and the /metrics scrape endpoint. Default OFF with
    zero hot-path overhead: no trace stamping (wire frames stay
    byte-identical legacy DTR1), no hop recording, no ring writes, no
    HTTP server. Shared by the actor and learner binaries (--obs.*)."""

    # Master switch: stamp trace ids on published rollouts (actor),
    # record per-hop pipeline events + flight-recorder ring (both).
    enabled: bool = False
    # HTTP /metrics port, Prometheus text format (0 = no server). Serves
    # the latest MetricsLogger scalars plus live obs gauges (broker
    # queue depth, staging occupancy, replay reservoir stats). Stdlib
    # http.server only — no new dependencies.
    metrics_port: int = 0
    # Bounded in-memory ring of recent pipeline events per process,
    # dumped to JSON on crash, BatchLayoutError, SIGTERM, or explicit
    # FlightRecorder.dump().
    ring_size: int = 2048
    # Where flight-recorder dumps land ("" = current working directory).
    dump_dir: str = ""
    # Install process-wide SIGTERM + excepthook dump triggers. On by
    # default when obs is enabled; off for embedders (tests, drivers)
    # that own their signal handling.
    install_handlers: bool = True
    # Learner step-phase decomposition (obs/compute.py StepPhaseTimer):
    # fetch/pack/h2d/device_step/host wall time per iteration, logged as
    # compute_phase_* scalars. Under the pipelined loop
    # (--learner.prefetch, the default) the timer runs in OVERLAP mode:
    # fetch/pack/h2d are recorded on the prefetch lane (fenced there —
    # the lane's own time, hidden behind the device step), the loop lane
    # reports take-wait/residual/host, phases still tile the wall, and
    # the pipeline_* scalars carry the overlap accounting — no per-step
    # device fence, no overlap forfeited. Only the SERIAL loop
    # (--learner.prefetch false) still pays the per-step
    # block_until_ready fence for causal attribution.
    step_phases: bool = True
    # Where POST /profile?seconds=N captures land (jax.profiler.trace
    # TensorBoard dirs). "" = dump_dir (or cwd). Replaces the deprecated
    # learner profile_port always-on server.
    profile_dir: str = ""
    # Hard cap on a single on-demand profile capture; /profile clamps to
    # this (an unbounded capture would fill the pod disk).
    profile_max_seconds: float = 60.0
    # Liveness watchdog (obs/watchdog.py) — learner only.
    watchdog: WatchdogConfig = field(default_factory=WatchdogConfig)


@dataclass
class LearnerConfig:
    """Learner binary (reference: optimizer.py CLI)."""

    batch_size: int = 256  # sequences per train step (global, across dp shards)
    seq_len: int = 16  # rollout chunk length = LSTM truncation window
    ppo: PPOConfig = field(default_factory=PPOConfig)
    replay: ReplayConfig = field(default_factory=ReplayConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    policy: PolicyConfig = field(default_factory=PolicyConfig)
    retry: RetryConfig = field(default_factory=RetryConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    broker_url: str = "mem://"
    # Broker-fabric shard subset this learner consumes, as a comma-
    # separated index list into the --broker_url shard list ("0,1").
    # "" (default) = consume every shard. Only valid when --broker_url
    # is itself a comma-separated shard list (transport/fabric.py); the
    # multi-learner data-parallel fan-in assigns each learner a DISJOINT
    # subset so the steady-state stream is partitioned exactly once.
    # Known limitation (documented, bounded): a producer FAILOVER
    # republish follows the key's rendezvous order, which can cross
    # subset boundaries — each learner's fence is per-consumer, so the
    # stale original and the republish can each train once, in
    # DIFFERENT learners. This is the same rare at-least-once duplicate
    # class the classic tcp reconnect resend has always had ("harmless
    # to PPO", transport/tcp.py _Conn), at publish-failover frequency.
    # Publishing (weight fanout) always reaches every shard regardless.
    broker_shards: str = ""
    checkpoint_dir: str = ""
    # Remote checkpoint mirror (reference behavior: upload finished
    # checkpoints to object storage — SURVEY §3.4). Any epath scheme
    # (gs://bucket/path, s3://...); each finished step is file-copied up
    # and a fresh learner with an empty checkpoint_dir pulls the newest
    # complete remote step back down (runtime/checkpoint.py).
    checkpoint_remote_dir: str = ""
    checkpoint_every: int = 100  # steps between durable checkpoints
    # Preemption tolerance (--ckpt.*): transactional full-state
    # checkpoints, async save, SIGTERM drain. All default off.
    ckpt: CkptConfig = field(default_factory=CkptConfig)
    publish_every: int = 1  # steps between weight fanout publishes
    # Rolling-upgrade transition flag (ADVICE r4): emit legacy DTW1
    # weight frames (no boot_epoch) so not-yet-upgraded subscribers keep
    # parsing while the fleet rolls. Compat is one-directional — new
    # readers accept DTW1 — so the safe order is: (1) learner with this
    # flag ON, (2) upgrade all actors/evaluators, (3) flag OFF to get
    # boot-epoch resync back. Costs restart-resync determinism while ON.
    publish_legacy_dtw1: bool = False
    # Steps between host↔device metric syncs. Fetching the metrics dict
    # forces a device sync; doing it every step serializes the host onto
    # the step's critical path (the round-2 e2e-vs-device gap). Scalars
    # are logged once per window with window-averaged timings.
    metrics_every: int = 10
    log_dir: str = ""
    seed: int = 0
    mesh_shape: str = "dp=-1"  # e.g. "dp=4,tp=2"; -1 = all remaining devices
    # C++ batch packer on the staging path (falls back to python when the
    # build/load fails or DOTACLIENT_TPU_NO_NATIVE=1 is set)
    native_packer: bool = True
    # Parallel host feed (--staging.pack_workers / --staging.transfer_depth).
    staging: StagingConfig = field(default_factory=StagingConfig)
    # Overlapped step loop (--learner.prefetch / --learner.prefetch_depth):
    # the field is named `learner` so the flags spell --learner.* on the
    # learner binary — the pipeline knobs of the loop itself, as opposed
    # to the staging/transport layers above.
    learner: LearnerPipelineConfig = field(default_factory=LearnerPipelineConfig)
    # Stage obs floats in the policy compute dtype (bf16) on the host:
    # numerically identical (the policy's first op is the same cast) and
    # halves the dominant host→device transfer (runtime/staging.py
    # cast_obs_to_compute_dtype). Off = ship f32 and cast on device.
    stage_obs_compute_dtype: bool = True
    # Move each batch to the device as 4 dtype-grouped buffers instead of
    # 17 pytree leaves (parallel/fused_io.py): per-transfer overhead
    # dominated the on-silicon e2e bench. Auto-falls back to the per-leaf
    # tree path in sequence-parallel mode.
    fused_h2d: bool = True
    # With fused_h2d: collapse the 4 dtype-grouped buffers further into
    # ONE [B, row_bytes] u8 buffer per batch (free in-jit bitcasts
    # unpack it). Saves the remaining 3 per-transfer RPC overheads on
    # tunneled/remote chips; a wash on directly-attached hardware.
    # Default ON (the production pipelined path): the committed transfer
    # A/B on the tunneled chip put the same batch bytes at 1.961 ms as
    # 4 group buffers vs 0.105 ms as one buffer
    # (BENCH_TPU_20260730T0510.json transfer_layout_ab; OVERLAP_AB.json
    # re-records the layout A/B beside the pipelined-loop evidence).
    # Set false to fall back to the 4-buffer layout.
    fused_single_h2d: bool = True
    # jax.profiler server port (0 = off); connect with TensorBoard's
    # profile plugin or jax.profiler.trace to capture device traces
    profile_port: int = 0
    # "" = default backend (TPU in production). "cpu" pins the learner to
    # host devices — CPU smoke deployments, and hosts whose TPU plugin
    # would hang backend init.
    platform: str = ""
    # Multi-host learner (SURVEY.md §5 "Distributed communication
    # backend": jax.distributed over DCN if the learner ever spans
    # hosts). When true, jax.distributed.initialize() joins this process
    # to the cluster BEFORE backend init; jax.devices() then spans every
    # process's chips and the mesh/shardings work unchanged (XLA routes
    # intra-host collectives over ICI, cross-host over DCN). Each process
    # runs this same binary with its own process_id.
    multihost: bool = False
    # Each resolves independently: "" / -1 = let jax auto-detect from
    # cluster env or TPU metadata; set explicitly for manual clusters.
    coordinator: str = ""  # host:port of process 0
    num_processes: int = -1
    process_id: int = -1
    # Stop after this many train steps (0 = run forever). Smoke/CI use.
    train_steps: int = 0


@dataclass
class ActorConfig:
    """Actor binary (reference: agent.py CLI)."""

    env_addr: str = "localhost:13337"
    # "internal": this framework's env protos (fake env, tests);
    # "valve": a real dotaservice speaking CMsgBotWorldState — adapted at
    # the stub boundary (env/valve_adapter.py), actor loop unchanged.
    env_dialect: str = "internal"
    broker_url: str = "mem://"
    rollout_len: int = 16  # steps per published experience chunk
    host_timescale: float = 10.0
    ticks_per_observation: int = 30
    max_dota_time: float = 600.0
    hero: str = "npc_dota_hero_nevermore"
    # "scripted":      1v1 vs the env's passive scripted bot (runtime/actor.py)
    # "scripted_hard": 1v1 vs the hard scripted bot (farms + retreats) — the
    #                  north-star TrueSkill yardstick
    # "self":          mirror self-play, both sides live weights (runtime/selfplay.py)
    # "league":        PFSP league self-play vs frozen snapshots (eval/league.py)
    opponent: str = "scripted"
    # Heroes per team (1 = the 1v1 ladder rungs; 5 = BASELINE configs 4-5
    # team play). Self-play batches ALL controlled heroes into one jit
    # call per tick (B = 2*team_size mirror, B = team_size per side in
    # league mode) and publishes per-hero trajectories.
    team_size: int = 1
    league_capacity: int = 8  # max snapshots in the local league pool
    league_snapshot_every: int = 20  # learner versions between snapshots
    pfsp_mode: str = "hard"  # "hard" | "even" | "uniform"
    # Kill switch: exit (for supervisor restart) if no weight broadcast
    # arrives for this many seconds. 0 disables. Default ON (ADVICE r4):
    # with the switch disabled, a mixed-version deploy whose learner
    # emits frames this build can't parse (e.g. a future wire bump)
    # would silently freeze policy propagation cluster-wide — per-frame
    # warnings and an ever-staler policy. 900s is ~3 orders of magnitude
    # above the normal broadcast cadence and comfortably above learner
    # restart + checkpoint-restore time, so it only fires when
    # propagation is genuinely dead.
    max_weight_age_s: float = 900.0
    # Ablation: mask the CAST action out of every observation, so the
    # policy can never use abilities. Exists to measure whether ability
    # usage is ADVANTAGEOUS (scripts/ab_cast.py trains with and without);
    # never set in production.
    disable_cast: bool = False
    # Vectorized actor fleet (runtime/actor.py VectorActor): one process
    # drives this many env sessions on a single asyncio loop, gathering
    # their observations into ONE batched jit inference call per tick
    # (lax.map over rows — bit-identical to stepping each env alone) so
    # per-dispatch framework overhead amortizes across envs. 1 = the
    # classic one-env-per-process path, byte-for-byte unchanged.
    # ACTOR_FLEET.json holds the measured offered-rate curve that picks
    # the production default. Scripted opponents batch across envs;
    # self/league actors run envs_per_process concurrent sessions per
    # loop instead (each already batches its own heroes per jit call).
    envs_per_process: int = 1
    # Bounded gather window for the batched inference tick, seconds: the
    # batcher fires as soon as every env slot has submitted, and no later
    # than this after the FIRST submission of the tick — a slow gRPC
    # observe() can stall its own env, never the whole batch (partial
    # batches are padded to capacity and the pad rows' results dropped).
    gather_window_s: float = 0.005
    obs: ObsConfig = field(default_factory=ObsConfig)
    policy: PolicyConfig = field(default_factory=PolicyConfig)
    retry: RetryConfig = field(default_factory=RetryConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    # Experience-wire quantization (--wire.obs_dtype {f32,bf16}).
    wire: WireConfig = field(default_factory=WireConfig)
    # Centralized inference service opt-in (--serve.endpoint host:port):
    # ship featurized obs to a dedicated batching server instead of
    # running the policy locally. Default off = the local jit path,
    # byte-identical to the pre-serve build.
    serve: ServeClientConfig = field(default_factory=ServeClientConfig)
    seed: int = 0
    actor_id: int = 0
    # Actors are CPU processes (reference architecture: the accelerator
    # belongs to the learner). "cpu" also defeats environments that
    # force-register an accelerator backend for every python process.
    platform: str = "cpu"


@dataclass
class InferenceConfig:
    """Inference-service binary (dotaclient_tpu/serve/server.py): owns
    one param tree (init'd from --seed like an actor, hot-swapped from
    the broker weight fanout), serves batched policy steps to remote
    actors, and exports serve_* scalars on the obs scrape surface."""

    serve: ServeConfig = field(default_factory=ServeConfig)
    policy: PolicyConfig = field(default_factory=PolicyConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)
    retry: RetryConfig = field(default_factory=RetryConfig)
    chaos: ChaosConfig = field(default_factory=ChaosConfig)
    # Weight-fanout source (same URL the actors use). The service is a
    # weights SUBSCRIBER only — experience never flows through it.
    broker_url: str = "mem://"
    # Param-init seed: must match the learner fleet's seed so the
    # service can serve from step zero (the actor-boot convention).
    seed: int = 0
    # "cpu" pins the service to host devices; "" = default backend
    # (a GPU/TPU inference pod serves large-batch forward passes).
    platform: str = "cpu"


@dataclass
class HandoffConfig:
    """Carry-store binary (dotaclient_tpu/serve/handoff.py): the small
    replicated session-continuity store the inference replicas stream
    chunk-boundary carries to (--serve.handoff_endpoint) and read back
    on failover. Pure stdlib + numpy — it never builds a policy or
    touches jax, so it boots in milliseconds and can run as a tiny
    sidecar-class pod (k8s/inference.yaml `carry-store`)."""

    # TCP port the store listens on (0 = pick a free port, test use;
    # the k8s Service pins 13390).
    port: int = 13390
    # Entries retained per session key. 2 is load-bearing, not a cache
    # knob: the previous boundary must stay readable so a client whose
    # chunk-fill ACK was lost in a kill (store written, reply dead) can
    # still resume from the boundary it actually observed.
    keep: int = 2
    # The full store shard ring this pod belongs to, as the SAME comma
    # list the serve replicas get in --serve.handoff_endpoint ("" = a
    # single unsharded store). The store itself never routes — placement
    # is client-side rendezvous — but declaring the ring here makes the
    # pod's ready line name its topology, so a mis-rolled ring (pods
    # and replicas disagreeing about the shard list) is visible at boot
    # instead of surfacing as resume misses.
    stores: str = ""
    # /metrics + /healthz scrape surface (serve_handoff_store_* gauges).
    obs: ObsConfig = field(default_factory=ObsConfig)


@dataclass
class ControlLoopConfig:
    """The --control.* surface of the control-plane binary
    (dotaclient_tpu/control/server.py). All topology lists are comma
    `host:port` endpoint lists naming each tier's METRICS surfaces —
    the controller scrapes /metrics + /healthz there, decides against
    the policy, and actuates through the configured driver."""

    # Port of the controller's own HTTP surface: GET /topology (the
    # discovery endpoint actors and serve clients poll at (re)connect),
    # plus the standard /metrics + /healthz (control_* gauges). The k8s
    # Service pins 13400; 0 = pick a free port (test use).
    port: int = 13400
    # Scrape-decide-actuate cadence, seconds. Size against the policy
    # cooldowns (a poll period much longer than a cooldown makes the
    # cooldown a no-op; much shorter just re-reads unchanged gauges).
    poll_s: float = 2.0
    # Declarative scaling policy: ";"-separated clauses, each
    # "tier:meter,high=H,low=L,min=M,max=X,cooldown=C,step=S" — scale
    # `tier` up by `step` when `meter` > H (down when < L), clamped to
    # [M, X], at most one move per C seconds (control/policy.py). The
    # high/low gap is the hysteresis band (the --shed_high/--shed_low
    # watermark discipline applied to topology); "" = observe-only.
    policy: str = ""
    # Actuation driver: "static" observes and ledgers decisions without
    # actuating (the safe default — rollback is a driver flip, not a
    # rollout); "k8s" speaks `kubectl scale statefulset` against the
    # committed manifests. The in-process driver (soaks/tests) is
    # injected programmatically, never flag-selected.
    driver: str = "static"
    # Per-tier metrics endpoints the scraper polls (comma host:port
    # lists; "" = tier unmanaged). These are OBS ports, not data ports.
    brokers: str = ""
    servers: str = ""
    actors: str = ""
    stores: str = ""
    learner: str = ""
    # k8s driver scope: the namespace the StatefulSets live in, and the
    # kubectl binary to exec (tests point this at a recorder script).
    namespace: str = "dotaclient"
    kubectl: str = "kubectl"


@dataclass
class ControlConfig:
    """Control-plane binary (python -m dotaclient_tpu.control.server):
    the closed-loop autoscaler/router. Scrapes the fleet's existing
    Prometheus-text /metrics + /healthz surfaces, computes target
    replica counts per tier from the declarative policy, actuates via
    the pluggable driver, and serves /topology for discovery. Stdlib
    only — never imports jax or the wire stack."""

    control: ControlLoopConfig = field(default_factory=ControlLoopConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)


@dataclass
class FleetLoopConfig:
    """The --fleet.* surface of the fleet telemetry aggregator
    (python -m dotaclient_tpu.obs.fleetd): topology-driven scraping of
    every tier's /metrics surface, a continuous frame-conservation
    audit, fleet SLO rollups, and alert-triggered flight-recorder
    fan-in. Stdlib only — the controller's weight class."""

    # Port of fleetd's own HTTP surface: GET /fleet (the JSON rollup),
    # /metrics (fleet_* gauges the control plane can consume as policy
    # meters), /healthz (503 while any ledger is stale/alarming), and
    # /debug/flight. The k8s Service pins 13420; 0 = free port (tests).
    port: int = 13420
    # Scrape-audit-alert cadence, seconds. One poll = one audit window:
    # the injected-loss detection latency bound is exactly this.
    poll_s: float = 2.0
    # Per-target time-series ring length (poll windows retained for the
    # /fleet history view); bounds fleetd memory per target.
    window: int = 64
    # Seconds without a successful scrape before a target is reported
    # stale in /fleet (the audit freezes immediately either way).
    stale_s: float = 10.0
    # Control-plane address (host:port) whose GET /topology "metrics"
    # map is the discovery source; discovered endpoints MERGE with the
    # literal lists below. "" = literal lists only (the rollback
    # position, same semantics as --serve.endpoint).
    control: str = ""
    # Literal per-tier scrape lists (comma host:port of OBS surfaces;
    # "" = tier absent). These are the rollback position AND the way to
    # aggregate tiers the control plane does not manage.
    brokers: str = ""
    servers: str = ""
    actors: str = ""
    stores: str = ""
    learners: str = ""
    leagues: str = ""
    # Alert clauses: ";"-separated "meter,op,threshold,for=W" — meter
    # names fleetd's OWN rollup gauges (fleet_unaccounted_frames,
    # fleet_targets_up, ...), op in gt|ge|lt|le|eq|ne, W = consecutive
    # breached poll windows before firing. A firing edge snapshots
    # every target's GET /debug/flight ring into one incident bundle.
    # "" = audit-only (no alerting). Parse errors fail boot LOUDLY.
    alerts: str = ""
    # Directory incident bundles land in ("" = cwd).
    bundle_dir: str = ""


@dataclass
class FleetConfig:
    """Fleet telemetry binary (python -m dotaclient_tpu.obs.fleetd):
    the standing aggregator. Scrapes the fleet, audits the conservation
    ledgers live, serves fleet_* rollups. Stdlib only — never imports
    jax or the wire stack."""

    fleet: FleetLoopConfig = field(default_factory=FleetLoopConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)


@dataclass
class LeagueServiceConfig:
    """The --league.* surface of the standing league service
    (dotaclient_tpu/league/server.py): a disk-backed snapshot registry
    with checkpoint-lineage records, a matchmaking endpoint over the
    declarative policy grammar, and a TrueSkill rating service — the
    eval/league.py per-actor pool promoted to ONE queryable population
    shared by the whole fleet."""

    # Port of the service's HTTP surface: GET /match, /leaderboard,
    # /lineage, /assignments, /snapshot plus the standard /metrics +
    # /healthz (league_* gauges) and POST /result, /snapshot. The k8s
    # Service pins 13410; 0 = pick a free port (test use).
    port: int = 13410
    # Registry root: snapshots persist as <dir>/<name>.npz beside
    # lineage.json (the checkpoint-lineage ledger) and matches.jsonl
    # (the append-only match log the leaderboard is reproducible from).
    # "" = in-memory only (tests); a restart then loses the population.
    dir: str = ""
    # Opponent-pool capacity — also the number of frozen serve slots a
    # multi-model server needs (--serve.models = capacity + 1: slot 0
    # stays the live tree). Eviction past capacity is the eval/league.py
    # rule: weakest by mu, never the newest.
    capacity: int = 8
    # Serve model slots the service publishes assignments for (GET
    # /assignments maps slot 1..slots onto the most recent population
    # members; slot 0 is always the live tree and never assigned). Size
    # to the serve tier's --serve.models - 1.
    slots: int = 3
    # Admission cadence for fan-out-fed snapshots, learner versions
    # (the eval/league.py maybe_snapshot gating, version-regression
    # reset included).
    snapshot_every: int = 20
    # Matchmaking policy: ";"-separated weighted clauses
    # "kind[@weight]", kind ∈ uniform | prioritized | exploiter
    # (league/policy.py). Each GET /match draws a clause by weight:
    # uniform samples the pool flat, prioritized weights opponents by
    # observed loss rate (the PFSP-hard analog over ingested results),
    # exploiter assigns the caller the exploiter role vs the MAIN live
    # tree (model 0). E.g. "prioritized@0.7;exploiter@0.3".
    policy: str = "uniform"
    # The serve endpoint handed to /match callers ("host:port" of the
    # multi-model inference tier). The service never dials it — it is
    # matchmaking metadata, so fleets learn the serving address and the
    # opponent model id from ONE response.
    serve_endpoint: str = ""
    # Weight-fanout source feeding the registry (the WeightPublisher
    # broadcasts actors already receive). "" = no subscription: the
    # population grows only via POST /snapshot registrations.
    broker_url: str = ""
    # Fanout poll cadence, seconds.
    poll_s: float = 1.0
    # Exploiter promotion gate: an exploiter candidate whose ingested
    # results vs main reach gate_games matches AND gate_winrate wins
    # is promoted into the opponent pool (lineage event "promote").
    gate_games: int = 5
    gate_winrate: float = 0.55
    # Matchmaking draw seed (deterministic soaks/tests).
    seed: int = 0


@dataclass
class LeagueConfig:
    """League-service binary (python -m dotaclient_tpu.league.server).
    Like the control plane it is a standing HTTP service outside the
    data path — numpy for snapshot trees, stdlib for everything else;
    it never imports jax or the serve wire stack."""

    league: LeagueServiceConfig = field(default_factory=LeagueServiceConfig)
    obs: ObsConfig = field(default_factory=ObsConfig)


@dataclass
class EvalConfig:
    """Evaluator binary (eval/evaluator.py): plays frozen-policy episodes
    vs the scripted bot on each fresh weight broadcast."""

    actor: ActorConfig = field(default_factory=ActorConfig)
    episodes: int = 16  # episodes per evaluation round
    eval_every: int = 10  # learner versions between evaluations
    log_dir: str = ""


def _parse_bool(s: str) -> bool:
    low = s.lower()
    if low in ("1", "true", "yes", "on"):
        return True
    if low in ("0", "false", "no", "off"):
        return False
    raise argparse.ArgumentTypeError(f"expected a boolean, got {s!r}")


def add_flags(parser: argparse.ArgumentParser, cfg, prefix: str = "") -> None:
    """Register one --flag per (possibly nested) dataclass field."""
    for f in dataclasses.fields(cfg):
        val = getattr(cfg, f.name)
        name = f"{prefix}{f.name}"
        if dataclasses.is_dataclass(val):
            add_flags(parser, val, prefix=f"{name}.")
        elif isinstance(val, bool):
            parser.add_argument(f"--{name}", type=_parse_bool, default=val)
        else:
            parser.add_argument(f"--{name}", type=type(val), default=val)


def parse_config(cfg, argv=None):
    """Parse CLI flags into a fresh deep copy of `cfg` (returns the copy)."""
    cfg = copy.deepcopy(cfg)
    parser = argparse.ArgumentParser()
    add_flags(parser, cfg)
    args = parser.parse_args(argv)
    _apply(cfg, vars(args))
    return cfg


def _apply(cfg, flat: dict, prefix: str = "") -> None:
    for f in dataclasses.fields(cfg):
        val = getattr(cfg, f.name)
        name = f"{prefix}{f.name}"
        if dataclasses.is_dataclass(val):
            _apply(val, flat, prefix=f"{name}.")
        elif name in flat:
            setattr(cfg, f.name, flat[name])
