"""The control-plane binary: scrape → decide → actuate → serve topology.

    python -m dotaclient_tpu.control.server \\
        --control.driver k8s \\
        --control.policy "server:serve_load_occupancy.mean,high=0.8,low=0.2,min=2,max=8,cooldown=30" \\
        --control.port 13400 --obs.metrics_port 13400

One standing process (k8s/control.yaml): a poll loop scrapes every
managed tier's EXISTING /metrics + /healthz surfaces (control/scrape.py
— the same endpoints the probes and dashboards read), evaluates the
declarative policy (control/policy.py hysteresis + cooldowns), and
actuates through the configured driver (control/drivers.py). Every
evaluation — moves and holds alike — lands in a bounded decision ledger
WITH the meter values that justified it; the autoscale soak commits
that ledger as the audit trail.

The same HTTP surface serves discovery: GET /topology returns

    {"ok": true, "epoch": N, "tiers": {"server": ["h:p", ...], ...},
     "metrics": {"server": ["h:obs_p", ...], ...}}

— `tiers` is the DATA endpoint map actors and serve clients poll at
(re)connect, `metrics` the scrape-surface map the fleet telemetry
aggregator (obs/fleetd) discovers its targets from. Clients read when
their `--serve.endpoint` is `control:<host:port>` (serve/client.py;
the client speaks plain HTTP and never imports this package). `epoch`
bumps on every actuated scale, so a client can cheaply detect "shape
changed since I last looked". Rollback is the endpoint spec itself:
flip back to a literal `host:port,...` list and discovery is out of
the loop entirely.

Deploy order (MIGRATION): the controller rolls LAST — every tier it
manages must already serve /metrics before the loop's first poll; until
then `--control.driver static` observes and ledgers without touching
topology.
"""

from __future__ import annotations

import collections
import json
import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from dotaclient_tpu.config import ControlConfig, parse_config
from dotaclient_tpu.control.drivers import K8sDriver, StaticDriver, TierSpec
from dotaclient_tpu.control.policy import PolicyEngine, parse_policy
from dotaclient_tpu.control.scrape import aggregate_tier, scrape_endpoint, scrape_health
from dotaclient_tpu.obs.flight_recorder import FlightRecorder
from dotaclient_tpu.obs.http import MetricsHTTPServer

_log = logging.getLogger(__name__)

# The committed-manifest contracts the k8s driver actuates against
# (k8s/*.yaml: workload kind/name, headless service, data + obs ports,
# boot replicas). Scale targets clamp via policy min/max, so a spec's
# `replicas` is only the pre-first-actuation view.
_K8S_SPECS: Dict[str, TierSpec] = {
    "broker": TierSpec(
        tier="broker", workload="statefulset/broker", service="broker",
        data_port=13370, obs_port=9100, replicas=3,
    ),
    "server": TierSpec(
        tier="server", workload="statefulset/inference", service="inference",
        data_port=13380, obs_port=9100, replicas=2,
    ),
    "actor": TierSpec(
        tier="actor", workload="deployment/actors", service="actors",
        data_port=0, obs_port=9100, replicas=256,
    ),
    "store": TierSpec(
        tier="store", workload="deployment/carry-store", service="carry-store",
        data_port=13390, obs_port=9100, replicas=1,
    ),
    "learner": TierSpec(
        tier="learner", workload="statefulset/learner", service="learner",
        data_port=0, obs_port=9100, replicas=1,
    ),
}

_LEDGER_CAP = 4096  # bounded: a week of 2 s polls must not grow RSS


class ControlPlane:
    """The closed loop. `driver` is any control/drivers.py duck-type;
    `metrics_overrides` pins a tier's scrape list regardless of the
    driver's derived endpoints (flag lists in k8s mode, injected
    surfaces in soaks); `now_fn` feeds the policy cooldown clocks."""

    def __init__(
        self,
        cfg: ControlConfig,
        driver,
        metrics_overrides: Optional[Dict[str, List[str]]] = None,
        now_fn: Callable[[], float] = time.monotonic,
    ):
        self.cfg = cfg.control
        self.obs_cfg = cfg.obs
        self.driver = driver
        self.engine = PolicyEngine(parse_policy(self.cfg.policy), now_fn=now_fn)
        self._overrides = {t: list(e) for t, e in (metrics_overrides or {}).items()}
        self._scrape_timeout = max(0.5, min(2.0, float(self.cfg.poll_s)))
        # The controller's crash ring: every actuated scale lands here,
        # so a fleetd incident bundle shows WHAT the control plane did
        # around the alert (served via GET /debug/flight).
        self.recorder = FlightRecorder(
            "control", ring_size=self.obs_cfg.ring_size, dump_dir=self.obs_cfg.dump_dir
        )
        self._lock = threading.Lock()
        self.decisions: collections.deque = collections.deque(maxlen=_LEDGER_CAP)
        self.topology_epoch = 0
        self.polls_total = 0
        self.scrapes_total = 0
        self.scrape_errors_total = 0
        self.scale_ups_total = 0
        self.scale_downs_total = 0
        self.holds_total = 0
        self.actuation_failures_total = 0
        self.last_meters: Dict[str, Dict[str, float]] = {}
        self._http: Optional[MetricsHTTPServer] = None
        self._thread: Optional[threading.Thread] = None
        self._stop = threading.Event()

    # ------------------------------------------------------------- loop

    def _tier_endpoints(self, tier: str) -> List[str]:
        if tier in self._overrides:
            return list(self._overrides[tier])
        return self.driver.metrics_endpoints(tier)

    def poll_once(self) -> dict:
        """One scrape-decide-actuate round. Returns {"meters", "evals"}
        (the soak's per-round record); ledger + counters updated."""
        meters: Dict[str, Dict[str, float]] = {}
        current: Dict[str, int] = {}
        for tier in self.driver.tiers():
            eps = self._tier_endpoints(tier)
            samples = []
            healthy = 0
            for ep in eps:
                s = scrape_endpoint(ep, timeout_s=self._scrape_timeout)
                samples.append(s)
                self.scrapes_total += 1
                if s is None:
                    self.scrape_errors_total += 1
                    continue
                ok, _ = scrape_health(ep, timeout_s=self._scrape_timeout)
                healthy += 1 if ok else 0
            agg = aggregate_tier(samples)
            agg["healthy"] = float(healthy)
            agg["replicas"] = float(self.driver.replicas(tier))
            meters[tier] = agg
            current[tier] = self.driver.replicas(tier)
        evals = self.engine.evaluate(meters, current)
        now = time.time()
        # Actuate OUTSIDE the surface lock: a scale can take seconds
        # (kubectl round-trip; an in-process driver booting a real
        # replica), and /topology + /healthz must keep answering while
        # it runs — a discovery client mid-reconnect polls exactly then.
        entries = []
        ups = downs = holds = failures = bumps = 0
        for ev in evals:
            entry = dict(ev)
            entry["t"] = now
            entry["meters"] = dict(meters.get(ev["tier"], {}))
            if ev["action"] in ("up", "down"):
                actuation = self.driver.scale(ev["tier"], ev["target"])
                entry["actuation"] = actuation
                if actuation.get("actuated"):
                    bumps += 1
                    if ev["action"] == "up":
                        ups += 1
                    else:
                        downs += 1
                else:
                    failures += 1
                _log.info(
                    "scale %s %s %d -> %d (%s)",
                    ev["tier"], ev["action"], ev["current"], ev["target"],
                    ev["reason"],
                )
                self.recorder.record(
                    "scale",
                    tier=ev["tier"],
                    action=ev["action"],
                    target=ev["target"],
                    reason=ev["reason"],
                    actuated=bool(actuation.get("actuated")),
                )
            else:
                holds += 1
            entries.append(entry)
        with self._lock:
            self.last_meters = meters
            self.topology_epoch += bumps
            self.scale_ups_total += ups
            self.scale_downs_total += downs
            self.holds_total += holds
            self.actuation_failures_total += failures
            self.decisions.extend(entries)
            self.polls_total += 1
        return {"meters": meters, "evals": evals}

    def _run(self) -> None:
        while not self._stop.wait(float(self.cfg.poll_s)):
            try:
                self.poll_once()
            except Exception:
                # a broken poll must not kill the standing loop — the
                # next round re-scrapes from scratch
                _log.exception("control poll failed")

    # ---------------------------------------------------------- surfaces

    def topology(self) -> dict:
        with self._lock:
            return {
                "ok": True,
                "epoch": self.topology_epoch,
                "tiers": self.driver.topology(),
                # Scrape-surface map (obs ports, override lists first):
                # what obs/fleetd discovers its aggregation targets from.
                # Additive key — /topology consumers that only read
                # "tiers" (serve/client.py) are unaffected.
                "metrics": {
                    tier: self._tier_endpoints(tier)
                    for tier in self.driver.tiers()
                },
            }

    def health(self) -> dict:
        with self._lock:
            return {
                "ok": True,
                "polls": self.polls_total,
                "epoch": self.topology_epoch,
                "tiers": {t: self.driver.replicas(t) for t in self.driver.tiers()},
            }

    def stats(self) -> Dict[str, float]:
        with self._lock:
            out = {
                "control_polls_total": float(self.polls_total),
                "control_scrapes_total": float(self.scrapes_total),
                "control_scrape_errors_total": float(self.scrape_errors_total),
                "control_scale_ups_total": float(self.scale_ups_total),
                "control_scale_downs_total": float(self.scale_downs_total),
                "control_holds_total": float(self.holds_total),
                "control_actuation_failures_total": float(self.actuation_failures_total),
                "control_topology_epoch": float(self.topology_epoch),
                "control_managed_tiers": float(len(self.driver.tiers())),
                "control_decisions_ledgered": float(len(self.decisions)),
                "control_policy_clauses": float(len(self.engine.clauses)),
            }
            for tier in self.driver.tiers():
                out[f"control_replicas_{tier}"] = float(self.driver.replicas(tier))
        return out

    def ledger(self) -> List[dict]:
        with self._lock:
            return list(self.decisions)

    # --------------------------------------------------------- lifecycle

    @property
    def port(self) -> int:
        return self._http.port if self._http is not None else int(self.cfg.port)

    def start(self) -> "ControlPlane":
        self._http = MetricsHTTPServer(
            int(self.cfg.port),
            sources=[self.stats],
            health_provider=self.health,
            json_routes={"/topology": self.topology},
            flight_provider=self.recorder.snapshot,
        ).start()
        self._thread = threading.Thread(target=self._run, daemon=True, name="control-loop")
        self._thread.start()
        return self

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=10)
            self._thread = None
        if self._http is not None:
            self._http.stop()
            self._http = None


def build_driver(cfg: ControlConfig):
    """Driver + scrape overrides from flags. Managed tiers = those with
    a non-empty endpoint list (static) or named by a policy clause
    (k8s, endpoints derived from per-pod DNS unless a flag list pins
    them)."""
    flag_lists = {
        "broker": cfg.control.brokers,
        "server": cfg.control.servers,
        "actor": cfg.control.actors,
        "store": cfg.control.stores,
        "learner": cfg.control.learner,
    }
    lists: Dict[str, List[str]] = {}
    for tier, spec in flag_lists.items():
        if str(spec).strip():
            lists[tier] = [p.strip() for p in str(spec).split(",") if p.strip()]
    if cfg.control.driver == "static":
        return StaticDriver(lists), {}
    if cfg.control.driver == "k8s":
        tiers = {cl.tier for cl in parse_policy(cfg.control.policy)} | set(lists)
        specs = {}
        for tier in sorted(tiers):
            base = _K8S_SPECS[tier]
            specs[tier] = TierSpec(
                tier=base.tier, workload=base.workload, service=base.service,
                namespace=cfg.control.namespace, data_port=base.data_port,
                obs_port=base.obs_port, replicas=base.replicas,
            )
        return K8sDriver(specs, kubectl=cfg.control.kubectl), lists
    raise ValueError(
        f"--control.driver must be static|k8s, got {cfg.control.driver!r}"
    )


def main(argv=None):
    from dotaclient_tpu.obs import ObsRuntime

    logging.basicConfig(level=logging.INFO)
    cfg = parse_config(ControlConfig(), argv)
    driver, overrides = build_driver(cfg)
    plane = ControlPlane(cfg, driver, metrics_overrides=overrides).start()
    # The controller's own obs surface is its control port (stats,
    # health, /topology all live there); a separately-set
    # --obs.metrics_port adds the standard standalone surface too.
    obs = ObsRuntime.create(cfg.obs, role="control")
    if obs is not None and cfg.obs.metrics_port not in (0, int(cfg.control.port)):
        obs.serve_metrics([plane.stats])
    print(
        json.dumps(
            {
                "serving": True,
                "port": plane.port,
                "driver": cfg.control.driver,
                "tiers": driver.tiers(),
            }
        ),
        flush=True,
    )
    try:
        while True:
            time.sleep(3600)
    except KeyboardInterrupt:
        pass
    finally:
        plane.stop()
        if obs is not None:
            obs.close()


if __name__ == "__main__":
    main()
