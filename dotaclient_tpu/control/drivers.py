"""Actuation drivers: how a scale decision becomes replicas.

One interface, three shapes:

- ``StaticDriver`` — observe-only. Topology is the literal flag lists;
  scale() records the decision and actuates NOTHING. This is the safe
  default (--control.driver static) and the rollback position: flip a
  misbehaving k8s controller back to static and the fleet freezes at
  its current shape while the ledger keeps explaining what the policy
  WOULD do.
- ``K8sDriver`` — speaks the committed StatefulSet contracts
  (k8s/*.yaml): `kubectl scale statefulset/<name> --replicas=N`, with
  topology derived from the per-pod DNS identity the manifests pin
  (`<set>-<i>.<service>` — pod index IS shard/affinity identity, so
  scale-down removes the HIGHEST indices, which is exactly the
  rendezvous-friendly removal order). The kubectl invocation goes
  through an injectable runner callable, so tests assert the exact
  argv without a cluster. Rollout ORDER discipline (store-first on the
  way up, broker-first drains on the way down — MIGRATION) is the
  operator contract this driver inherits; it changes replica COUNTS
  only, one tier per decision, cooldowns spacing the moves.
- ``InProcessDriver`` — wraps live in-process routers (anything with
  ``replica_count()`` and ``scale_to(n)``, e.g. the chaos incarnation
  controllers behind an elastic router shim), so the whole closed loop
  — scrape, decide, actuate, re-scrape — soaks inside one process with
  REAL HTTP surfaces and real kills (scripts/soak_autoscale.py).

Driver interface (duck-typed, no ABC ceremony):
    replicas(tier) -> int             current replica count
    scale(tier, n) -> dict            actuation record (ledgered)
    metrics_endpoints(tier) -> [str]  obs surfaces to scrape
    topology() -> {tier: [str]}       DATA endpoints for /topology
    tiers() -> [str]                  tiers this driver manages
"""

from __future__ import annotations

import logging
import subprocess
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional

_log = logging.getLogger(__name__)


@dataclass
class TierSpec:
    """One tier's k8s identity (the committed-manifest contract)."""

    tier: str
    workload: str  # "statefulset/<name>" or "deployment/<name>"
    service: str = ""  # headless Service for per-pod DNS ("" = workload name)
    namespace: str = "dotaclient"
    data_port: int = 0  # the port clients dial (topology)
    obs_port: int = 9100  # the /metrics + /healthz port (scraping)
    replicas: int = 1  # boot-time count (refreshed by scale())


class StaticDriver:
    """Observe-only actuation: endpoints are the literal flag lists,
    scale() is a ledgered no-op. `metrics` maps tier → obs endpoints;
    `topology_map` (optional) maps tier → data endpoints for /topology —
    when omitted the metrics lists are served verbatim (observe-only
    discovery: the operator's literal lists, unchanged)."""

    def __init__(
        self,
        metrics: Dict[str, List[str]],
        topology_map: Optional[Dict[str, List[str]]] = None,
    ):
        self._metrics = {t: list(eps) for t, eps in metrics.items() if eps}
        self._topology = {
            t: list(eps) for t, eps in (topology_map or self._metrics).items() if eps
        }
        self.noop_scales = 0

    def tiers(self) -> List[str]:
        return sorted(self._metrics)

    def replicas(self, tier: str) -> int:
        return len(self._metrics.get(tier, []))

    def metrics_endpoints(self, tier: str) -> List[str]:
        return list(self._metrics.get(tier, []))

    def topology(self) -> Dict[str, List[str]]:
        return {t: list(eps) for t, eps in self._topology.items()}

    def scale(self, tier: str, n: int) -> dict:
        self.noop_scales += 1
        return {"driver": "static", "tier": tier, "replicas": int(n), "actuated": False}


class InProcessDriver:
    """Wraps live routers: {tier: router} where a router answers
    ``replica_count()`` and ``scale_to(n)`` (the soak's elastic shim
    over the chaos incarnation controllers). `metrics` / `topology_fn`
    are callables so endpoint lists track the router's LIVE shape —
    a scaled-up replica's obs surface appears on the next poll."""

    def __init__(
        self,
        routers: Dict[str, object],
        metrics: Optional[Dict[str, Callable[[], List[str]]]] = None,
        topology_fn: Optional[Callable[[], Dict[str, List[str]]]] = None,
    ):
        self._routers = dict(routers)
        self._metrics = dict(metrics or {})
        self._topology_fn = topology_fn
        self.scales = 0

    def tiers(self) -> List[str]:
        return sorted(self._routers)

    def replicas(self, tier: str) -> int:
        return int(self._routers[tier].replica_count())

    def metrics_endpoints(self, tier: str) -> List[str]:
        fn = self._metrics.get(tier)
        return list(fn()) if fn is not None else []

    def topology(self) -> Dict[str, List[str]]:
        return dict(self._topology_fn()) if self._topology_fn is not None else {}

    def scale(self, tier: str, n: int) -> dict:
        self._routers[tier].scale_to(int(n))
        self.scales += 1
        return {
            "driver": "in-process",
            "tier": tier,
            "replicas": int(n),
            "actuated": True,
        }


class K8sDriver:
    """kubectl-backed actuation against the committed manifests.

    `runner` takes an argv list and returns the process returncode
    (default: subprocess.run). Replica counts are tracked locally and
    committed only on a zero returncode — a failed kubectl leaves the
    driver's view (and the next poll's decisions) at the last known
    actuated shape instead of assuming success."""

    def __init__(
        self,
        specs: Dict[str, TierSpec],
        kubectl: str = "kubectl",
        runner: Optional[Callable[[List[str]], int]] = None,
    ):
        self._specs = dict(specs)
        self._kubectl = kubectl
        self._run = runner if runner is not None else self._default_runner
        self._replicas = {t: int(s.replicas) for t, s in self._specs.items()}
        self.kubectl_calls = 0
        self.kubectl_failures = 0

    @staticmethod
    def _default_runner(argv: List[str]) -> int:
        return subprocess.run(argv, capture_output=True).returncode

    def tiers(self) -> List[str]:
        return sorted(self._specs)

    def replicas(self, tier: str) -> int:
        return self._replicas[tier]

    def _pod_dns(self, spec: TierSpec, i: int) -> str:
        # StatefulSet per-pod DNS: <set>-<i>.<service>.<ns>.svc — pod
        # index IS the shard/affinity identity (the PR-10/PR-14 shape).
        name = spec.workload.partition("/")[2] or spec.workload
        service = spec.service or name
        return f"{name}-{i}.{service}.{spec.namespace}.svc"

    def metrics_endpoints(self, tier: str) -> List[str]:
        spec = self._specs[tier]
        return [
            f"{self._pod_dns(spec, i)}:{spec.obs_port}"
            for i in range(self._replicas[tier])
        ]

    def topology(self) -> Dict[str, List[str]]:
        out: Dict[str, List[str]] = {}
        for tier, spec in self._specs.items():
            if spec.data_port:
                out[tier] = [
                    f"{self._pod_dns(spec, i)}:{spec.data_port}"
                    for i in range(self._replicas[tier])
                ]
        return out

    def scale(self, tier: str, n: int) -> dict:
        spec = self._specs[tier]
        argv = [
            self._kubectl,
            "scale",
            spec.workload,
            f"--replicas={int(n)}",
            "-n",
            spec.namespace,
        ]
        self.kubectl_calls += 1
        rc = self._run(argv)
        if rc == 0:
            self._replicas[tier] = int(n)
        else:
            self.kubectl_failures += 1
            _log.warning("kubectl scale failed (rc=%d): %s", rc, " ".join(argv))
        return {
            "driver": "k8s",
            "tier": tier,
            "replicas": int(n),
            "argv": argv,
            "rc": rc,
            "actuated": rc == 0,
        }
