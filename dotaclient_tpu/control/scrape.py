"""Meter acquisition for the control loop: scrape the fleet's EXISTING
obs surfaces (obs/http.py /metrics Prometheus text + /healthz JSON) and
aggregate per tier.

Deliberately stdlib-only (urllib): the controller is a tiny standing
pod in the carry-store weight class — it must never drag jax, numpy, or
the wire stack in, and it scrapes the same endpoints the k8s probes and
a human's `curl` hit, so what the controller decides on is exactly what
an operator would have seen.

A failed scrape is DATA, not an error path: the sample is dropped, the
tier's `up` count falls, and the policy sees the reduced aggregate —
meters must degrade the way the fleet does, per-replica, never by
taking the whole poll down.
"""

from __future__ import annotations

import json
import logging
import urllib.error
import urllib.request
from typing import Dict, List, Optional, Tuple

_log = logging.getLogger(__name__)

# The obs/http.py exposition prefix, stripped on parse so policy specs
# name scalars the way the registry does ("serve_load_occupancy", not
# "dotaclient_serve_load_occupancy").
PREFIX = "dotaclient_"


def parse_prometheus_text(text: str, prefix: str = PREFIX) -> Dict[str, float]:
    """The inverse of obs/http.py render_prometheus: `name value` lines
    → {name: float}, comments/TYPE lines skipped, the exposition prefix
    stripped. Unparseable lines are dropped (a scraper must survive a
    surface it half-understands)."""
    out: Dict[str, float] = {}
    for line in text.splitlines():
        line = line.strip()
        if not line or line.startswith("#"):
            continue
        name, _, value = line.partition(" ")
        try:
            v = float(value)
        except ValueError:
            continue
        if name.startswith(prefix):
            name = name[len(prefix):]
        out[name] = v
    return out


def scrape_endpoint(endpoint: str, timeout_s: float = 2.0) -> Optional[Dict[str, float]]:
    """GET http://<endpoint>/metrics → scalar dict; None on ANY failure
    (dial, timeout, bad body) — the caller counts it against `up`."""
    try:
        with urllib.request.urlopen(
            f"http://{endpoint}/metrics", timeout=timeout_s
        ) as resp:
            return parse_prometheus_text(resp.read().decode("utf-8", "replace"))
    except Exception as e:
        _log.debug("scrape %s failed: %s", endpoint, e)
        return None


def scrape_health(endpoint: str, timeout_s: float = 2.0) -> Tuple[bool, Dict]:
    """GET http://<endpoint>/healthz → (ok, body). The obs/http.py
    contract: 200 = ok, 503 = a tripped watchdog (the 503 BODY still
    carries the verdict — surface it, the controller ledgers why)."""
    try:
        with urllib.request.urlopen(
            f"http://{endpoint}/healthz", timeout=timeout_s
        ) as resp:
            return True, json.loads(resp.read().decode("utf-8", "replace"))
    except urllib.error.HTTPError as e:
        try:
            body = json.loads(e.read().decode("utf-8", "replace"))
        except Exception:
            body = {}
        return False, body
    except Exception as e:
        _log.debug("healthz %s failed: %s", endpoint, e)
        return False, {}


def aggregate_tier(samples: List[Optional[Dict[str, float]]]) -> Dict[str, float]:
    """Per-tier meter namespace from per-replica scrapes: for every
    scalar any replica reported, `<name>.mean`, `<name>.max`, and
    `<name>.sum` over the replicas that reported it, plus `up` (scrapes
    that succeeded) and `scraped` (scrapes attempted). Policy meters
    name these directly — e.g. `serve_load_occupancy.mean` for tier
    load, `fabric_shard_depth.max` for the deepest broker shard."""
    alive = [s for s in samples if s is not None]
    out: Dict[str, float] = {
        "up": float(len(alive)),
        "scraped": float(len(samples)),
    }
    names = set()
    for s in alive:
        names.update(s)
    for name in names:
        vals = [s[name] for s in alive if name in s]
        out[f"{name}.mean"] = sum(vals) / len(vals)
        out[f"{name}.max"] = max(vals)
        out[f"{name}.sum"] = sum(vals)
    return out
