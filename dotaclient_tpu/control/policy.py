"""Declarative scaling policy: thresholds with hysteresis + cooldowns.

The --control.policy grammar is ";"-separated clauses:

    tier:meter,high=H,low=L[,min=M][,max=X][,cooldown=C][,step=S]

    server:serve_load_occupancy.mean,high=0.8,low=0.2,min=2,max=8,cooldown=30
    broker:fabric_shard_depth.max,high=6000,low=500,min=2,max=8
    actor:up.sum,high=1e18,low=1,min=4,max=256

One clause = one meter watched for one tier. The decision rule is the
--shed_high/--shed_low watermark discipline applied to topology:

- meter > high  → scale UP by `step`   (clamped to max)
- meter < low   → scale DOWN by `step` (clamped to min)
- low <= meter <= high → HOLD — the hysteresis band. Size it so the
  meter's expected post-scale move lands INSIDE the band: scaling up at
  occupancy 0.8 drops per-replica load by ~1/n, so `low` must sit below
  high*(1 - 1/min) or every scale-up earns an immediate scale-down and
  the controller oscillates (the classic thrash).
- at most one move per tier per `cooldown` seconds — scrapes are
  near-instant but the fleet's response (pod schedule, client
  re-discovery, queue drain) is not; the cooldown makes the controller
  wait for its own last action's effect before judging the meter again.

Every evaluation — moves AND holds — is returned as a record carrying
the meter value and thresholds that justified it; the control loop
ledgers them so `AUTOSCALE_SOAK.json` can prove each decision against
its triggering meters. Unknown/missing meters HOLD loudly (reason
"meter missing"), never default to a number: a scraper outage must
freeze topology, not shrink it.
"""

from __future__ import annotations

import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional

VALID_TIERS = ("broker", "server", "actor", "store", "learner")


@dataclass(frozen=True)
class PolicyClause:
    tier: str
    meter: str  # aggregated meter name, e.g. "serve_load_occupancy.mean"
    high: float
    low: float
    min: int = 1
    max: int = 8
    cooldown_s: float = 30.0
    step: int = 1


def parse_policy(spec: str) -> List[PolicyClause]:
    """Parse --control.policy; loud ValueError on malformation (the
    parse_endpoints discipline — a typo'd policy must fail the
    controller at boot, never silently observe-only)."""
    clauses: List[PolicyClause] = []
    if not str(spec).strip():
        return clauses
    for raw in str(spec).split(";"):
        raw = raw.strip()
        if not raw:
            raise ValueError(f"policy has an empty clause: {spec!r}")
        head, _, tail = raw.partition(",")
        tier, sep, meter = head.partition(":")
        tier = tier.strip()
        meter = meter.strip()
        if not sep or not meter:
            raise ValueError(f"policy clause needs tier:meter, got {raw!r}")
        if tier not in VALID_TIERS:
            raise ValueError(f"unknown policy tier {tier!r} in {raw!r}")
        kv: Dict[str, float] = {}
        for item in tail.split(",") if tail else []:
            k, s, v = item.strip().partition("=")
            if not s:
                raise ValueError(f"policy clause item needs k=v, got {item!r} in {raw!r}")
            try:
                kv[k.strip()] = float(v)
            except ValueError:
                raise ValueError(f"policy value is not a number: {item!r} in {raw!r}") from None
        unknown = set(kv) - {"high", "low", "min", "max", "cooldown", "step"}
        if unknown:
            raise ValueError(f"unknown policy keys {sorted(unknown)} in {raw!r}")
        if "high" not in kv or "low" not in kv:
            raise ValueError(f"policy clause needs high= and low=: {raw!r}")
        clause = PolicyClause(
            tier=tier,
            meter=meter,
            high=kv["high"],
            low=kv["low"],
            min=int(kv.get("min", 1)),
            max=int(kv.get("max", 8)),
            cooldown_s=float(kv.get("cooldown", 30.0)),
            step=int(kv.get("step", 1)),
        )
        if clause.low >= clause.high:
            raise ValueError(
                f"policy needs low < high (the hysteresis band), got {raw!r}"
            )
        if clause.min < 1 or clause.max < clause.min or clause.step < 1:
            raise ValueError(f"policy bounds need 1 <= min <= max, step >= 1: {raw!r}")
        clauses.append(clause)
    return clauses


class PolicyEngine:
    """Evaluates the clause list against one poll's aggregated meters.
    Holds the per-tier cooldown clocks; injectable `now_fn` so tests and
    the soak drive virtual time."""

    def __init__(self, clauses: List[PolicyClause], now_fn: Callable[[], float] = time.monotonic):
        self.clauses = list(clauses)
        self._now = now_fn
        self._last_move: Dict[str, float] = {}

    def evaluate(
        self,
        meters: Dict[str, Dict[str, float]],
        current: Dict[str, int],
    ) -> List[dict]:
        """One record per clause: tier, meter, value, high/low, current,
        target, action ("up"|"down"|"hold"), reason. At most one MOVE
        per tier per call (clause order wins; later clauses for a moved
        tier hold with reason "superseded")."""
        now = self._now()
        out: List[dict] = []
        moved: set = set()
        for cl in self.clauses:
            cur = int(current.get(cl.tier, 0))
            rec = {
                "tier": cl.tier,
                "meter": cl.meter,
                "value": None,
                "high": cl.high,
                "low": cl.low,
                "current": cur,
                "target": cur,
                "action": "hold",
                "reason": "",
            }
            value: Optional[float] = meters.get(cl.tier, {}).get(cl.meter)
            if value is None:
                rec["reason"] = "meter missing"
                out.append(rec)
                continue
            rec["value"] = value
            if cl.tier in moved:
                rec["reason"] = "superseded"
                out.append(rec)
                continue
            if value > cl.high:
                want, direction = min(cur + cl.step, cl.max), "up"
            elif value < cl.low:
                want, direction = max(cur - cl.step, cl.min), "down"
            else:
                rec["reason"] = "in hysteresis band"
                out.append(rec)
                continue
            if want == cur:
                rec["reason"] = f"at {'max' if direction == 'up' else 'min'} bound"
                out.append(rec)
                continue
            last = self._last_move.get(cl.tier)
            if last is not None and (now - last) < cl.cooldown_s:
                rec["reason"] = f"cooldown ({cl.cooldown_s - (now - last):.1f}s left)"
                out.append(rec)
                continue
            rec["target"] = want
            rec["action"] = direction
            rec["reason"] = (
                f"{cl.meter}={value:.6g} {'>' if direction == 'up' else '<'} "
                f"{cl.high if direction == 'up' else cl.low:.6g}"
            )
            self._last_move[cl.tier] = now
            moved.add(cl.tier)
            out.append(rec)
        return out
