"""Control plane: the closed-loop autoscaler + discovery service.

The fleet already exposes everything a controller needs — every binary
serves Prometheus-text /metrics + structured /healthz (obs/http.py),
the broker fabric ledgers per-shard depth/starvation, the serve tier
exports its S_INFO load dict as serve_load_* gauges — but acting on
those meters was a human: watch a dashboard, edit `replicas:`, re-roll
endpoint lists. This package closes the loop:

- control/scrape.py   stdlib scraper over the EXISTING /metrics +
                      /healthz surfaces, with per-tier aggregation
                      (`<scalar>.mean/.max/.sum` + up counts);
- control/policy.py   declarative threshold policy (--control.policy):
                      hysteresis bands + per-tier cooldowns — the
                      --shed_high/--shed_low watermark discipline
                      applied to topology;
- control/drivers.py  pluggable actuation: StaticDriver (observe-only,
                      the rollback position), K8sDriver (kubectl scale
                      against the committed StatefulSet contracts),
                      and duck-typed in-process routers so the whole
                      loop soaks without a cluster;
- control/server.py   the standing binary: scrape → decide → actuate
                      on a poll loop, every decision ledgered with the
                      meter values that justified it, plus GET
                      /topology — the discovery endpoint actors and
                      serve clients poll at (re)connect
                      (`--serve.endpoint control:<host:port>`).

Inertness: nothing imports this package unless a --control.* flag or a
`control:` endpoint scheme is used; the discovery client in
serve/client.py speaks plain HTTP and never imports it either.
"""

from dotaclient_tpu.control.drivers import (
    InProcessDriver,
    K8sDriver,
    StaticDriver,
    TierSpec,
)
from dotaclient_tpu.control.policy import PolicyClause, PolicyEngine, parse_policy
from dotaclient_tpu.control.scrape import (
    aggregate_tier,
    parse_prometheus_text,
    scrape_endpoint,
    scrape_health,
)
from dotaclient_tpu.control.server import ControlPlane

__all__ = [
    "ControlPlane",
    "InProcessDriver",
    "K8sDriver",
    "PolicyClause",
    "PolicyEngine",
    "StaticDriver",
    "TierSpec",
    "aggregate_tier",
    "parse_policy",
    "parse_prometheus_text",
    "scrape_endpoint",
    "scrape_health",
]
