"""Hero registry: the 1v1 hero pool with per-hero stat profiles.

BASELINE config 3 is "1v1 hero-pool self-play with a shared LSTM": one
policy plays many heroes, conditioned on who it is playing. The reference
passes hero names straight through to Dota (`GameConfig.hero_picks`,
SURVEY.md §2 env protos); the stats below drive the fake env's MDP and
the identity features the shared policy conditions on.

Two identity signals reach the policy:
- the stat profile itself (hp/damage/range/speed flow through the
  existing worldstate→feature path — a melee hero FEELS different);
- an 8-dim hashed embedding of the hero name (stable across processes,
  no vocabulary to sync — new heroes get a deterministic code for free).
"""

from __future__ import annotations

import functools
import hashlib
from typing import Dict, NamedTuple

import numpy as np

HERO_ID_DIM = 8


class HeroProfile(NamedTuple):
    hp: float
    damage: float
    attack_range: float
    speed: float
    regen: float


DEFAULT_HERO = "npc_dota_hero_nevermore"

# A laning-relevant spread: ranged glass cannons, long-range pokers, and
# tanky melee bruisers. Values are coarse 2018-era level-1 ballparks — the
# MDP needs contrast between heroes, not patch-accurate numbers.
HEROES: Dict[str, HeroProfile] = {
    # nevermore keeps the legacy single-hero MDP's exact stats (range 600)
    # so pre-pool TrueSkill/win-rate curves stay comparable
    "npc_dota_hero_nevermore": HeroProfile(hp=650, damage=53, attack_range=600, speed=310, regen=4.0),
    "npc_dota_hero_drow_ranger": HeroProfile(hp=600, damage=58, attack_range=625, speed=300, regen=3.0),
    "npc_dota_hero_sniper": HeroProfile(hp=570, damage=45, attack_range=550, speed=290, regen=3.0),
    "npc_dota_hero_lina": HeroProfile(hp=580, damage=52, attack_range=670, speed=295, regen=3.5),
    "npc_dota_hero_viper": HeroProfile(hp=620, damage=50, attack_range=575, speed=280, regen=4.5),
    "npc_dota_hero_axe": HeroProfile(hp=700, damage=55, attack_range=150, speed=310, regen=5.5),
    "npc_dota_hero_sven": HeroProfile(hp=660, damage=62, attack_range=150, speed=325, regen=4.5),
    "npc_dota_hero_bloodseeker": HeroProfile(hp=640, damage=60, attack_range=150, speed=300, regen=5.0),
}


def profile(name: str) -> HeroProfile:
    """Stat profile for `name`; unknown names get the default hero's
    (the real dotaservice would reject them — the fake env shrugs)."""
    return HEROES.get(name, HEROES[DEFAULT_HERO])


def parse_pool(spec: str) -> list[str]:
    """An ActorConfig.hero value is one name or a comma-separated pool."""
    names = [n.strip() for n in spec.split(",") if n.strip()]
    return names or [DEFAULT_HERO]


@functools.lru_cache(maxsize=None)
def hero_id_features(name: str, dim: int = HERO_ID_DIM) -> np.ndarray:
    """Deterministic ±1 code for a hero name (md5-seeded, process- and
    language-stable — NOT python hash(), which is salted per process).
    Cached and read-only: this runs once per observation in the actor hot
    loop, and callers only ever copy it into their feature rows."""
    if not name:
        code = np.zeros(dim, np.float32)
    else:
        digest = hashlib.md5(name.encode()).digest()
        bits = np.unpackbits(np.frombuffer(digest, np.uint8))[:dim]
        code = bits.astype(np.float32) * 2.0 - 1.0
    code.setflags(write=False)
    return code
