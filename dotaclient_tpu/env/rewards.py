"""Shaped component rewards from consecutive world-state deltas.

The reference computes per-step rewards in agent.py as a weighted sum of
component deltas between the previous and current worldstate — xp, hp,
last-hits, denies, kills/deaths, tower damage, and a terminal win bonus
(SURVEY.md §3.1 hot loop). Exact reference weights are [MED]-confidence
(mount was empty); the weights below follow the same component set and are
centralized so they can be corrected against a populated reference.

Host-side pure Python/numpy: rewards are computed once per env step on the
actor CPU, never on device.
"""

from __future__ import annotations

import math
from typing import Dict, Optional

from dotaclient_tpu.protos import worldstate_pb2 as ws
from dotaclient_tpu.env.featurizer import finite_or_zero, find_hero

REWARD_WEIGHTS: Dict[str, float] = {
    "xp": 0.002,  # per xp point
    "hp": 0.5,  # per health fraction
    "mana": 0.25,  # per mana fraction
    "last_hits": 0.16,
    "denies": 0.15,
    "kills": 0.5,
    "deaths": -0.5,
    "tower_hp": 1.0,  # per enemy-tower health fraction destroyed
    "win": 2.5,
}


def _tower_hp_frac(world: ws.World, enemy_team: int) -> float:
    total = 0.0
    for u in world.units:
        if u.team_id == enemy_team and u.unit_type in (ws.Unit.TOWER, ws.Unit.FORT, ws.Unit.BARRACKS):
            total += u.health / max(u.health_max, 1.0)
    return total


def component_rewards(
    prev: Optional[ws.World],
    world: ws.World,
    player_id: int,
    last_hero: Optional[ws.Unit] = None,
) -> Dict[str, float]:
    """Per-component reward deltas for `player_id` between two observations.

    `prev` may be None (first step): all deltas are zero except `win`.
    A dead hero contributes via the deaths counter, not a spurious negative
    hp delta. If the hero record despawns from `prev` entirely, pass
    `last_hero` — the last worldstate snapshot of the hero the caller saw —
    so counter deltas (deaths, kills, xp, last-hits) spanning the despawn
    gap are not lost; the actor loop maintains this snapshot.
    """
    out = {k: 0.0 for k in REWARD_WEIGHTS}
    hero = find_hero(world, player_id)
    prev_hero = find_hero(prev, player_id) if prev is not None else None
    if prev_hero is None:
        prev_hero = last_hero

    if world.winning_team:
        out["win"] = 1.0 if world.winning_team == world.team_id else -1.0

    if hero is None or prev_hero is None:
        return out

    out["xp"] = float(hero.xp - prev_hero.xp)
    if hero.is_alive and prev_hero.is_alive:
        hp_frac = hero.health / max(hero.health_max, 1.0)
        prev_hp_frac = prev_hero.health / max(prev_hero.health_max, 1.0)
        out["hp"] = hp_frac - prev_hp_frac
        mana_frac = hero.mana / max(hero.mana_max, 1.0)
        prev_mana_frac = prev_hero.mana / max(prev_hero.mana_max, 1.0)
        out["mana"] = mana_frac - prev_mana_frac
    out["last_hits"] = float(hero.last_hits - prev_hero.last_hits)
    out["denies"] = float(hero.denies - prev_hero.denies)
    out["kills"] = float(hero.kills - prev_hero.kills)
    out["deaths"] = float(hero.deaths - prev_hero.deaths)

    if prev is not None:
        enemy_team = 3 if hero.team_id == 2 else 2
        out["tower_hp"] = _tower_hp_frac(prev, enemy_team) - _tower_hp_frac(world, enemy_team)
    # health/mana/health_max are FLOAT wire fields — a corrupt frame can
    # carry nan/inf and every arithmetic path above propagates it into
    # the return, poisoning GAE downstream (tests/test_fuzz_wire.py).
    # One choke point: a non-finite component contributes zero.
    return {k: finite_or_zero(v) for k, v in out.items()}


def total_reward(components: Dict[str, float]) -> float:
    return math.fsum(REWARD_WEIGHTS[k] * v for k, v in components.items())


def reward(
    prev: Optional[ws.World],
    world: ws.World,
    player_id: int,
    last_hero: Optional[ws.Unit] = None,
) -> float:
    return total_reward(component_rewards(prev, world, player_id, last_hero))
