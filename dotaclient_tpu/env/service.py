"""Hand-written gRPC plumbing for the DotaService API.

The reference imports protoc-generated `DotaService_pb2_grpc` stubs from
the dotaservice pip package (SURVEY.md §1 L1). This image has no
`grpc_tools`, so the equivalent stubs are written against grpc's generic
handler API — same wire behavior (`/dotaclient_tpu.DotaService/<method>`
unary-unary calls carrying the protos from dotaservice.proto), no
generated code.
"""

from __future__ import annotations

from concurrent import futures
from typing import Optional

import grpc

from dotaclient_tpu.protos import dotaservice_pb2 as ds

SERVICE_NAME = "dotaclient_tpu.DotaService"

_METHODS = {
    # name: (request class, response class)
    "reset": (ds.GameConfig, ds.Observation),
    "observe": (ds.ObserveRequest, ds.Observation),
    "act": (ds.Actions, ds.Empty),
}


class DotaServiceServicer:
    """Subclass and override; mirrors the reference's servicer surface."""

    def reset(self, request: ds.GameConfig, context) -> ds.Observation:
        raise NotImplementedError

    def observe(self, request: ds.ObserveRequest, context) -> ds.Observation:
        raise NotImplementedError

    def act(self, request: ds.Actions, context) -> ds.Empty:
        raise NotImplementedError


def add_servicer_to_server(servicer: DotaServiceServicer, server: grpc.Server) -> None:
    handlers = {
        name: grpc.unary_unary_rpc_method_handler(
            getattr(servicer, name),
            request_deserializer=req.FromString,
            response_serializer=resp.SerializeToString,
        )
        for name, (req, resp) in _METHODS.items()
    }
    server.add_generic_rpc_handlers((grpc.method_handlers_generic_handler(SERVICE_NAME, handlers),))


def serve(servicer: DotaServiceServicer, port: int = 0, max_workers: int = 4):
    """Start an insecure server; returns (server, bound_port)."""
    server = grpc.server(futures.ThreadPoolExecutor(max_workers=max_workers))
    add_servicer_to_server(servicer, server)
    bound = server.add_insecure_port(f"127.0.0.1:{port}")
    server.start()
    return server, bound


class DotaServiceStub:
    """Client stub; works over a sync channel (tests, tools) or a
    grpc.aio channel (the asyncio actor loop) — unary_unary has the same
    construction signature on both."""

    def __init__(self, channel):
        self.channel = channel  # owners close it on teardown
        for name, (req, resp) in _METHODS.items():
            setattr(
                self,
                name,
                channel.unary_unary(
                    f"/{SERVICE_NAME}/{name}",
                    request_serializer=req.SerializeToString,
                    response_deserializer=resp.FromString,
                ),
            )


# Same class serves both channel kinds; alias kept for call-site clarity.
AsyncDotaServiceStub = DotaServiceStub


class _LocalContext:
    """Stands in for grpc's ServicerContext on the in-process path; the
    fake env keys sessions by peer()."""

    def __init__(self, name: str):
        self._name = name

    def peer(self) -> str:
        return self._name


class _ClosableNone:
    async def close(self) -> None:  # duck-types grpc.aio channel teardown
        pass


class LocalDotaServiceStub:
    """In-process stub: same async surface as DotaServiceStub, zero gRPC.

    For many-actor single-process runs (learning smokes, benchmarks) the
    gRPC loopback hop is pure overhead — and grpc.aio pollers across many
    threads on a small host actively thrash. Each stub gets its own peer
    name so the fake env gives it a private session, exactly like a
    distinct network client."""

    _n = 0

    def __init__(self, servicer: DotaServiceServicer, name: Optional[str] = None):
        LocalDotaServiceStub._n += 1
        self._servicer = servicer
        self._ctx = _LocalContext(name or f"local-{LocalDotaServiceStub._n}")
        self.channel = _ClosableNone()  # reset_env_stub closes channels

    async def reset(self, request):
        return self._servicer.reset(request, self._ctx)

    async def observe(self, request):
        return self._servicer.observe(request, self._ctx)

    async def act(self, request):
        return self._servicer.act(request, self._ctx)


_uid = 0


def _unique_options():
    """gRPC fuses channels to the same target onto one shared TCP
    connection; a distinct channel arg forces a private connection so the
    server sees a distinct peer per client (the fake env keys sessions by
    peer)."""
    global _uid
    _uid += 1
    return [("dotaclient.channel_uid", _uid)]


def connect(addr: str) -> DotaServiceStub:
    return DotaServiceStub(grpc.insecure_channel(addr, options=_unique_options()))


def connect_async(addr: str) -> DotaServiceStub:
    return DotaServiceStub(grpc.aio.insecure_channel(addr, options=_unique_options()))
